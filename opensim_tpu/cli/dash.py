"""``simon dash``: the fleet's live terminal view (ISSUE 20,
docs/observability.md "Watching the fleet").

The dashboard is a PURE function of one fetched payload bundle — fetch
and render are strictly separated so ``--once --json`` output is
byte-stable for a given payload (the dash-smoke gate renders the same
payload twice and compares bytes). Every number comes from the
time-series ring (``GET /api/debug/timeseries``) and the SLO engine
(``GET /api/fleet/slo``); nothing here re-derives state the server
doesn't already expose.

Rows rendered:

- fleet QPS + p50/p99 request latency over the queried range, from
  ``simon_requests_total`` / ``simon_request_seconds`` deltas between the
  oldest and newest in-range ring samples (per-worker ``worker=``-labeled
  copies are dropped first — the summed series already counts them);
- event-to-servable freshness per pipeline stage, from
  ``simon_fleet_freshness_seconds`` (mean + p99 per stage);
- admission lane depths (``simon_lane_depth``, newest sample);
- takeover markers: every ring sample where
  ``simon_fleet_takeovers_total`` stepped, with its reason;
- SLO burn rates per objective per window (``/api/fleet/slo``).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import MetricKey, counter_delta, histogram_quantile

__all__ = [
    "dash_payload",
    "dash_rows",
    "fetch_dash",
    "format_dash",
]


def _drop_worker(sample: Dict[MetricKey, float]) -> Dict[MetricKey, float]:
    """Remove per-worker labeled copies: the aggregated endpoint exposes
    both the summed series and ``{worker="i"}`` breakdowns; deltas and
    quantiles must count each request once."""
    return {
        (name, labels): v
        for (name, labels), v in sample.items()
        if "worker" not in dict(labels)
    }


def _parse_samples(raw: List[list]) -> List[Tuple[float, Dict[MetricKey, float]]]:
    """``/api/debug/timeseries`` samples (JSON: ``[ts, {key: value}]``)
    → parsed ``(ts, {MetricKey: value})``, worker copies dropped."""
    from ..obs.metrics import parse_metrics

    out = []
    for ts, series in raw:
        text = "\n".join(f"{k} {v!r}" for k, v in series.items())
        out.append((float(ts), _drop_worker(parse_metrics(text))))
    return out


def _takeover_markers(samples) -> List[dict]:
    """Ring samples where ``simon_fleet_takeovers_total`` stepped —
    rendered as timeline markers so a failover is visible next to the
    latency it caused."""
    markers: List[dict] = []
    prev: Dict[tuple, float] = {}
    for ts, sample in samples:
        for (name, labels), v in sample.items():
            if name != "simon_fleet_takeovers_total":
                continue
            if v > prev.get(labels, 0.0):
                markers.append({
                    "unix": round(ts, 3),
                    "reason": dict(labels).get("reason", ""),
                    "count": v,
                })
            prev[labels] = v
    return markers


def _freshness_rows(first, last) -> List[dict]:
    rows: List[dict] = []
    for stage in ("journaled", "published", "attached", "served"):
        match = {"stage": stage}
        count = counter_delta(
            first, last, "simon_fleet_freshness_seconds_count", match
        )
        if count <= 0:
            continue
        total_s = counter_delta(
            first, last, "simon_fleet_freshness_seconds_sum", match
        )
        p99 = histogram_quantile(
            first, last, "simon_fleet_freshness_seconds", 0.99, match
        )
        rows.append({
            "stage": stage,
            "events": count,
            "mean_s": round(total_s / count, 6),
            "p99_s": round(p99, 6) if p99 is not None else None,
        })
    return rows


def dash_rows(payload: dict) -> dict:
    """The dashboard's structured rows — a pure function of the fetched
    payload (no clocks, no I/O): rendering the same payload twice yields
    identical rows, which is what makes ``--once --json`` byte-stable."""
    ts_doc = payload.get("timeseries") or {}
    samples = _parse_samples(ts_doc.get("samples") or [])
    out: dict = {
        "ring": ts_doc.get("stats") or {},
        "samples": len(samples),
    }
    if len(samples) >= 2:
        (t0, first), (t1, last) = samples[0], samples[-1]
        span = max(1e-9, t1 - t0)
        requests = counter_delta(first, last, "simon_requests_total")
        out["window_s"] = round(span, 3)
        out["qps"] = round(requests / span, 3)
        out["latency"] = {
            q: (round(v, 6) if v is not None else None)
            for q, v in (
                ("p50", histogram_quantile(first, last, "simon_request_seconds", 0.5)),
                ("p99", histogram_quantile(first, last, "simon_request_seconds", 0.99)),
            )
        }
        out["freshness"] = _freshness_rows(first, last)
        out["takeovers"] = _takeover_markers(samples)
        out["lanes"] = {
            dict(labels).get("lane", ""): v
            for (name, labels), v in sorted(samples[-1][1].items())
            if name == "simon_lane_depth"
        }
    slo_doc = payload.get("slo")
    if isinstance(slo_doc, dict):
        out["slo"] = [
            {
                "name": row.get("name"),
                "target_pct": row.get("target_pct"),
                "windows": {
                    label: {
                        "burn_rate": win.get("burn_rate"),
                        "no_data": bool(win.get("no_data")),
                    }
                    for label, win in sorted((row.get("windows") or {}).items())
                },
            }
            for row in slo_doc.get("objectives") or []
        ]
    for key in ("timeseries_error", "slo_error"):
        if payload.get(key):
            out[key] = payload[key]
    return out


def format_dash(payload: dict) -> str:
    """Human rendering of :func:`dash_rows` (same data, fixed layout)."""
    rows = dash_rows(payload)
    lines: List[str] = []
    ring = rows.get("ring") or {}
    lines.append(
        f"fleet dash — ring {ring.get('windows', 0)}/{ring.get('window_capacity', '?')} "
        f"windows, {rows['samples']} samples"
        + (f", {rows['window_s']}s span" if "window_s" in rows else "")
    )
    if "qps" in rows:
        lat = rows.get("latency") or {}

        def ms(v: Optional[float]) -> str:
            return f"{v * 1000:.1f}ms" if v is not None else "-"

        lines.append(
            f"traffic   qps={rows['qps']:g}  "
            f"p50={ms(lat.get('p50'))}  p99={ms(lat.get('p99'))}"
        )
    for f in rows.get("freshness") or []:
        lines.append(
            f"freshness {f['stage']:<10} events={f['events']:g}  "
            f"mean={f['mean_s'] * 1000:.1f}ms  "
            + (f"p99={f['p99_s'] * 1000:.1f}ms" if f["p99_s"] is not None else "p99=-")
        )
    lanes = rows.get("lanes") or {}
    if lanes:
        lines.append(
            "lanes     " + "  ".join(f"{k}={v:g}" for k, v in sorted(lanes.items()))
        )
    for m in rows.get("takeovers") or []:
        lines.append(
            f"takeover  reason={m['reason']}  count={m['count']:g}  at={m['unix']}"
        )
    for row in rows.get("slo") or []:
        burns = "  ".join(
            f"{label}={'-' if win['no_data'] else format(win['burn_rate'], 'g')}"
            for label, win in row["windows"].items()
        )
        lines.append(f"slo       {row['name']:<12} target={row['target_pct']:g}%  {burns}")
    for key in ("timeseries_error", "slo_error"):
        if rows.get(key):
            lines.append(f"[{key.split('_')[0]} unavailable: {rows[key]}]")
    return "\n".join(lines)


def fetch_dash(url: str, range_spec: str = "", timeout_s: float = 10.0) -> dict:
    """One payload bundle from a live server/fleet-admin endpoint. Each
    surface degrades independently (a standby answers 503 on the ring but
    may still be worth watching), so errors land IN the payload instead
    of raising."""
    import urllib.error
    import urllib.parse
    import urllib.request

    base = url.rstrip("/")
    payload: dict = {}

    def get(path: str) -> dict:
        with urllib.request.urlopen(base + path, timeout=timeout_s) as resp:
            return json.load(resp)

    qs = "?" + urllib.parse.urlencode({"range": range_spec}) if range_spec else ""
    try:
        payload["timeseries"] = get("/api/debug/timeseries" + qs)
    except (urllib.error.URLError, OSError, ValueError) as e:
        payload["timeseries_error"] = str(e)
    try:
        payload["slo"] = get("/api/fleet/slo")
    except (urllib.error.URLError, OSError, ValueError) as e:
        payload["slo_error"] = str(e)
    return payload


def dash_payload(url: str, range_spec: str = "", timeout_s: float = 10.0) -> dict:
    """Fetch + rows in one call (what ``simon dash --once --json`` prints,
    via ``json.dumps(..., sort_keys=True)``)."""
    return dash_rows(fetch_dash(url, range_spec, timeout_s))
