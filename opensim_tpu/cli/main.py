"""simon CLI — parity with ``cmd/simon/simon.go``: ``simon {apply, server,
version, gen-doc}`` with the same flags (``cmd/apply/apply.go:27-36``,
``cmd/server/options.go:14``). Log level comes from the ``LogLevel`` env
(``cmd/simon/simon.go:46-66``). Beyond the reference: ``simon lint``
exposes the opensim-lint static analyzer (docs/static-analysis.md)
without make."""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import List, Optional

from .. import __version__ as VERSION  # single source of truth
COMMIT_ID = os.environ.get("SIMON_COMMIT_ID", "unknown")

LOG_LEVELS = {
    "panic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
    "error": logging.ERROR,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
    "trace": logging.DEBUG,
}


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="simon",
        description="Simon: a TPU-native cluster simulator for capacity planning",
    )
    sub = parser.add_subparsers(dest="command")

    backend_parent = argparse.ArgumentParser(add_help=False)
    backend_parent.add_argument(
        "--backend",
        default="auto",
        choices=["auto", "tpu", "cpu", "xla", "native"],
        help=(
            "auto = accelerator if reachable (Pallas fast path on TPU, C++ "
            "engine on CPU); tpu = require the accelerator; cpu = force host "
            "CPU; xla = disable the Pallas/C++ engines (pure XLA scan); "
            "native = force the C++ scan engine"
        ),
    )

    apply_p = sub.add_parser(
        "apply", parents=[backend_parent], help="run a capacity-planning simulation",
        description="run a capacity-planning simulation (the reference's `simon apply`)",
    )
    apply_p.add_argument("-f", "--simon-config", required=True, help="path of simon config (Config CR yaml)")
    apply_p.add_argument(
        "-d", "--default-scheduler-config", default="", help="path of kube-scheduler config overrides"
    )
    apply_p.add_argument("-o", "--output-file", default="", help="redirect the report to a file")
    apply_p.add_argument("--use-greed", action="store_true", help="use greed algorithm to sort pods")
    apply_p.add_argument(
        "--enable-preemption", action="store_true",
        help="let unschedulable high-priority pods evict lower-priority ones (beyond-reference)",
    )
    apply_p.add_argument("-i", "--interactive", action="store_true", help="interactive add-node mode")
    apply_p.add_argument(
        "-e",
        "--extended-resources",
        default="",
        help="comma-separated extended resource reports (gpu,open-local)",
    )
    apply_p.add_argument("--max-new-nodes", type=int, default=128, help="upper bound for the node sweep")
    apply_p.add_argument("--report-pods", action="store_true", help="include the per-node Pod Info table")
    apply_p.add_argument(
        "--trace", default="", metavar="FILE",
        help="write a Chrome-trace/Perfetto JSON of the run's span tree "
        "(prepare/encode/engine/decode phases; docs/observability.md)",
    )
    apply_p.add_argument(
        "--tie-break", default="lowest", metavar="lowest|sample[:seed]",
        help="equal-score node selection: deterministic lowest index "
        "(default) or the reference's sampled tie-break, seeded for "
        "reproducible distribution-comparison runs (C++ engine or XLA "
        "scan; the Pallas megakernel stays lowest-index)",
    )
    apply_p.add_argument(
        "--explain", action="store_true",
        help="decision audit (docs/observability.md): append the placement "
        "audit to the report — per-filter reject totals plus a kube-style "
        "'0/N nodes are available' breakdown for every unschedulable pod",
    )

    explain_p = sub.add_parser(
        "explain", parents=[backend_parent],
        help="explain why a pod landed where it did (or why it is unschedulable)",
        description=(
            "run the simulation with the decision audit enabled and print one "
            "pod's full placement explanation: the winning node with its "
            "per-plugin score breakdown and runner-up margin, or the kube-style "
            "'0/N nodes are available' per-filter rejection counts. Without a "
            "pod argument, prints the audit summary and every unschedulable "
            "pod's breakdown"
        ),
    )
    explain_p.add_argument("-f", "--simon-config", required=True, help="path of simon config (Config CR yaml)")
    explain_p.add_argument(
        "-d", "--default-scheduler-config", default="", help="path of kube-scheduler config overrides"
    )
    explain_p.add_argument(
        "pod", nargs="?", default="",
        help="pod to explain, as namespace/name (or bare name when unambiguous)",
    )
    explain_p.add_argument("--use-greed", action="store_true", help="use greed algorithm to sort pods")
    explain_p.add_argument("--json", action="store_true", help="emit the explanation(s) as JSON")

    defrag_p = sub.add_parser(
        "defrag",
        aliases=["drain"],
        parents=[backend_parent],
        help="evaluate node-drain what-ifs (the README's Pods Migration feature, batch-evaluated)",
        description="evaluate node-drain what-ifs (Pods Migration), batch-evaluated as scenarios",
    )
    defrag_p.add_argument("-f", "--simon-config", required=True, help="path of simon config (Config CR yaml)")
    defrag_p.add_argument(
        "--candidates", default="", help="comma-separated node names to evaluate (default: all)"
    )
    defrag_p.add_argument(
        "--json", action="store_true",
        help="emit the drain plan as JSON (the same table rows the text "
        "renderer prints — byte-parity via planner/report.py)",
    )
    defrag_p.add_argument("-o", "--output-file", default="", help="redirect the report to a file")

    campaign_p = sub.add_parser(
        "campaign",
        parents=[backend_parent],
        help="run a cluster-lifecycle campaign (drain waves, reclaim storms, scored what-ifs)",
        description=(
            "execute a declarative lifecycle campaign (docs/campaigns.md): an "
            "ordered list of typed steps — PDB-aware drain waves, spot reclaim "
            "storms, deploys/scales, add-nodes, scale-down safety checks, "
            "defrag plans, journal-sourced event ranges — evaluated against "
            "the spec's cluster (or a live server with --url) with every step "
            "scored by the capacity observatory: placements delta, disruption "
            "budget consumed, utilization/fragmentation/headroom movement, and "
            "a bit-stable step fingerprint"
        ),
    )
    campaign_p.add_argument("spec", help="campaign spec yaml (kind: Campaign)")
    campaign_p.add_argument("--json", action="store_true", help="print the full result JSON instead of tables")
    campaign_p.add_argument(
        "--exec", dest="exec_mode", default="", choices=["", "warm", "cold"],
        help="execution mode override (default OPENSIM_CAMPAIGN_EXEC): warm = "
        "one full prepare + prepcache deltas; cold = per-step full prepare "
        "(the verification mode)",
    )
    campaign_p.add_argument(
        "--url", default="",
        help="POST the campaign's steps to a live server's /api/campaign and "
        "evaluate against its observed cluster (live twin) instead of the "
        "spec's cluster section",
    )
    campaign_p.add_argument("--timeout", type=float, default=600.0, help="--url request timeout seconds")
    campaign_p.add_argument("-o", "--output-file", default="", help="also write the result to a file")

    server_p = sub.add_parser(
        "server", parents=[backend_parent], help="start the simon REST server",
        description="start the simon REST server (deploy-apps / scale-apps / healthz / metrics)",
    )
    server_p.add_argument("--kubeconfig", default="", help="kubeconfig of the real cluster")
    server_p.add_argument("--master", default="", help="apiserver address override")
    server_p.add_argument("--port", type=int, default=8080, help="listen port")
    server_p.add_argument(
        "--watch", default="auto", choices=["auto", "on", "off"],
        help="live-twin mode (docs/live-twin.md): consume the cluster's "
        "watch streams and keep an always-warm incremental snapshot. "
        "auto = watch with graceful fallback to per-TTL polling; on = "
        "require the twin to sync at startup; off = polling only",
    )
    server_p.add_argument(
        "--access-log", action="store_true",
        help="emit one JSON access-log line per request (request id, "
        "endpoint, status, duration) — same as OPENSIM_ACCESS_LOG=1",
    )
    server_p.add_argument(
        "--workers", type=int, default=0,
        help="serve through N worker PROCESSES sharing the port "
        "(docs/serving.md 'Scaling past one process'): a twin-owner "
        "process publishes arena deltas over shared memory and N workers "
        "attach zero-copy and run the full admission/batching ladder "
        "past the GIL. Requires the live twin (--kubeconfig, --watch "
        "auto|on). 0/1 = single process; OPENSIM_WORKERS_FLEET is the "
        "env default",
    )
    server_p.add_argument(
        "--journal", default="",
        help="directory for the crash-safe watch-event journal "
        "(docs/live-twin.md 'Durability & replay'): every accepted twin "
        "event is recorded off the dispatch path, and a restart restores "
        "the twin from the newest checkpoint + suffix replay instead of "
        "a cold relist. Requires the live twin (--kubeconfig, --watch "
        "auto|on)",
    )
    server_p.add_argument(
        "--standby", action="store_true",
        help="run as the HA hot standby (docs/serving.md 'Surviving owner "
        "loss & rolling upgrades'): tail the owner's --journal live onto "
        "a private twin and take over the fleet — fenced by the lease "
        "epoch, at a continuous generation, adopting the surviving "
        "workers — when the owner's lease expires or is handed over. "
        "Requires --journal and the live twin flags; the owner enables "
        "HA with OPENSIM_HA=1",
    )
    server_p.add_argument(
        "--handover", action="store_true",
        help="with --standby: once the journal tail reaches parity, ask "
        "the live owner to drain and hand the fleet over (zero-downtime "
        "rolling upgrade); without it the standby only takes over when "
        "the lease expires",
    )

    loadgen_p = sub.add_parser(
        "loadgen",
        help="drive a live simon server at load and report QPS + latency",
        description=(
            "open/closed-loop load harness for the serving core "
            "(docs/serving.md): drive the live server's /api/deploy-apps at a "
            "target concurrency (closed loop) or arrival rate (open loop) and "
            "report sustained QPS with p50/p99 latency read straight from the "
            "server's simon_request_seconds_bucket histogram, plus batching "
            "and shed statistics. Prints one JSON report"
        ),
    )
    loadgen_p.add_argument("--url", required=True, help="base URL of the live server (http://host:port)")
    loadgen_p.add_argument(
        "--mode", default="closed", choices=["closed", "open"],
        help="closed = each worker waits for its response (sustained-QPS "
        "measurement); open = fire at --qps regardless of completions",
    )
    loadgen_p.add_argument("--concurrency", type=int, default=8, help="closed-loop workers / open-loop in-flight cap")
    loadgen_p.add_argument("--qps", type=float, default=0.0, help="open loop: target arrival rate")
    loadgen_p.add_argument("--duration", type=float, default=10.0, help="measured seconds")
    loadgen_p.add_argument("--replicas", type=int, default=3, help="max replicas per generated deployment")
    loadgen_p.add_argument("--cpu", default="500m", help="per-pod cpu request of the generated workload")
    loadgen_p.add_argument("--mem", default="1Gi", help="per-pod memory request of the generated workload")
    loadgen_p.add_argument("--timeout", type=float, default=60.0, help="per-request client timeout seconds")
    loadgen_p.add_argument("-o", "--output-file", default="", help="also write the JSON report to a file")

    top_p = sub.add_parser(
        "top",
        help="live cluster capacity view (utilization, headroom, fragmentation)",
        description=(
            "render a live capacity view of the cluster a simon server "
            "observes (docs/observability.md 'Watching cluster capacity'): "
            "per-resource utilization/spread/fragmentation, headroom per "
            "registered workload profile, the hottest nodes and pending "
            "pressure — read from GET /api/cluster/report, the same "
            "computation path as the text report tables. One shot by "
            "default; --watch refreshes in place like kubectl top"
        ),
    )
    top_p.add_argument("--url", required=True, help="base URL of the live server (http://host:port)")
    top_p.add_argument("--json", action="store_true", help="print the raw report JSON instead of tables")
    top_p.add_argument(
        "--watch", action="store_true",
        help="refresh the view in place until interrupted (Ctrl-C exits)",
    )
    top_p.add_argument(
        "--interval", type=float, default=2.0,
        help="--watch refresh interval in seconds (default 2)",
    )
    top_p.add_argument(
        "--no-headroom", action="store_true",
        help="skip the headroom probes (cheaper polling; utilization/"
        "fragmentation only)",
    )
    top_p.add_argument(
        "--mem", action="store_true",
        help="add the memory observatory block (process RSS, prep-cache "
        "arena bytes, ring occupancy — docs/observability.md 'Memory & "
        "profiles')",
    )
    top_p.add_argument(
        "-e", "--extended-resources", default="",
        help="comma-separated extended resource sections (gpu,open-local)",
    )
    top_p.add_argument("--timeout", type=float, default=60.0, help="per-request client timeout seconds")

    dash_p = sub.add_parser(
        "dash",
        help="live fleet dashboard (QPS, latency, freshness, lanes, SLO burn)",
        description=(
            "render the fleet's live terminal view (docs/observability.md "
            "'Watching the fleet') from the time-series ring and the SLO "
            "engine of a running server or fleet admin endpoint: fleet QPS "
            "and p50/p99 from merged per-worker histograms, event-to-"
            "servable freshness per pipeline stage, admission lane depths, "
            "takeover markers and multi-window SLO burn rates. Refreshes "
            "in place until interrupted; --once prints one frame"
        ),
    )
    dash_p.add_argument("--url", required=True, help="base URL of the server or fleet admin endpoint (http://host:port)")
    dash_p.add_argument("--once", action="store_true", help="print one frame and exit instead of refreshing")
    dash_p.add_argument("--json", action="store_true", help="print the structured rows as JSON (stable key order)")
    dash_p.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh interval in seconds (default 2)",
    )
    dash_p.add_argument(
        "--range", default="5m", dest="range_spec", metavar="RANGE",
        help="ring query range: bare seconds or <n><s|m|h|d> (default 5m)",
    )
    dash_p.add_argument("--timeout", type=float, default=10.0, help="per-request client timeout seconds")

    mem_p = sub.add_parser(
        "mem",
        help="memory observatory: arena/cache footprint of a live server",
        description=(
            "read GET /api/debug/memory from a live simon server "
            "(docs/observability.md 'Memory & profiles'): process RSS and "
            "watermarks, per-device accelerator memory where available, the "
            "prep cache's host arena bytes attributed per entry (by encoder "
            "field and dtype, with lineage depth and drop-mask density), and "
            "bounded-ring occupancy (flight recorder, capacity timeline, "
            "journal writer queue). Totals count shared delta-entry leaves "
            "once and reconcile exactly with the per-entry unique-bytes sum"
        ),
    )
    mem_p.add_argument("--url", required=True, help="base URL of the live server (http://host:port)")
    mem_p.add_argument("--json", action="store_true", help="print the raw debug JSON instead of tables")
    mem_p.add_argument(
        "--fields", action="store_true",
        help="include the per-entry per-field arena breakdown (verbose)",
    )
    mem_p.add_argument("--timeout", type=float, default=60.0, help="per-request client timeout seconds")

    profile_p = sub.add_parser(
        "profile",
        help="cumulative phase profiles + compile telemetry of a live server",
        description=(
            "read GET /api/debug/profile from a live simon server "
            "(docs/observability.md 'Memory & profiles'): per-span cumulative "
            "latency profiles folded from every recorded request trace "
            "(count, inclusive/exclusive seconds, p50/p99) so 'where do "
            "requests spend their time' is one query instead of N traces, "
            "plus JIT compile telemetry — compiles and seconds per "
            "instrumented boundary with recompile-cause attribution (shape "
            "vs dtype vs static-flag change) and the persistent compile "
            "cache's footprint"
        ),
    )
    profile_p.add_argument("--url", required=True, help="base URL of the live server (http://host:port)")
    profile_p.add_argument("--json", action="store_true", help="print the raw debug JSON instead of tables")
    profile_p.add_argument("--timeout", type=float, default=60.0, help="per-request client timeout seconds")

    replay_p = sub.add_parser(
        "replay",
        help="reconstruct and replay a recorded watch-event journal",
        description=(
            "replay a journal recorded by `simon server --journal` "
            "(docs/live-twin.md 'Durability & replay'): reconstruct the "
            "live twin at any recorded generation and stream the accepted "
            "event history — at N× recorded speed or as fast as possible — "
            "through the same apply path the live dispatch uses, feeding "
            "the capacity observatory as it goes. Prints one JSON summary "
            "line: record counts, final generation, the reconstructed "
            "twin's content fingerprint, event throughput, and the final "
            "capacity sample. --schedule additionally drives the scheduler "
            "against the reconstructed cluster, turning a recorded "
            "production trace into a repeatable scenario"
        ),
    )
    replay_p.add_argument("journal", help="journal directory recorded by `simon server --journal`")
    replay_p.add_argument(
        "--speed", type=float, default=0.0,
        help="pace the stream at N× the recorded inter-event gaps "
        "(0 = as fast as possible, the default; gaps clamp at 30s)",
    )
    replay_p.add_argument(
        "--at-generation", type=int, default=None, metavar="G",
        help="stop once the twin reaches generation G (time-machine view "
        "of any recorded moment; default: the full history)",
    )
    replay_p.add_argument(
        "--capacity", action=argparse.BooleanOptionalAction, default=True,
        help="feed the capacity observatory during replay and include the "
        "final utilization/fragmentation sample in the summary",
    )
    replay_p.add_argument(
        "--schedule", type=int, default=0, metavar="PODS",
        help="after replay, schedule PODS synthetic pods onto the "
        "reconstructed cluster and report placements (proves the replayed "
        "twin is schedulable state, not just a data dump)",
    )
    replay_p.add_argument(
        "--events", action="store_true",
        help="also print one JSON line per replayed record (type, "
        "generation, resource) before the summary — the raw stream view",
    )
    replay_p.add_argument("-o", "--output-file", default="", help="also write the JSON summary to a file")

    lint_p = sub.add_parser(
        "lint",
        help="run the opensim-lint static analyzer (27 OSL rules)",
        description=(
            "repo-specific static analyzer (docs/static-analysis.md): AST "
            "rules, whole-program lock-discipline checks, and the "
            "interprocedural dataflow pack (jit-impurity, tracer-leak, "
            "input-taint, C++/Python abi-parity). Exit 1 on findings."
        ),
    )
    lint_p.add_argument(
        "lint_paths", nargs="*", metavar="PATH",
        help="files/directories to lint (default: [tool.opensim-lint] "
        "paths in ./pyproject.toml, else opensim_tpu)",
    )
    lint_p.add_argument("--rules", default="", help="comma-separated rule names/codes (default: all)")
    lint_p.add_argument(
        "--format", default="", choices=["", "human", "json", "sarif"],
        help="output format (sarif = SARIF 2.1.0 for CI/editor annotation)",
    )
    lint_p.add_argument("--list-rules", action="store_true", help="list registered rules and exit")
    lint_p.add_argument(
        "--cache", default="", metavar="PATH",
        help="content-hash result cache (unchanged files skip their rules)",
    )
    lint_p.add_argument("--no-cache", action="store_true", help="disable the result cache")
    lint_p.add_argument(
        "--sarif-out", default="", metavar="PATH",
        help="also write SARIF to this path (stable CI artifact)",
    )
    lint_p.add_argument(
        "--corpus", default="", metavar="DIR",
        help="run the detector-awake fixture gate over DIR after linting",
    )
    lint_p.add_argument(
        "--changed", action="store_true",
        help="lint only files with uncommitted git changes (the fast "
        "pre-commit loop)",
    )
    lint_p.add_argument(
        "--jobs", type=int, default=None, metavar="N",
        help="process-pool width for the per-file rule tier (default: "
        "auto; 1 = serial)",
    )

    sub.add_parser("version", help="print version", description="print version and commit id")

    doc_p = sub.add_parser(
        "gen-doc", help="generate markdown docs for the CLI",
        description="generate one markdown doc per subcommand plus an index",
    )
    doc_p.add_argument("--output-dir", default="docs/commandline", help="where to write the docs")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    # persistent XLA compilation cache: repeated simon invocations with the
    # same shapes skip the (tens of seconds) first-compile cost; opt out /
    # relocate with OPENSIM_JIT_CACHE (utils/jitcache.py)
    from ..utils.jitcache import maybe_enable as _enable_jit_cache

    _enable_jit_cache(default=True)
    level = LOG_LEVELS.get(os.environ.get("LogLevel", "info").lower(), logging.INFO)
    logging.basicConfig(level=level, format="%(levelname)s %(message)s")

    parser = build_parser()
    args = parser.parse_args(argv)

    backend = getattr(args, "backend", "auto")
    if backend != "auto":
        _select_backend(backend)
    elif args.command in ("apply", "defrag", "drain", "server", "explain") or (
        args.command == "campaign" and not args.url
    ):  # --url campaigns are pure HTTP: no local engine, skip the probe
        # auto mode must not hang when the accelerator tunnel is dead: any
        # jax device op can block forever (utils/probe.py), so probe in a
        # subprocess first and fall back to the host CPU with a note
        from ..utils.probe import ensure_accelerator_or_cpu

        note = ensure_accelerator_or_cpu()
        if note:
            logging.getLogger("opensim_tpu").warning(note)

    if args.command == "version":
        print(f"simon version: {VERSION}, commit: {COMMIT_ID}")
        return 0
    if args.command == "apply":
        from ..planner.apply import Applier, Options
        from ..utils import validate

        try:
            # validator rejections render the same one-liner as run errors
            opts = Options(
                simon_config=validate.user_path(args.simon_config, label="--simon-config"),
                default_scheduler_config=validate.user_path(
                    args.default_scheduler_config, label="--default-scheduler-config",
                    allow_empty=True,
                ),
                output_file=validate.user_path(
                    args.output_file, label="--output-file", allow_empty=True
                ),
                use_greed=args.use_greed,
                enable_preemption=args.enable_preemption,
                interactive=args.interactive,
                extended_resources=[r for r in args.extended_resources.split(",") if r],
                report_pods=args.report_pods,
                max_new_nodes=args.max_new_nodes,
                tie_break=args.tie_break,
                explain=args.explain,
            )
            if not args.trace:
                return Applier(opts).run()
            # span-trace the whole apply run and export Chrome-trace JSON
            # (the explicit flag wins over OPENSIM_TRACE=0). The file is
            # written in a finally: a FAILED run's partial trace is exactly
            # the one worth inspecting
            from ..obs import trace as tracing

            tr = tracing.start_trace("apply", force=True)
            rc = 1
            try:
                with tracing.trace_scope(tr):
                    rc = Applier(opts).run()
                return rc
            finally:
                tr.finish(status="ok" if rc == 0 else "error")
                tracing.write_chrome(tr, args.trace)
                print(
                    f"trace written to {args.trace} "
                    "(chrome://tracing or ui.perfetto.dev)",
                    file=sys.stderr,
                )
        except (OSError, ValueError) as e:
            print(f"simon apply: {e}", file=sys.stderr)
            return 1
    if args.command in ("defrag", "drain"):
        try:
            return run_defrag(args)
        except (OSError, ValueError) as e:
            print(f"simon defrag: {e}", file=sys.stderr)
            return 1
    if args.command == "campaign":
        try:
            return run_campaign_cmd(args)
        except (OSError, ValueError) as e:
            print(f"simon campaign: {e}", file=sys.stderr)
            return 1
    if args.command == "explain":
        try:
            return run_explain(args)
        except (OSError, ValueError) as e:
            print(f"simon explain: {e}", file=sys.stderr)
            return 1
    if args.command == "server":
        from .. import native
        from ..server.rest import serve

        if args.access_log:
            os.environ["OPENSIM_ACCESS_LOG"] = "1"
        native.available()  # warm the C++ engine build before the first request
        try:
            return serve(
                kubeconfig=args.kubeconfig, master=args.master, port=args.port,
                watch=args.watch, journal=args.journal, workers=args.workers,
                standby=args.standby, ha_handover=args.handover,
            )
        except ValueError as e:
            # serve()'s path validators reject control characters
            print(f"simon server: {e}", file=sys.stderr)
            return 1
    if args.command == "replay":
        try:
            return run_replay(args)
        except (OSError, ValueError) as e:
            print(f"simon replay: {e}", file=sys.stderr)
            return 1
    if args.command == "loadgen":
        import json as _json

        from ..server.loadgen import run_loadgen

        try:
            report = run_loadgen(
                args.url.rstrip("/"), mode=args.mode, concurrency=args.concurrency,
                qps=args.qps, duration_s=args.duration, replicas=args.replicas,
                cpu=args.cpu, mem=args.mem, timeout_s=args.timeout,
            )
        except (OSError, ValueError) as e:
            print(f"simon loadgen: {e}", file=sys.stderr)
            return 1
        line = _json.dumps(report, sort_keys=True)
        print(line)
        if args.output_file:
            from ..utils import validate

            try:
                with open(validate.user_path(args.output_file, label="--output-file"), "w") as f:
                    f.write(line + "\n")
            except (OSError, ValueError) as e:
                print(f"simon loadgen: {e}", file=sys.stderr)
                return 1
        return 0
    if args.command == "top":
        try:
            return run_top(args)
        except KeyboardInterrupt:
            return 0
    if args.command == "dash":
        try:
            return run_dash(args)
        except KeyboardInterrupt:
            return 0
    if args.command == "mem":
        return run_mem(args)
    if args.command == "profile":
        return run_profile(args)
    if args.command == "lint":
        # same engine as `python -m opensim_tpu.analysis` / `make lint`:
        # forward the flags so the analyzer stays reachable without make
        from ..analysis.__main__ import main as lint_main

        argv2: List[str] = list(args.lint_paths)
        if args.rules:
            argv2 += ["--rules", args.rules]
        if args.format:
            argv2 += ["--format", args.format]
        if args.list_rules:
            argv2.append("--list-rules")
        if args.cache:
            argv2 += ["--cache", args.cache]
        if args.no_cache:
            argv2.append("--no-cache")
        if args.sarif_out:
            argv2 += ["--sarif-out", args.sarif_out]
        if args.corpus:
            argv2 += ["--corpus", args.corpus]
        if args.changed:
            argv2.append("--changed")
        if args.jobs is not None:
            argv2 += ["--jobs", str(args.jobs)]
        return lint_main(argv2)
    if args.command == "gen-doc":
        try:
            return gen_doc(parser, args.output_dir)
        except (OSError, ValueError) as e:
            print(f"simon gen-doc: {e}", file=sys.stderr)
            return 1
    parser.print_help()
    return 2


def run_top(args) -> int:
    """``simon top``: the capacity observatory's live view — fetch
    ``/api/cluster/report`` and render the same numbers the report tables
    carry (one shot, ``--json``, or a ``--watch`` refresh loop)."""
    import json as _json
    import time as _time
    import urllib.error
    import urllib.parse
    import urllib.request

    from ..obs.capacity import format_top

    params = {}
    if args.no_headroom:
        params["headroom"] = "0"
    if args.mem:
        params["mem"] = "1"
    extended = [e for e in args.extended_resources.split(",") if e]
    if extended:
        params["extended"] = ",".join(extended)
    url = f"{args.url.rstrip('/')}/api/cluster/report"
    if params:
        url += "?" + urllib.parse.urlencode(params)

    def fetch() -> dict:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            return _json.load(resp)

    while True:
        try:
            report = fetch()
        except (urllib.error.URLError, OSError, ValueError) as e:
            if args.watch:
                # a dashboard must survive server restarts and transient
                # blips (watch(1)/kubectl top semantics): report the error
                # in place and keep polling until Ctrl-C
                print(f"\x1b[2J\x1b[Hsimon top: {url}: {e} (retrying)", flush=True)
                _time.sleep(max(0.1, args.interval))
                continue
            print(f"simon top: {url}: {e}", file=sys.stderr)
            return 1
        if args.json:
            rendered = _json.dumps(report, indent=2, sort_keys=True)
        else:
            rendered = format_top(report).rstrip("\n")
        if args.watch:
            # clear + home, like watch(1)/kubectl top: the view refreshes
            # in place instead of scrolling the terminal
            print(f"\x1b[2J\x1b[H{rendered}", flush=True)
            _time.sleep(max(0.1, args.interval))
        else:
            print(rendered)
            return 0


def run_dash(args) -> int:
    """``simon dash``: fetch the ring + SLO surfaces, render via the pure
    row functions in ``cli/dash.py`` (one frame with ``--once``, refresh
    in place otherwise — watch(1) semantics like ``simon top``)."""
    import json as _json
    import time as _time

    from .dash import dash_rows, fetch_dash, format_dash

    while True:
        payload = fetch_dash(args.url, args.range_spec, timeout_s=args.timeout)
        if args.json:
            rendered = _json.dumps(dash_rows(payload), sort_keys=True)
        else:
            rendered = format_dash(payload)
        if args.once:
            print(rendered)
            # both surfaces down = nothing was dashboarded; exit nonzero
            # so smoke harnesses notice
            return 1 if ("timeseries" not in payload and "slo" not in payload) else 0
        print(f"\x1b[2J\x1b[H{rendered}", flush=True)
        _time.sleep(max(0.1, args.interval))


def run_defrag(args) -> int:
    """``simon defrag`` / ``simon drain``: batch-evaluated node-drain
    what-ifs. Text and ``--json`` both serialize the SAME rows
    (``planner/report.drain_plan_rows`` — the byte-parity contract every
    report table follows)."""
    import json as _json

    from ..planner.apply import Applier, Options
    from ..planner.defrag import plan_drains
    from ..planner.report import _table, drain_plan_rows
    from ..utils import validate

    applier = Applier(
        Options(simon_config=validate.user_path(args.simon_config, label="--simon-config"))
    )
    cluster = applier.load_cluster()
    apps = applier.load_apps()

    candidates = [c.strip() for c in args.candidates.split(",") if c.strip()] or None
    if candidates:
        known = {n.metadata.name for n in cluster.nodes}
        unknown = [c for c in candidates if c not in known]
        if unknown:
            print(f"simon defrag: unknown node(s): {', '.join(unknown)}", file=sys.stderr)
            return 1
    result = plan_drains(cluster, apps, candidates=candidates)
    rows = drain_plan_rows(result.plans)
    out = (
        open(validate.user_path(args.output_file, label="--output-file"), "w")
        if args.output_file
        else sys.stdout
    )
    try:
        if args.json:
            print(
                _json.dumps(
                    {
                        "table": {"header": rows[0], "rows": rows[1:]},
                        "drainable": len(result.drainable()),
                        "total": len(result.plans),
                    },
                    sort_keys=True,
                ),
                file=out,
            )
        else:
            print("Drain Plan", file=out)
            _table(rows, out)
            print(f"\n{len(result.drainable())}/{len(result.plans)} node(s) drainable", file=out)
    finally:
        if args.output_file:
            out.close()
    return 0


def run_campaign_cmd(args) -> int:
    """``simon campaign <spec.yaml>``: execute a lifecycle campaign locally
    against the spec's cluster, or — with ``--url`` — POST its steps to a
    live server's ``/api/campaign`` (evaluated against the live twin).
    Text and ``--json`` both serialize the same table rows."""
    import json as _json

    from ..planner import campaign as campaign_mod
    from ..planner.report import render_campaign
    from ..utils import validate

    spec_path = validate.user_path(args.spec, label="spec")
    spec = campaign_mod.load_campaign(spec_path)
    if args.url:
        import urllib.error
        import urllib.request
        import yaml as _yaml

        with open(spec_path) as fh:
            doc = _yaml.safe_load(fh) or {}
        body = _json.dumps(
            {
                "name": spec.name,
                "steps": (doc.get("spec") or {}).get("steps") or [],
                **({"mode": args.exec_mode} if args.exec_mode else {}),
            }
        ).encode()
        req = urllib.request.Request(
            f"{args.url.rstrip('/')}/api/campaign",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=args.timeout) as resp:
                result = _json.load(resp)
        except urllib.error.HTTPError as e:
            try:
                detail = _json.load(e)
            except ValueError:
                detail = {"error": str(e)}
            print(f"simon campaign: HTTP {e.code}: {detail.get('error', e)}", file=sys.stderr)
            return 1
    else:
        cluster = campaign_mod.load_campaign_cluster(spec)
        result = campaign_mod.run_campaign(
            cluster, spec, mode=args.exec_mode or None
        ).to_dict()
    out = sys.stdout
    if args.json:
        rendered = _json.dumps(result, indent=2, sort_keys=True)
        print(rendered, file=out)
    else:
        render_campaign(result, out)
    if args.output_file:
        with open(validate.user_path(args.output_file, label="--output-file"), "w") as fh:
            fh.write(_json.dumps(result, sort_keys=True) + "\n")
    # a campaign that left evictions blocked or pods unschedulable is a
    # finding, not a failure: exit 0 with the verdict in the report
    return 0


def _fetch_debug(url: str, timeout: float):
    import json as _json
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return _json.load(resp), None
    except (urllib.error.URLError, OSError, ValueError) as e:
        return None, f"{url}: {e}"


def run_mem(args) -> int:
    """``simon mem``: the memory observatory's live view — fetch
    ``GET /api/debug/memory`` and render the footprint tables (or the raw
    JSON with ``--json``)."""
    import json as _json

    from ..obs.footprint import fmt_bytes
    from ..planner.report import _table

    url = f"{args.url.rstrip('/')}/api/debug/memory"
    if not args.fields:
        url += "?fields=0"
    payload, err = _fetch_debug(url, args.timeout)
    if err:
        print(f"simon mem: {err}", file=sys.stderr)
        return 1
    if args.json:
        print(_json.dumps(payload, indent=2, sort_keys=True))
        return 0
    out = sys.stdout
    proc = payload.get("process") or {}
    print(
        f"process: RSS {fmt_bytes(int(proc.get('rss_bytes', 0)))} "
        f"(peak {fmt_bytes(int(proc.get('rss_peak_bytes', 0)))})",
        file=out,
    )
    for dev, stats in sorted((payload.get("devices") or {}).items()):
        print(
            f"device {dev}: {fmt_bytes(int(stats.get('in_use', 0)))} in use "
            f"(peak {fmt_bytes(int(stats.get('peak', 0)))})",
            file=out,
        )
    cache = payload.get("prepcache") or {}
    entries = cache.get("entries") or []
    print(
        f"\nprep cache: {fmt_bytes(int(cache.get('total_bytes', 0)))} across "
        f"{len(entries)} entr{'y' if len(entries) == 1 else 'ies'} "
        f"({fmt_bytes(int(cache.get('shared_bytes', 0)))} shared between "
        f"delta lineages), {cache.get('compactions', 0)} compaction(s)",
        file=out,
    )
    dtypes = cache.get("dtypes") or {}
    if dtypes:
        print(
            "arena bytes by dtype: "
            + ", ".join(f"{k}={fmt_bytes(int(v))}" for k, v in sorted(dtypes.items())),
            file=out,
        )
    if entries:
        rows = [["Entry", "Bytes", "Unique", "Depth", "Pods", "Drop%"]]
        for e in entries:
            rows.append(
                [
                    e.get("key", "")[:40],
                    fmt_bytes(int(e.get("bytes", 0))),
                    fmt_bytes(int(e.get("unique_bytes", 0))),
                    str(e.get("lineage_depth", 0)),
                    str(e.get("pods", 0)),
                    f"{float(e.get('drop_density', 0.0)) * 100:.1f}",
                ]
            )
        print("", file=out)
        _table(rows, out)
    rings = payload.get("rings") or {}
    if rings:
        rows = [["Ring", "Occupancy"]]
        for ring, occ in sorted(rings.items()):
            rows.append([ring, f"{occ.get('entries', 0)}/{occ.get('capacity', 0)}"])
        print("", file=out)
        _table(rows, out)
    return 0


def run_profile(args) -> int:
    """``simon profile``: cumulative per-phase latency profiles + compile
    telemetry from ``GET /api/debug/profile``."""
    import json as _json

    from ..planner.report import _table

    payload, err = _fetch_debug(
        f"{args.url.rstrip('/')}/api/debug/profile", args.timeout
    )
    if err:
        print(f"simon profile: {err}", file=sys.stderr)
        return 1
    if args.json:
        print(_json.dumps(payload, indent=2, sort_keys=True))
        return 0
    out = sys.stdout
    phases = payload.get("phases") or {}
    spans = phases.get("spans") or {}
    print(f"phase profile over {phases.get('traces', 0)} recorded trace(s):", file=out)
    rows = [["Span", "Calls", "Total s", "Exclusive s", "Mean s", "p50 s", "p99 s", "Max s"]]
    for name, d in spans.items():
        rows.append(
            [
                name,
                str(d.get("count", 0)),
                f"{d.get('seconds', 0.0):.3f}",
                f"{d.get('exclusive_seconds', 0.0):.3f}",
                f"{d.get('mean_s', 0.0):.4f}",
                f"{d.get('p50_s', 0.0):.4f}",
                f"{d.get('p99_s', 0.0):.4f}",
                f"{d.get('max_s', 0.0):.4f}",
            ]
        )
    _table(rows, out)
    compiles = payload.get("compiles") or {}
    backend = compiles.get("backend") or {}
    print(
        f"\nbackend compiles: {backend.get('compiles', 0)} "
        f"({backend.get('seconds', 0.0):.2f}s)",
        file=out,
    )
    boundaries = compiles.get("boundaries") or {}
    if boundaries:
        rows = [["Boundary", "Compiles", "Seconds", "Signatures", "Causes"]]
        for name, fn in sorted(boundaries.items()):
            causes = ", ".join(
                f"{c}={n}" for c, n in sorted((fn.get("causes") or {}).items())
            )
            rows.append(
                [
                    name,
                    str(fn.get("compiles", 0)),
                    f"{fn.get('seconds', 0.0):.3f}",
                    str(fn.get("distinct_signatures", 0)),
                    causes,
                ]
            )
        _table(rows, out)
    pc = compiles.get("persistent_cache")
    if pc:
        print(
            f"persistent jit cache: {pc.get('files', 0)} file(s), "
            f"{pc.get('bytes', 0)} bytes at {pc.get('dir', '')}",
            file=out,
        )
    events = compiles.get("cache_events") or {}
    if events:
        print(
            "compilation-cache events: "
            + ", ".join(f"{k}={v}" for k, v in sorted(events.items())),
            file=out,
        )
    pipe = payload.get("pipeline") or {}
    if pipe.get("batches"):
        overlap = pipe.get("prep_overlap_s", 0.0)
        print(
            f"\nadmission pipeline ({'on' if pipe.get('enabled') else 'off'}): "
            f"{pipe.get('batches', 0)} batch(es), "
            f"{pipe.get('overlapped_batches', 0)} overlapped "
            f"({overlap:.3f}s prep under dispatch)",
            file=out,
        )
        stages = pipe.get("stages") or {}
        if stages:
            rows = [["Stage", "Batches", "Total s", "Mean s", "Max s"]]
            for stage in ("prep", "dispatch", "decode"):
                d = stages.get(stage)
                if not d:
                    continue
                count = d.get("count", 0)
                total = d.get("total_s", 0.0)
                rows.append(
                    [
                        stage,
                        str(int(count)),
                        f"{total:.3f}",
                        f"{(total / count if count else 0.0):.4f}",
                        f"{d.get('max_s', 0.0):.4f}",
                    ]
                )
            _table(rows, out)
        lanes = pipe.get("lane_admitted") or {}
        if pipe.get("lanes_enabled") and lanes:
            promo = pipe.get("starvation_promotions", 0)
            print(
                "priority lanes: "
                + ", ".join(
                    f"{lane}={n} admitted" for lane, n in sorted(lanes.items())
                )
                + f", {promo} starvation promotion(s)",
                file=out,
            )
    native = payload.get("native") or {}
    steps = native.get("steps") or {}
    if any(steps.values()):
        inc = int(steps.get("incremental", 0))
        gen = int(steps.get("generic", 0))
        total = inc + gen
        pct = (100.0 * inc / total) if total else 0.0
        print(
            f"\nC++ engine paths: {inc} incremental / {gen} generic "
            f"step(s) ({pct:.1f}% incremental)",
            file=out,
        )
        classes = native.get("classes") or {}
        if classes:
            print(
                "incremental carry classes: "
                + ", ".join(f"{k}={n}" for k, n in sorted(classes.items())),
                file=out,
            )
        bails = native.get("bails") or {}
        if bails:
            rows = [["Bail reason", "Count"]]
            for reason, n in sorted(bails.items(), key=lambda kv: (-kv[1], kv[0])):
                rows.append([reason, str(n)])
            _table(rows, out)
    return 0


def run_replay(args) -> int:
    """``simon replay <journal>`` — the twin time machine (ISSUE 11,
    server/journal.py). Streams the recorded accepted-event history through
    the live apply path, optionally paced, optionally feeding the capacity
    observatory and the scheduler, and prints one JSON summary line."""
    import json as _json
    import time as _time

    from ..server.journal import replay_events

    if not os.path.isdir(args.journal):
        print(f"simon replay: {args.journal}: not a journal directory", file=sys.stderr)
        return 1
    capacity = None
    if args.capacity:
        from ..obs.capacity import CapacityEngine

        capacity = CapacityEngine()
    counts = {"ev": 0, "rb": 0, "ck": 0}
    twin = None
    t0 = _time.time()
    for rec, twin, change in replay_events(
        args.journal, speed=args.speed, at_generation=args.at_generation
    ):
        counts[str(rec.get("t"))] = counts.get(str(rec.get("t")), 0) + 1
        if capacity is not None:
            capacity.on_replay(rec, twin, change)
        if args.events:
            print(_json.dumps({
                "type": rec.get("t"), "generation": rec.get("gen"),
                "resource": rec.get("f", ""), "event": rec.get("k", ""),
            }, sort_keys=True))
    wall_s = _time.time() - t0
    if twin is None:
        print(f"simon replay: {args.journal}: no replayable records", file=sys.stderr)
        return 1
    summary = {
        "journal": args.journal,
        "records": sum(counts.values()),
        "events": counts.get("ev", 0),
        "rebases": counts.get("rb", 0),
        "checkpoints": counts.get("ck", 0),
        "generation": twin.generation,
        "fingerprint": twin.fingerprint(),
        "wall_s": round(wall_s, 3),
        "speed": args.speed,
        "events_per_s": round(counts.get("ev", 0) / wall_s, 1) if wall_s > 0 else 0.0,
    }
    if capacity is not None:
        s = capacity.sample()
        if s is not None:
            summary["capacity"] = {
                "nodes": s.nodes, "pods_bound": s.pods_bound,
                "pods_pending": s.pods_pending,
                "utilization": {k: round(v, 4) for k, v in s.utilization.items()},
                "fragmentation": {k: round(v, 4) for k, v in s.fragmentation.items()},
            }
    if args.schedule > 0:
        # the reconstructed twin is schedulable state, not a data dump:
        # place a synthetic workload onto it through the full engine path
        from ..engine.simulator import AppResource, simulate
        from ..models import ResourceTypes, fixtures as fx

        rt = ResourceTypes()
        rt.deployments.append(
            fx.make_fake_deployment("replay-probe", args.schedule, "100m", "256Mi")
        )
        t1 = _time.time()
        result = simulate(twin.materialize(), [AppResource("replay", rt)])
        summary["schedule"] = {
            "requested": args.schedule,
            "scheduled": args.schedule - len(result.unscheduled_pods),
            "unscheduled": len(result.unscheduled_pods),
            "wall_s": round(_time.time() - t1, 3),
        }
    line = _json.dumps(summary, sort_keys=True)
    print(line)
    if args.output_file:
        from ..utils import validate

        with open(validate.user_path(args.output_file, label="--output-file"), "w") as f:
            f.write(line + "\n")
    return 0


def _render_explanation(e, out) -> None:
    """Human rendering of one PlacementExplanation (``simon explain``)."""
    print(f"pod {e.pod}: {e.status}"
          + (f" on {e.node}" if e.node else "")
          + (" (pre-bound; bypassed the scheduler)" if e.forced else ""),
          file=out)
    from ..engine import reasons as reasons_mod

    if e.message:
        print(f"  {e.message}", file=out)
    for line in reasons_mod.count_lines(e.reasons):
        print(f"  {line}", file=out)
    if e.scores:
        print(f"  per-plugin score breakdown on {e.node}:", file=out)
        width = max(len(k) for k in e.scores)
        for k, v in e.scores.items():
            print(f"    {k:<{width}}  {v:10.4f}", file=out)
        print(f"    {'total':<{width}}  {e.score:10.4f}", file=out)
        if e.runner_up is not None:
            print(f"  margin {e.margin:.4f} over runner-up {e.runner_up}", file=out)


def run_explain(args) -> int:
    """``simon explain``: one simulation with the decision audit on, then
    print the named pod's deep explanation (score breakdown / kube-style
    rejection counts) or, without a pod, the audit summary."""
    import json as _json

    from ..engine import explain as explain_mod
    from ..engine.simulator import simulate
    from ..planner.apply import Applier, Options

    from ..utils import validate

    applier = Applier(
        Options(
            simon_config=validate.user_path(args.simon_config, label="--simon-config"),
            default_scheduler_config=validate.user_path(
                args.default_scheduler_config, label="--default-scheduler-config",
                allow_empty=True,
            ),
            use_greed=bool(args.use_greed),
        )
    )
    cluster = applier.load_cluster()
    apps = applier.load_apps()
    result = simulate(
        cluster, apps, use_greed=args.use_greed,
        sched_config=applier.sched_config, explain=True,
    )
    engine = result.engine
    if engine is None or engine.explain_ctx is None:
        print("simon explain: the simulation produced no decisions (no pods)", file=sys.stderr)
        return 1
    ctx = engine.explain_ctx
    out = sys.stdout
    if args.pod:
        idx = ctx.index_of(args.pod)
        if idx is None:
            known = sorted(
                f"{p.metadata.namespace}/{p.metadata.name}" for p in ctx.prep.ordered
            )
            preview = ", ".join(known[:8]) + (", …" if len(known) > 8 else "")
            print(
                f"simon explain: no pod named {args.pod!r} in the simulated "
                f"stream ({len(known)} pods: {preview})",
                file=sys.stderr,
            )
            return 1
        deep = explain_mod.explain_pod(ctx, idx)
        if args.json:
            print(_json.dumps(deep.to_dict(), indent=2))
        else:
            _render_explanation(deep, out)
        return 0
    # no pod named: summary + every non-scheduled pod's breakdown
    if args.json:
        print(
            _json.dumps(
                {
                    "engine": engine.describe(),
                    "filter_rejects": engine.filter_rejects or {},
                    "unschedulable": [
                        e.to_dict()
                        for e in engine.explanations or []
                        if e.status != "scheduled"
                    ],
                },
                indent=2,
            )
        )
        return 0
    from ..engine import reasons as reasons_mod

    print(f"engine: {engine.describe()}", file=out)
    if engine.filter_rejects:
        print(
            "filter rejects (nodes rejected per filter, all steps): "
            + reasons_mod.format_rejects(engine.filter_rejects),
            file=out,
        )
    bad = [e for e in engine.explanations or [] if e.status != "scheduled"]
    n_ok = len(engine.explanations or []) - len(bad)
    print(f"{n_ok} pod(s) scheduled, {len(bad)} not", file=out)
    for e in bad:
        _render_explanation(e, out)
    return 0


def _select_backend(backend: str) -> None:
    """--backend plumbing (the BASELINE north star's `--backend=tpu` knob):
    auto picks the best engine for the platform (Pallas megakernel on TPU,
    C++ engine on CPU); cpu forces the host platform; xla disables both the
    Pallas and C++ engines (pure XLA scan); native forces the C++ engine
    (implies the CPU platform for the JAX side); tpu requires the
    accelerator."""
    import jax

    if backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
        from .. import native

        native.available()  # warm the g++ build before the first request
    elif backend == "xla":
        os.environ["OPENSIM_DISABLE_FASTPATH"] = "1"
        os.environ["OPENSIM_DISABLE_NATIVE"] = "1"
    elif backend == "native":
        from .. import native

        if not native.available():
            print(f"simon: --backend native unavailable: {native.load_error()}", file=sys.stderr)
            raise SystemExit(1)
        os.environ["OPENSIM_NATIVE"] = "1"
        # the C++ engine is the no-accelerator path; keep the JAX side
        # (encoding + static precompute) off the device too
        jax.config.update("jax_platforms", "cpu")
    elif backend == "tpu":
        # probe first: jax.default_backend() itself hangs forever when the
        # accelerator tunnel is dead (utils/probe.py)
        from ..utils.probe import accelerator_reachable

        if not accelerator_reachable(fresh=True):
            print("simon: --backend tpu requested but the accelerator is unreachable", file=sys.stderr)
            raise SystemExit(1)
        if jax.default_backend() != "tpu":
            print("simon: --backend tpu requested but no TPU backend is available", file=sys.stderr)
            raise SystemExit(1)
        # a megakernel compile failure must be a hard error under an explicit
        # TPU request, not a silent fallback (engine/simulator.py honors this)
        os.environ["OPENSIM_REQUIRE_TPU"] = "1"


def gen_doc(parser: argparse.ArgumentParser, output_dir: str) -> int:
    """Markdown CLI docs — one file per subcommand plus a root index, the
    same tree cobra/doc emits for the reference
    (cmd/doc/generate_markdown.go:33 → docs/commandline/simon_apply.md …)."""
    from ..utils import validate

    output_dir = validate.user_path(output_dir, label="--output-dir")
    os.makedirs(output_dir, exist_ok=True)
    sub_actions = [a for a in parser._actions if isinstance(a, argparse._SubParsersAction)]
    commands = []
    seen_parsers = set()  # aliases map to the same parser: document once
    for action in sub_actions:
        for name, sp in action.choices.items():
            if id(sp) in seen_parsers:
                continue
            seen_parsers.add(id(sp))
            commands.append((name, sp))
    written = []
    with open(os.path.join(output_dir, "simon.md"), "w") as f:
        f.write(f"# simon\n\n{parser.description}\n\n```\n{parser.format_help()}```\n\n")
        f.write("## Commands\n\n")
        for name, sp in commands:
            f.write(f"- [simon {name}](simon_{name.replace('-', '_')}.md) — {sp.description or ''}\n")
    written.append("simon.md")
    for name, sp in commands:
        fname = f"simon_{name.replace('-', '_')}.md"
        with open(os.path.join(output_dir, fname), "w") as f:
            f.write(f"# simon {name}\n\n{sp.description or sp.prog}\n\n")
            f.write(f"```\n{sp.format_help()}```\n\n[simon](simon.md)\n")
        written.append(fname)
    print(f"docs written to {output_dir}: {', '.join(written)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
