"""Capacity planner — parity with ``pkg/apply/apply.go``.

``Applier.run()`` mirrors ``Applier.Run`` (``apply.go:103-267``): load the
cluster (custom yaml dir or live kubeconfig), render each app (chart or yaml
dir), load the candidate new-node template, then find the minimum number of
new nodes that schedules everything within the ``MaxCPU``/``MaxMemory``/
``MaxVG`` utilization caps (``satisfyResourceSetting``, ``apply.go:689-775``).

Where the reference re-simulates one candidate count at a time behind an
interactive prompt (``apply.go:203-259``), the default mode here evaluates a
whole *batch* of candidate counts as sharded scenarios in one compiled sweep
(``opensim_tpu/parallel/scenarios.py``) and binary-searches the frontier.
``--interactive`` keeps the reference's prompt loop.
"""

from __future__ import annotations

import copy
import os
import sys
from dataclasses import dataclass, field
from typing import Callable, List, Optional, TextIO

import numpy as np

from ..engine.simulator import (
    AppResource,
    SimulateResult,
    prepare,
    restore_bind_state,
    simulate,
    snapshot_bind_state,
)
from ..models import expand
from ..models.objects import ENV_MAX_CPU, ENV_MAX_MEMORY, ENV_MAX_VG, Node, ResourceTypes
from ..parallel import scenarios
from . import report as report_mod


@dataclass
class SimonConfig:
    """The simon/v1alpha1 Config CR (pkg/api/v1alpha1/types.go:3-29)."""

    name: str = ""
    custom_cluster: str = ""
    kube_config: str = ""
    app_list: List[dict] = field(default_factory=list)  # {name, path, chart}
    new_node: str = ""

    @classmethod
    def load(cls, path: str) -> "SimonConfig":
        import yaml

        with open(path) as f:
            doc = yaml.safe_load(f)
        if not isinstance(doc, dict) or doc.get("kind") != "Config":
            raise ValueError(f"{path}: not a simon Config CR")
        spec = doc.get("spec") or {}
        cluster = spec.get("cluster") or {}
        cfg = cls(
            name=(doc.get("metadata") or {}).get("name", ""),
            custom_cluster=cluster.get("customConfig", "") or "",
            kube_config=cluster.get("kubeConfig", "") or "",
            app_list=list(spec.get("appList") or []),
            new_node=spec.get("newNode", "") or "",
        )
        if not cfg.custom_cluster and not cfg.kube_config:
            raise ValueError("config: spec.cluster needs customConfig or kubeConfig")
        return cfg


@dataclass
class Options:
    simon_config: str = ""
    default_scheduler_config: str = ""
    output_file: str = ""
    use_greed: bool = False
    enable_preemption: bool = False
    interactive: bool = False
    extended_resources: List[str] = field(default_factory=list)
    report_pods: bool = False  # include the per-node Pod Info table
    max_new_nodes: int = 128  # sweep upper bound (auto mode)
    tie_break: str = "lowest"  # lowest | sample[:seed] (see parse_tie_break)
    explain: bool = False  # decision audit: append the placement audit to the report
    base_dir: str = ""  # paths in the config resolve relative to this


def _resolve(base: str, path: str) -> str:
    return path if os.path.isabs(path) or not base else os.path.join(base, path)


def resource_caps() -> tuple:
    """MaxCPU / MaxMemory / MaxVG env caps (apply.go:689-719): percentages,
    values outside [0, 100] fall back to 100."""
    caps = []
    for env in (ENV_MAX_CPU, ENV_MAX_MEMORY, ENV_MAX_VG):
        raw = os.environ.get(env, "")
        val = 100
        if raw:
            try:
                val = int(raw)
            except ValueError as e:
                raise ValueError(f"failed to convert env {env} to int: {e}")
            if val > 100 or val < 0:
                val = 100
        caps.append(val)
    return tuple(caps)


def satisfy_resource_setting(result: SimulateResult) -> tuple:
    """(ok, reason) — cluster-wide occupancy vs the env caps."""
    import json

    max_cpu, max_mem, max_vg = resource_caps()
    total_cpu = total_mem = used_cpu = used_mem = 0.0
    vg_cap = vg_req = 0.0
    for status in result.node_status:
        node = status.node
        total_cpu += node.allocatable.get("cpu", 0.0)
        total_mem += node.allocatable.get("memory", 0.0)
        for pod in status.pods:
            req = pod.resource_requests()
            used_cpu += req.get("cpu", 0.0)
            used_mem += req.get("memory", 0.0)
        anno = node.metadata.annotations.get("simon/node-local-storage")
        if anno:
            try:
                for vg in json.loads(anno).get("vgs") or []:
                    vg_cap += float(vg.get("capacity", 0) or 0)
                    vg_req += float(vg.get("requested", 0) or 0)
            except ValueError:
                pass
    if total_cpu > 0 and int(used_cpu / total_cpu * 100) > max_cpu:
        return False, (
            f"the average occupancy rate({int(used_cpu / total_cpu * 100)}%) of cpu "
            f"goes beyond the env setting({max_cpu}%)"
        )
    if total_mem > 0 and int(used_mem / total_mem * 100) > max_mem:
        return False, (
            f"the average occupancy rate({int(used_mem / total_mem * 100)}%) of memory "
            f"goes beyond the env setting({max_mem}%)"
        )
    if vg_cap > 0 and int(vg_req / vg_cap * 100) > max_vg:
        return False, (
            f"the average occupancy rate({int(vg_req / vg_cap * 100)}%) of vg "
            f"goes beyond the env setting({max_vg}%)"
        )
    return True, ""


class Applier:
    def __init__(self, opts: Options) -> None:
        self.opts = opts
        self.config = SimonConfig.load(opts.simon_config)
        base = opts.base_dir or os.path.dirname(os.path.abspath(opts.simon_config))
        self.base = base
        self.out: TextIO = sys.stdout
        # interactive-mode input source (VERDICT r4 weak #6): prompts render
        # through self.out like every other line, and the line reader is
        # injectable so scripted sessions/tests drive the survey loop without
        # a real terminal. Must raise EOFError when the source is exhausted
        # (the prompt loops treat EOF as Exit).
        self.input_fn: Callable[[], str] = input
        from ..engine.simulator import parse_tie_break

        # sampled tie-break applies to the full simulations; the batched
        # capacity sweep stays deterministic lowest-index (one packing per
        # candidate count — like running the reference's loop once)
        self.tie_seed = parse_tie_break(opts.tie_break)
        self.sched_config = None
        if opts.default_scheduler_config:
            from ..engine.schedconfig import load_scheduler_config

            self.sched_config = load_scheduler_config(opts.default_scheduler_config)

    # -- input loading ------------------------------------------------------

    def load_cluster(self) -> ResourceTypes:
        if self.config.kube_config:
            from ..server.snapshot import cluster_from_kubeconfig

            return cluster_from_kubeconfig(_resolve(self.base, self.config.kube_config))
        return expand.load_cluster_from_dir(_resolve(self.base, self.config.custom_cluster))

    def load_apps(self) -> List[AppResource]:
        apps = []
        for app in self.config.app_list:
            path = _resolve(self.base, app.get("path", ""))
            if app.get("chart"):
                from ..chart.render import process_chart

                contents = process_chart(app.get("name", ""), path)
                docs = expand.decode_yaml_strings(contents)
            else:
                docs = expand.load_yaml_objects(path)
            rt, _ = expand.resources_from_dicts(docs)
            apps.append(AppResource(name=app.get("name", ""), resources=rt))
        return apps

    def load_new_node(self) -> Optional[Node]:
        if not self.config.new_node:
            return None
        path = _resolve(self.base, self.config.new_node)
        rt = expand.load_cluster_from_dir(path)
        return rt.nodes[0] if rt.nodes else None

    # -- capacity search ----------------------------------------------------

    def _cluster_with_new_nodes(self, cluster: ResourceTypes, template: Node, count: int) -> ResourceTypes:
        new_cluster = copy.copy(cluster)
        new_cluster.nodes = list(cluster.nodes) + expand.new_fake_nodes(template, count)
        return new_cluster

    def find_min_nodes_batched(self, prep, n_real: int) -> Optional[int]:
        """Evaluate candidate new-node counts 0..max as one sharded scenario
        sweep over an existing Prepared (the cluster plus `max_new_nodes`
        candidates); return the minimal feasible count (caps included), or
        None. The same Prepared then serves the final masked re-simulation
        (VERDICT r4 #5: one expansion+encode for sweep and re-simulate)."""
        kmax = self.opts.max_new_nodes
        if prep is None:
            return 0

        # coarse geometric sweep finds the feasibility bracket, then one
        # fine sweep inside it. Feasibility is usually monotone in the node
        # count, but per-node DaemonSet load interacting with the occupancy
        # caps can make it non-monotone — so a coarse pass with no feasible
        # point falls back to sweeping every unprobed count.
        coarse = sorted({0, kmax} | {2**i for i in range(kmax.bit_length()) if 2**i <= kmax})
        ok = self._feasible_counts(prep, n_real, coarse)
        feasible_ks = [k for k, good in zip(coarse, ok) if good]
        if not feasible_ks:
            # non-monotone corner (DaemonSet load × occupancy caps): probe the
            # remaining counts in ascending chunks and stop at the first chunk
            # holding a feasible point — bounds the worst case at one extra
            # chunk instead of a full 0..kmax sweep
            rest = [k for k in range(kmax + 1) if k not in set(coarse)]
            if not rest:
                return None
            chunk = 32
            for lo in range(0, len(rest), chunk):
                batch = rest[lo : lo + chunk]
                ok = self._feasible_counts(prep, n_real, batch)
                feasible_rest = [k for k, good in zip(batch, ok) if good]
                if feasible_rest:
                    return min(feasible_rest)
            return None
        hi = min(feasible_ks)
        lo = max([k for k in coarse if k < hi], default=0)
        if hi == 0 or hi == lo + 1:
            return int(hi)
        fine = list(range(lo + 1, hi))
        ok = self._feasible_counts(prep, n_real, fine)
        for k, good in zip(fine, ok):
            if good:
                return int(k)
        return int(hi)

    def _feasible_counts(self, prep, n_real: int, ks: List[int]) -> List[bool]:
        """One sharded sweep over candidate new-node counts; a count is
        feasible when everything schedules within the env caps. DIFFERING
        scheduler profiles no longer need a sequential per-count fallback:
        ``sweep_auto`` routes mixed-profile streams through
        ``sweep_segmented`` (per-segment scans sharing each scenario's
        carry, ISSUE 8) — the NOTES.md round-5 rough edge is closed, gated
        against the segmented simulate in tests/test_planner.py."""
        res, node_valid = scenarios.sweep_counts(
            prep, n_real, ks, config=self.sched_config
        )
        S = len(ks)
        unscheduled = np.asarray(res.unscheduled)
        used = np.asarray(res.used)  # [S, N, R]
        max_cpu, max_mem, max_vg = resource_caps()
        alloc = np.asarray(prep.ec.alloc)
        vg_caps = np.asarray(prep.meta.node_vg_cap).sum(axis=-1)  # [N]
        vg_used = np.asarray(res.vg_used)

        from ..encoding.vocab import RES_CPU, RES_MEMORY

        out = []
        for s in range(S):
            if unscheduled[s] > 0:
                out.append(False)
                continue
            nv = node_valid[s]
            tot_cpu = float(alloc[nv, RES_CPU].sum())
            tot_mem = float(alloc[nv, RES_MEMORY].sum())
            cpu_occ = int(used[s, nv, RES_CPU].sum() / tot_cpu * 100) if tot_cpu else 0
            mem_occ = int(used[s, nv, RES_MEMORY].sum() / tot_mem * 100) if tot_mem else 0
            tot_vg = float(vg_caps[nv].sum())
            vg_occ = int(vg_used[s] / tot_vg * 100) if tot_vg else 0
            out.append(cpu_occ <= max_cpu and mem_occ <= max_mem and vg_occ <= max_vg)
        return out

    # -- run ----------------------------------------------------------------

    def run(self) -> int:
        close_out = False
        if self.opts.output_file:
            self.out = open(self.opts.output_file, "w")
            close_out = True
        try:
            return self._run_inner()
        finally:
            if close_out:
                self.out.close()

    def _run_inner(self) -> int:
        from ..parallel.multihost import initialize
        from ..utils.progress import Spinner

        initialize()  # no-op unless JAX_COORDINATOR is set (DCN scale-out)
        with Spinner("load cluster"):
            cluster = self.load_cluster()
        with Spinner(f"render {len(self.config.app_list)} app(s)"):
            apps = self.load_apps()
        template = self.load_new_node()

        if self.opts.interactive:
            return self._run_interactive(cluster, apps, template)

        # auto mode: batched capacity search. The initial simulation's
        # Prepared is kept so the sweep can DELTA re-encode the candidate
        # node template into it (encode once, materialize every count as
        # mask flips) instead of re-preparing the whole cluster.
        prep0 = snap0 = None
        if not self.opts.enable_preemption:  # prep reuse can't serve preemption
            prep0 = prepare(cluster, apps, use_greed=self.opts.use_greed)
            snap0 = snapshot_bind_state(prep0) if prep0 is not None else None
        with Spinner("schedule pods"):
            if prep0 is not None:
                result = simulate(
                    cluster, apps, sched_config=self.sched_config,
                    tie_seed=self.tie_seed, prep=prep0,
                    explain=self.opts.explain,
                )
            else:
                result = simulate(
                    cluster, apps, use_greed=self.opts.use_greed, sched_config=self.sched_config,
                    enable_preemption=self.opts.enable_preemption, tie_seed=self.tie_seed,
                    explain=self.opts.explain,
                )
        n_new = 0
        if result.unscheduled_pods or not satisfy_resource_setting(result)[0]:
            if template is None:
                print("Simulation failed: pods are unschedulable and no newNode is configured:", file=self.out)
                for i, up in enumerate(result.unscheduled_pods):
                    print(f"{i:4d} {up.pod.metadata.namespace}/{up.pod.metadata.name}: {up.reason}", file=self.out)
                return 1
            # one expansion+encode serves the whole sweep AND the final
            # re-simulation: the candidate template is encoded ONCE and
            # tiled into the existing arenas (prepcache.extend_with_nodes);
            # only greed/app-DaemonSet shapes fall back to a full prepare
            candidates = expand.new_fake_nodes(template, self.opts.max_new_nodes)
            full = copy.copy(cluster)
            full.nodes = list(cluster.nodes) + candidates
            with Spinner(f"capacity sweep (0..{self.opts.max_new_nodes} new nodes)"):
                prep_full = None
                if prep0 is not None:
                    from ..engine import prepcache

                    restore_bind_state(prep0, snap0)  # decode mutated the pods
                    prep_full = prepcache.extend_with_nodes(
                        prep0, candidates, cluster, apps, use_greed=self.opts.use_greed
                    )
                if prep_full is None:
                    prep_full = prepare(full, apps, use_greed=self.opts.use_greed)
                n_new = self.find_min_nodes_batched(
                    prep_full, len(cluster.nodes)
                )
            if n_new is None:
                print(
                    f"Simulation failed: still unschedulable after adding {self.opts.max_new_nodes} node(s)",
                    file=self.out,
                )
                return 1
            sub = copy.copy(cluster)
            sub.nodes = list(cluster.nodes) + candidates[:n_new]
            with Spinner(f"re-simulate with {n_new} new node(s)"):
                if self.opts.enable_preemption or self.opts.use_greed or prep_full is None:
                    # preemption mutates host state prep reuse cannot share;
                    # greed_sort's dominant-share ordering depends on the
                    # node TOTALS, so the full-candidate prep's stream order
                    # differs from a fresh sub-cluster sort — re-expand
                    result = simulate(
                        sub, apps, use_greed=self.opts.use_greed,
                        sched_config=self.sched_config,
                        enable_preemption=self.opts.enable_preemption,
                        tie_seed=self.tie_seed, explain=self.opts.explain,
                    )
                else:
                    mask = np.zeros(
                        np.asarray(prep_full.ec_np.node_valid).shape[0], dtype=bool
                    )
                    mask[: len(sub.nodes)] = True
                    result = simulate(
                        sub, apps, use_greed=self.opts.use_greed,
                        sched_config=self.sched_config, tie_seed=self.tie_seed,
                        prep=prep_full, node_valid=mask,
                        explain=self.opts.explain,
                    )
        print("Simulation success!", file=self.out)
        if n_new:
            print(f"(added {n_new} new node(s))", file=self.out)
        report_mod.report(
            result,
            extended_resources=self.opts.extended_resources,
            app_names=[a.name for a in apps],
            out=self.out,
            pod_nodes=[] if self.opts.report_pods else None,
        )
        if result.engine is not None:
            print(f"Scheduling engine: {result.engine.describe()}", file=self.out)
        if self.opts.explain and result.engine is not None:
            self._print_placement_audit(result.engine)
        return 0

    def _print_placement_audit(self, engine) -> None:
        """--explain (decision audit, ISSUE 7): per-filter reject totals
        over every scheduled step plus a kube-style breakdown for each pod
        that did not land."""
        if engine.explanations is None:
            # the final simulation ran without the audit (the interactive
            # prompt loop's re-simulations do not thread explain=)
            return
        print("\nPlacement audit:", file=self.out)
        if engine.filter_rejects:
            print(
                "  filter rejects (nodes rejected per filter, all steps): "
                + ", ".join(f"{k}={v}" for k, v in sorted(engine.filter_rejects.items())),
                file=self.out,
            )
        bad = [e for e in engine.explanations or [] if e.status != "scheduled"]
        if not bad:
            print("  every pod scheduled; no rejection breakdowns to report", file=self.out)
            return
        for e in bad:
            print(f"  {e.pod}: {e.message}", file=self.out)
            for c in e.reasons:
                print(f"    {c.count:5d} \u00d7 {c.label}", file=self.out)

    # survey.Select option labels (apply.go SurveyShowResults/AddNode/Exit)
    SURVEY_SHOW = "Show unschedulable pods"
    SURVEY_ADD = "Add nodes"
    SURVEY_EXIT = "Exit"

    def _input(self, prompt: str) -> str:
        """One interactive line: the prompt renders through ``self.out``
        (like every other line of the session) and the reply comes from the
        injectable ``self.input_fn``. EOFError propagates to the caller."""
        print(prompt, end="", file=self.out, flush=True)
        return self.input_fn()

    def _survey_select(self, message: str, options: List[str]) -> str:
        """A terminal stand-in for the reference's pterm/survey selection
        (apply.go:219-248): numbered options, accepting the number, a
        unique prefix of the label, or the legacy show/add/exit words."""
        print(message, file=self.out)
        for i, opt in enumerate(options, 1):
            print(f"  {i}) {opt}", file=self.out)
        legacy = {"show": self.SURVEY_SHOW, "add": self.SURVEY_ADD, "exit": self.SURVEY_EXIT}
        while True:
            try:
                raw = self._input("> ").strip()
            except EOFError:
                return self.SURVEY_EXIT
            if raw.isdigit() and 1 <= int(raw) <= len(options):
                return options[int(raw) - 1]
            lowered = raw.lower()
            if lowered in legacy and legacy[lowered] in options:
                return legacy[lowered]
            # legacy one-shot "add N" (the pre-round-5 syntax): stash the
            # count so the number prompt is skipped
            parts = lowered.split()
            if (
                len(parts) == 2 and parts[0] == "add" and self.SURVEY_ADD in options
                and parts[1].lstrip("-").isdigit()
            ):
                self._pending_add = int(parts[1])
                return self.SURVEY_ADD
            matches = [o for o in options if o.lower().startswith(lowered)] if raw else []
            if len(matches) == 1:
                return matches[0]
            print(f"choose 1-{len(options)}", file=self.out)

    def _survey_int(self, message: str) -> Optional[int]:
        """survey.Input for 'input node number' (apply.go:235-241)."""
        pending = getattr(self, "_pending_add", None)
        if pending is not None:
            self._pending_add = None
            raw = str(pending)
        else:
            try:
                raw = self._input(f"{message} > ").strip()
            except EOFError:
                return None
        try:
            num = int(raw)
        except ValueError:
            print("not a number", file=self.out)
            return None
        if num < 1:
            print("node number must be >= 1", file=self.out)
            return None
        return num

    def _run_interactive(self, cluster, apps, template) -> int:
        """The reference's prompt loop (apply.go:203-259): re-simulate only
        when the node count changed (Show Results re-prompts over the SAME
        result), survey-style selection, separate node-number input."""
        from ..utils.progress import Spinner

        n_new = 0
        result = None
        resimulate = True
        while True:
            if resimulate:
                with Spinner(f"schedule pods ({n_new} new node(s))"):
                    result = simulate(
                        self._cluster_with_new_nodes(cluster, template, n_new) if template else cluster,
                        apps,
                        use_greed=self.opts.use_greed,
                        sched_config=self.sched_config,
                        enable_preemption=self.opts.enable_preemption,
                        tie_seed=self.tie_seed,
                    )
            resimulate = True
            if result.unscheduled_pods:
                choice = self._survey_select(
                    f"there are still {len(result.unscheduled_pods)} pod(s) that can "
                    f"not be scheduled when add {n_new} nodes, you can:",
                    [self.SURVEY_SHOW, self.SURVEY_ADD, self.SURVEY_EXIT],
                )
                if choice == self.SURVEY_SHOW:
                    for i, up in enumerate(result.unscheduled_pods):
                        print(
                            f"{i:4d} {up.pod.metadata.namespace}/{up.pod.metadata.name}: {up.reason}",
                            file=self.out,
                        )
                    resimulate = False  # apply.go:204: Show re-prompts, no re-run
                elif choice == self.SURVEY_ADD:
                    if template is None:
                        print(
                            "no newNode template configured (spec.newNode); cannot add nodes",
                            file=self.out,
                        )
                        resimulate = False
                        continue
                    num = self._survey_int("input node number")
                    if num is None:
                        resimulate = False
                    else:
                        n_new = num
                else:
                    return 1
            else:
                ok, reason = satisfy_resource_setting(result)
                if not ok:
                    print(reason, file=self.out)
                    if template is None:
                        # nothing can improve occupancy without a newNode
                        # template; looping would re-simulate forever
                        print(
                            "no newNode template configured (spec.newNode); cannot add nodes",
                            file=self.out,
                        )
                        return 1
                    choice = self._survey_select(
                        "resource occupancy exceeds the env caps, you can:",
                        [self.SURVEY_ADD, self.SURVEY_EXIT],
                    )
                    if choice == self.SURVEY_ADD:
                        num = self._survey_int("input node number")
                        if num is None:
                            resimulate = False
                        else:
                            n_new = num
                    else:
                        return 1
                else:
                    break
        print("Simulation success!", file=self.out)
        # reportNodeInfo (apply.go:528-545) asks which nodes to detail
        try:
            nodes = self._input(
                "nodes to report pods for (comma-separated, empty = all, '-' = none) > "
            ).strip()
        except EOFError:
            nodes = "-"  # scripted stdin exhausted: skip the pod table
        pod_nodes = None if nodes == "-" else [n.strip() for n in nodes.split(",") if n.strip()]
        report_mod.report(
            result,
            extended_resources=self.opts.extended_resources,
            app_names=[a.name for a in apps],
            out=self.out,
            pod_nodes=pod_nodes,
        )
        if result.engine is not None:
            print(f"Scheduling engine: {result.engine.describe()}", file=self.out)
        if self.opts.explain and result.engine is not None:
            self._print_placement_audit(result.engine)
        return 0
