"""Report rendering — plain-text parity with the pterm tables of
``pkg/apply/apply.go:309-687`` (Node Info, Extended Resource Info, Pod Info,
App Info).

ONE computation path (ISSUE 9): every table is built by a ``*_rows``
function returning the formatted cells (header row first), and both
consumers — the text renderer below and the ``GET /api/cluster/report``
JSON endpoint (``obs/capacity.build_report``) — print/serialize those rows
verbatim. The report-parity test asserts the JSON cells are byte-equal to
the text table's cells, so the two surfaces cannot drift."""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, TextIO

from ..engine.simulator import SimulateResult
from ..models.objects import (
    ANNO_GPU_INDEX,
    ANNO_NODE_GPU_SHARE,
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_POD_LOCAL_STORAGE,
    LABEL_APP_NAME,
    LABEL_NEW_NODE,
    RES_GPU_COUNT,
    RES_GPU_MEM,
)
from ..models.quantity import format_milli, format_quantity


def _table(rows: List[List[str]], out: TextIO) -> None:
    if not rows:
        return
    widths = [max(len(str(r[c])) for r in rows) for c in range(len(rows[0]))]
    for r in rows:
        print(" | ".join(str(v).ljust(w) for v, w in zip(r, widths)).rstrip(), file=out)


def contains_gpu(extended: List[str]) -> bool:
    return "gpu" in extended


def contains_local_storage(extended: List[str]) -> bool:
    return "open-local" in extended


def report(
    result: SimulateResult,
    extended_resources: List[str],
    app_names: List[str],
    out: TextIO = sys.stdout,
    pod_nodes: List[str] = None,
) -> None:
    report_cluster_info(result, extended_resources, out)
    if pod_nodes is not None:
        report_node_info(result, extended_resources, pod_nodes, out)
    report_app_info(result, app_names, out)


# ---------------------------------------------------------------------------
# row builders (header row first; cells pre-formatted)
# ---------------------------------------------------------------------------


def pod_info_rows(
    result: SimulateResult, extended: List[str], nodes: List[str]
) -> List[List[str]]:
    """Pod Info per node — reportNodeInfo (apply.go:528-597); the reference
    prompts for the node selection, here the caller passes it (empty list =
    every node)."""
    selected = set(nodes) if nodes else {ns.node.metadata.name for ns in result.node_status}
    header = ["Node", "Pod", "App Name", "CPU Requests", "Memory Requests"]
    if contains_local_storage(extended):
        header.append("Volume Request")
    if contains_gpu(extended):
        header.append("GPU Mem Requests")
    rows = [header]
    for status in result.node_status:
        if status.node.metadata.name not in selected:
            continue
        for pod in status.pods:
            req = pod.resource_requests()
            row = [
                status.node.metadata.name,
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                pod.metadata.labels.get(LABEL_APP_NAME, ""),
                format_milli(int(req.get("cpu", 0.0) * 1000)),
                format_quantity(req.get("memory", 0.0)),
            ]
            if contains_local_storage(extended):
                sizes = [
                    f"{v.get('kind')}:{format_quantity(float(v.get('size', 0) or 0))}"
                    for v in pod.local_volumes()
                ]
                row.append(",".join(sizes))
            if contains_gpu(extended):
                row.append(format_quantity(pod.gpu_mem_request() * pod.gpu_count_request()))
            rows.append(row)
    return rows


def cluster_info_rows(result: SimulateResult, extended: List[str]) -> List[List[str]]:
    """Node Info — the capacity report's headline table (apply.go:309-400)."""
    header = ["Node", "CPU Allocatable", "CPU Requests", "Memory Allocatable", "Memory Requests"]
    if contains_gpu(extended):
        header += ["GPU Mem Allocatable", "GPU Mem Requests"]
    header += ["Pod Count", "New Node"]
    rows = [header]
    for status in result.node_status:
        node = status.node
        cpu_alloc = node.allocatable.get("cpu", 0.0)
        mem_alloc = node.allocatable.get("memory", 0.0)
        cpu_req = sum(p.resource_requests().get("cpu", 0.0) for p in status.pods)
        mem_req = sum(p.resource_requests().get("memory", 0.0) for p in status.pods)
        row = [
            node.metadata.name,
            format_milli(int(cpu_alloc * 1000)),
            f"{format_milli(int(cpu_req * 1000))}({int(cpu_req / cpu_alloc * 100) if cpu_alloc else 0}%)",
            format_quantity(mem_alloc),
            f"{format_quantity(mem_req)}({int(mem_req / mem_alloc * 100) if mem_alloc else 0}%)",
        ]
        if contains_gpu(extended):
            gpu_alloc = node.allocatable.get(RES_GPU_MEM, 0.0)
            gpu_req = sum(p.gpu_mem_request() * p.gpu_count_request() for p in status.pods)
            row += [
                format_quantity(gpu_alloc),
                f"{format_quantity(gpu_req)}({int(gpu_req / gpu_alloc * 100) if gpu_alloc else 0}%)",
            ]
        row += [str(len(status.pods)), "√" if LABEL_NEW_NODE in node.metadata.labels else ""]
        rows.append(row)
    return rows


def local_storage_rows(result: SimulateResult) -> List[List[str]]:
    """Node Local Storage — Extended Resource Info (apply.go:402-470)."""
    rows = [["Node", "Storage Kind", "Storage Name", "Storage Allocatable", "Storage Requests"]]
    for status in result.node_status:
        anno = status.node.metadata.annotations.get(ANNO_NODE_LOCAL_STORAGE)
        if not anno:
            continue
        try:
            storage = json.loads(anno)
        except ValueError:
            continue
        for vg in storage.get("vgs") or []:
            cap = float(vg.get("capacity", 0) or 0)
            req = float(vg.get("requested", 0) or 0)
            rows.append(
                [
                    status.node.metadata.name,
                    "VG",
                    vg.get("name", ""),
                    format_quantity(cap),
                    f"{format_quantity(req)}({int(req / cap * 100) if cap else 0}%)",
                ]
            )
        for dev in storage.get("devices") or []:
            rows.append(
                [
                    status.node.metadata.name,
                    f"Device({dev.get('mediaType', '')})",
                    dev.get("device", ""),
                    format_quantity(float(dev.get("capacity", 0) or 0)),
                    "used" if dev.get("isAllocated") else "unused",
                ]
            )
    return rows


def gpu_node_rows(result: SimulateResult) -> List[List[str]]:
    """GPU Node Resource (apply.go:472-526)."""
    rows = [["Node", "GPU ID", "GPU Request/Capacity", "Pod List"]]
    for status in result.node_status:
        anno = status.node.metadata.annotations.get(ANNO_NODE_GPU_SHARE)
        if not anno:
            continue
        try:
            info = json.loads(anno)
        except ValueError:
            continue
        total = float(info.get("GpuTotalMemory", 0))
        used = sum(float(d.get("GpuUsedMemory", 0)) for d in (info.get("DevsBrief") or {}).values())
        rows.append(
            [
                f"{status.node.metadata.name} ({info.get('GpuModel', 'N/A')})",
                f"{info.get('GpuCount', 0)} GPUs",
                f"{format_quantity(used)}/{format_quantity(total)}({int(used / total * 100) if total else 0}%)",
                f"{info.get('NumPods', 0)} Pods",
            ]
        )
        for idx, dev in sorted((info.get("DevsBrief") or {}).items()):
            dtot = float(dev.get("GpuTotalMemory", 0))
            if dtot <= 0:
                continue
            dused = float(dev.get("GpuUsedMemory", 0))
            rows.append(
                [
                    f"{status.node.metadata.name} ({info.get('GpuModel', 'N/A')})",
                    str(idx),
                    f"{format_quantity(dused)}/{format_quantity(dtot)}({int(dused / dtot * 100) if dtot else 0}%)",
                    str(dev.get("PodList") or []),
                ]
            )
    return rows


def gpu_pod_map_rows(result: SimulateResult) -> List[List[str]]:
    """Pod -> Node Map (the GPU report's companion table)."""
    pod_list = [p for status in result.node_status for p in status.pods]
    rows = [["Pod", "CPU Req", "Mem Req", "GPU Req", "Host Node", "GPU IDX"]]
    for pod in sorted(pod_list, key=lambda p: p.metadata.name):
        req = pod.resource_requests()
        rows.append(
            [
                pod.metadata.name,
                format_milli(int(req.get("cpu", 0.0) * 1000)),
                format_quantity(req.get("memory", 0.0)),
                format_quantity(pod.gpu_mem_request() * pod.gpu_count_request()),
                pod.spec.node_name,
                pod.metadata.annotations.get(ANNO_GPU_INDEX, ""),
            ]
        )
    return rows


def app_info_rows(result: SimulateResult, app_names: List[str]) -> List[List[str]]:
    """App Info — pods per app per node (reportAppInfo, apply.go:598-687)."""
    rows = [["App", "Pod Count", "Nodes"]]
    for app in app_names:
        pods = [
            p
            for status in result.node_status
            for p in status.pods
            if p.metadata.labels.get(LABEL_APP_NAME) == app
        ]
        nodes = sorted({p.spec.node_name for p in pods})
        rows.append([app, str(len(pods)), ",".join(nodes)])
    return rows


def drain_plan_rows(plans: List[object]) -> List[List[str]]:
    """Drain Plan — ``simon defrag``/``simon drain`` (ISSUE 13 satellite):
    the one row source both the text table and ``--json`` serialize, so
    the two surfaces stay byte-parity like every other report table.
    ``plans`` is ``defrag.DefragResult.plans``."""
    rows = [["Node", "Drainable", "Unscheduled", "Freed CPU", "Freed Memory"]]
    for p in plans:
        rows.append(
            [
                p.node,
                "√" if p.feasible else "",
                str(p.unscheduled),
                format_milli(int(p.freed_cpu_milli)),
                format_quantity(p.freed_memory),
            ]
        )
    return rows


def campaign_step_rows(steps: List[dict]) -> List[List[str]]:
    """Campaign step table (ISSUE 13) — one row per executed step from the
    ``StepReport.to_dict()`` payloads. The ``simon campaign`` text renderer
    and the JSON ``table`` section both serialize exactly these cells
    (byte-parity gated by tests/test_campaign.py)."""
    rows = [
        [
            "#", "Step", "Type", "Evicted", "Resched", "Unsched", "Blocked",
            "Nodes", "Pods", "Pending", "CPU Util", "Frag(cpu)", "Headroom",
        ]
    ]
    for s in steps:
        cap = s.get("capacity") or {}
        util = (cap.get("utilization") or {}).get("cpu", 0.0)
        frag = (cap.get("fragmentation") or {}).get("cpu", 0.0)
        headroom = ",".join(
            f"{k}={v}" for k, v in sorted((s.get("headroomFit") or {}).items())
        )
        rows.append(
            [
                str(s.get("index", "")),
                str(s.get("name", "")),
                str(s.get("type", "")),
                str(s.get("evicted", 0)),
                str(s.get("rescheduled", 0)),
                str(len(s.get("unschedulable") or [])),
                str(len(s.get("blocked") or [])),
                str(cap.get("nodes", 0)),
                str(cap.get("pods_bound", 0)),
                str(cap.get("pods_pending", 0)),
                f"{util * 100:.1f}%",
                f"{frag:.3f}",
                headroom,
            ]
        )
    return rows


def campaign_check_rows(checks: List[dict]) -> List[List[str]]:
    """Scale-down-check / defrag verdict table — same parity contract."""
    rows = [["Node", "Removable", "Pods", "Unschedulable", "PDB Blocked", "Freed CPU", "Freed Memory"]]
    for c in checks:
        rows.append(
            [
                str(c.get("node", "")),
                "√" if c.get("removable") else "",
                str(c.get("pods", 0)),
                str(c.get("unschedulable", 0)),
                str(c.get("pdbBlocked", 0)),
                format_milli(int(float(c.get("freedCpu", 0.0)) * 1000)),
                format_quantity(float(c.get("freedMemory", 0.0))),
            ]
        )
    return rows


def render_campaign(result: dict, out: TextIO = sys.stdout) -> None:
    """Text rendering of one ``CampaignResult.to_dict()`` payload — prints
    the SAME rows the JSON ``table`` section carries."""
    print(f"Campaign {result.get('name', '')} ({result.get('mode', '')} execution)", file=out)
    table = result.get("table") or {}
    rows = [table.get("header") or []] + list(table.get("rows") or [])
    _table([r for r in rows if r], out)
    steps = result.get("steps") or []
    checks = [c for s in steps for c in (s.get("checks") or [])]
    if checks:
        print("\nScale-down verdicts", file=out)
        _table(campaign_check_rows(checks), out)
    for s in steps:
        for b in s.get("blocked") or []:
            print(
                f"\nBLOCKED eviction (step {s.get('index')}): {b.get('pod')} on "
                f"{b.get('node')} — disruption budget exhausted ({b.get('pdb')})",
                file=out,
            )
        for u in s.get("unschedulable") or []:
            print(
                f"\nunschedulable (step {s.get('index')}): {u.get('pod')}: {u.get('reason')}",
                file=out,
            )
    print(f"\ncampaign fingerprint: {result.get('fingerprint', '')}", file=out)


def _table_dict(rows: List[List[str]]) -> Dict[str, object]:
    return {"header": rows[0], "rows": rows[1:]}


def report_data(
    result: SimulateResult,
    extended: List[str],
    app_names: List[str],
    pod_nodes: Optional[List[str]] = None,
) -> dict:
    """The structured report — the same rows the text tables print, keyed
    by section (``GET /api/cluster/report`` serializes this verbatim)."""
    out: dict = {"nodeInfo": _table_dict(cluster_info_rows(result, extended))}
    if contains_local_storage(extended):
        out["localStorage"] = _table_dict(local_storage_rows(result))
    if contains_gpu(extended):
        out["gpuNodes"] = _table_dict(gpu_node_rows(result))
        out["gpuPodMap"] = _table_dict(gpu_pod_map_rows(result))
    if app_names:
        out["appInfo"] = _table_dict(app_info_rows(result, app_names))
    if pod_nodes is not None:
        out["podInfo"] = _table_dict(pod_info_rows(result, extended, pod_nodes))
    return out


# ---------------------------------------------------------------------------
# text renderers (print the SAME rows)
# ---------------------------------------------------------------------------


def report_node_info(
    result: SimulateResult, extended: List[str], nodes: List[str], out: TextIO
) -> None:
    print("Pod Info", file=out)
    _table(pod_info_rows(result, extended, nodes), out)
    print("", file=out)


def report_cluster_info(result: SimulateResult, extended: List[str], out: TextIO) -> None:
    print("Node Info", file=out)
    _table(cluster_info_rows(result, extended), out)
    print("", file=out)

    if contains_local_storage(extended):
        print("Extended Resource Info", file=out)
        print("Node Local Storage", file=out)
        _table(local_storage_rows(result), out)
        print("", file=out)

    if contains_gpu(extended):
        print("GPU Node Resource", file=out)
        _table(gpu_node_rows(result), out)
        print("\nPod -> Node Map", file=out)
        _table(gpu_pod_map_rows(result), out)
        print("", file=out)


def report_app_info(result: SimulateResult, app_names: List[str], out: TextIO) -> None:
    if not app_names:
        return
    print("App Info", file=out)
    _table(app_info_rows(result, app_names), out)
    print("", file=out)
