"""Defragmentation / node-drain what-if sweeps.

The reference has no defragmentation tool — its only what-if loop is the
interactive add-node retry (``pkg/apply/apply.go:203-259``). This module is
the scenario-batch generalization BASELINE.md config 5 asks for: evaluate
hundreds of candidate drain plans as one sharded sweep. Scenario s drains
node d_s: the node is masked out of ``node_valid`` and the pods currently
bound to it lose their pre-bound status, so the scan reschedules them onto
the remaining nodes under full plugin semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from ..engine.simulator import AppResource, Prepared, prepare
from ..models.objects import ResourceTypes
from ..parallel import scenarios


@dataclass
class DrainPlan:
    node: str
    feasible: bool
    unscheduled: int
    # total cpu-milli + memory freed if the drain succeeds
    freed_cpu_milli: float = 0.0
    freed_memory: float = 0.0


@dataclass
class DefragResult:
    plans: List[DrainPlan] = field(default_factory=list)

    def drainable(self) -> List[DrainPlan]:
        return [p for p in self.plans if p.feasible]


def plan_drains(
    cluster: ResourceTypes,
    apps: Optional[List[AppResource]] = None,
    candidates: Optional[Sequence[str]] = None,
    prep: Optional[Prepared] = None,
) -> DefragResult:
    """Evaluate draining each candidate node (default: every node) as a
    batch of sharded scenarios; returns which drains keep the cluster
    schedulable."""
    if prep is None:
        prep = prepare(cluster, apps or [])
    if prep is None:
        return DefragResult()

    names = prep.meta.node_names
    name_to_idx = {n: i for i, n in enumerate(names)}
    cand = list(candidates) if candidates is not None else list(names)
    cand_idx = [name_to_idx[c] for c in cand if c in name_to_idx]

    N = prep.ec.node_valid.shape[0]
    P = len(prep.ordered)
    base_valid = np.asarray(prep.ec.node_valid)
    S = len(cand_idx)
    if S == 0:
        return DefragResult()

    node_valid = np.broadcast_to(base_valid, (S, N)).copy()
    pod_valid = np.ones((S, P), dtype=bool)
    forced = np.broadcast_to(prep.forced, (S, P)).copy()

    # which pods sit on each drained node (pre-bound via spec.nodeName, or
    # DaemonSet-pinned — DS pods of a drained node simply disappear)
    for s, d in enumerate(cand_idx):
        node_valid[s, d] = False
        for p, pod in enumerate(prep.ordered):
            if prep.ds_target[p] == d:
                pod_valid[s, p] = False
            elif prep.forced[p] and pod.spec.node_name == names[d]:
                forced[s, p] = False  # reschedule the drained node's pods

    res = scenarios.sweep_auto(prep, node_valid, pod_valid, forced_masks=forced)
    unscheduled = np.asarray(res.unscheduled)

    plans = []
    alloc = np.asarray(prep.ec.alloc)
    from ..encoding.vocab import RES_CPU, RES_MEMORY

    for s, d in enumerate(cand_idx):
        plans.append(
            DrainPlan(
                node=names[d],
                feasible=bool(unscheduled[s] == 0),
                unscheduled=int(unscheduled[s]),
                freed_cpu_milli=float(alloc[d, RES_CPU]),
                freed_memory=float(alloc[d, RES_MEMORY]),
            )
        )
    return DefragResult(plans=plans)
