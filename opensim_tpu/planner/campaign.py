"""Campaign engine — a declarative cluster-lifecycle scenario DSL (ISSUE 13).

The reference's only lifecycle scenario is the interactive add-node
capacity loop (``pkg/apply/apply.go:203-259``). A *campaign* replays an
ordered list of typed lifecycle steps — PDB-aware drain waves, spot
reclaim storms, deploys/scales, autoscaler what-ifs, defrag plans,
journal-sourced event ranges — against the warm prep, scoring every step
with the capacity observatory (``obs/capacity.py``).

Execution contract (``OPENSIM_CAMPAIGN_EXEC``):

- **warm** (default): ONE full ``prepare()`` for the whole campaign.
  Every later mutation is a prepcache delta — ``derive_with_app_slices``
  appends deployed pods onto the cached arenas, ``extend_with_nodes``
  splices added nodes (and their DaemonSet pods) in, drains/reclaims/
  deletes are mask flips. The scheduling carry between steps is rebuilt
  host-side from the recorded placements (``explain.replay_state`` — the
  same numpy mirror of ``kernels.bind_update`` the decision audit
  replays), so no engine state ever needs to survive a delta re-encode.
- **cold**: every step re-prepares the materialized cluster from scratch
  (pods as bare pre-bound objects in campaign stream order). The
  verification mode: ``tests/test_campaign.py`` gates warm-vs-cold
  step-fingerprint equality, which proves the delta path bit-equal to a
  per-step full prepare.

Both modes schedule through the same engines as ``simulate()`` (C++ scan
on accelerator-less hosts, XLA scan otherwise), and a step's scheduling
set is always processed in campaign stream order, so placements — and the
step fingerprints derived from them — are mode-independent.

Step types MUST be declared in :data:`STEP_TYPES` via :func:`register_step`
(lint rule OSL1501 bans ad-hoc ``step == "drain-wave"`` dispatch outside
this module). See docs/campaigns.md for the spec schema and step catalog.
"""

from __future__ import annotations

import contextlib
import copy
import hashlib
import logging
import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Type

import numpy as np

from ..engine import reasons
from ..models import expand
from ..models.objects import (
    ANNO_WORKLOAD_KIND,
    ANNO_WORKLOAD_NAME,
    ANNO_WORKLOAD_NAMESPACE,
    LABEL_NEW_NODE,
    Node,
    Pod,
    PodDisruptionBudget,
    ResourceTypes,
    Workload,
)
from ..models.selectors import match_label_selector
from ..utils import envknobs, validate

log = logging.getLogger("opensim_tpu.planner")

__all__ = [
    "CampaignError",
    "CampaignResult",
    "CampaignSpec",
    "STEP_TYPES",
    "StepReport",
    "load_campaign",
    "register_step",
    "run_campaign",
]


class CampaignError(ValueError):
    """Typed campaign-spec/execution error. ``step`` names the offending
    step (``"<index> (<name>)"``), ``field`` the offending spec field —
    the validation contract the spec tests pin down."""

    def __init__(self, message: str, step: Optional[str] = None, field: Optional[str] = None):
        self.step = step
        self.field = field
        prefix = f"step {step}: " if step is not None else ""
        body = f"{field}: {message}" if field else message
        super().__init__(prefix + body)


def exec_mode() -> str:
    """``OPENSIM_CAMPAIGN_EXEC``: ``warm`` (one full prepare + deltas) or
    ``cold`` (per-step full prepare — the verification mode)."""
    return str(envknobs.value("OPENSIM_CAMPAIGN_EXEC"))


def max_steps() -> int:
    return int(envknobs.value("OPENSIM_CAMPAIGN_MAX_STEPS"))


def max_waves() -> int:
    return int(envknobs.value("OPENSIM_CAMPAIGN_MAX_WAVES"))


# ---------------------------------------------------------------------------
# spec parsing: typed steps via the central registry
# ---------------------------------------------------------------------------

#: the central step registry (lint OSL1501: the ONLY place step types are
#: declared; dispatch anywhere else must go through this table)
STEP_TYPES: Dict[str, Type["Step"]] = {}


def register_step(type_name: str):
    def deco(cls: Type["Step"]) -> Type["Step"]:
        cls.type_name = type_name
        STEP_TYPES[type_name] = cls
        return cls

    return deco


def _where(index: int, name: str) -> str:
    return f"{index} ({name})" if name else str(index)


class _Fields:
    """Strict per-step field reader: unknown keys are typed errors naming
    the step and field (a typo'd key must not silently no-op)."""

    def __init__(self, d: dict, where: str):
        self.d = dict(d)
        self.where = where
        self.d.pop("type", None)
        self.d.pop("name", None)

    def take(self, key: str, default=None):
        return self.d.pop(key, default)

    def done(self) -> None:
        if self.d:
            bad = sorted(self.d)[0]
            raise CampaignError(
                f"unknown field (known fields are step-type specific; see docs/campaigns.md)",
                step=self.where,
                field=bad,
            )


@dataclass
class NodeSelection:
    """Shared node-targeting block: explicit ``nodes`` names, a label
    ``selector``, and an optional ``count``/``percent`` cap over the
    matched set (axis order, deterministic)."""

    nodes: List[str] = field(default_factory=list)
    selector: Optional[dict] = None
    count: Optional[int] = None
    percent: Optional[float] = None

    @classmethod
    def parse(cls, f: _Fields, require: bool = True) -> "NodeSelection":
        sel = cls(
            nodes=list(f.take("nodes") or []),
            selector=f.take("selector"),
            count=f.take("count"),
            percent=f.take("percent"),
        )
        if sel.selector is not None and not isinstance(sel.selector, dict):
            raise CampaignError("must be a label-selector mapping", step=f.where, field="selector")
        if sel.count is not None:
            try:
                sel.count = int(sel.count)
            except (TypeError, ValueError):
                raise CampaignError("must be an integer", step=f.where, field="count") from None
            if sel.count < 1:
                raise CampaignError("must be >= 1", step=f.where, field="count")
        if sel.percent is not None:
            try:
                sel.percent = float(sel.percent)
            except (TypeError, ValueError):
                raise CampaignError("must be a number", step=f.where, field="percent") from None
            if not 0.0 < sel.percent <= 100.0:
                raise CampaignError("must be in (0, 100]", step=f.where, field="percent")
        if require and not sel.nodes and sel.selector is None and sel.count is None and sel.percent is None:
            raise CampaignError(
                "needs a node selection ('nodes', 'selector', 'count' or 'percent')",
                step=f.where,
                field="nodes",
            )
        return sel

    def resolve(self, ex: "_Executor", where: str, sched_only: bool = True) -> List[int]:
        """State node indices, in axis order. Named nodes must exist and be
        alive (a typo'd node name is a typed error, not an empty drain)."""
        if self.nodes:
            out = []
            for name in self.nodes:
                si = ex.node_by_name.get(name)
                if si is None or not ex.node_alive[si]:
                    raise CampaignError(
                        f"unknown or already-removed node {name!r}", step=where, field="nodes"
                    )
                out.append(si)
        else:
            out = [
                si
                for si in range(len(ex.nodes))
                if ex.node_alive[si]
                and (not sched_only or ex.node_sched[si])
                and (
                    self.selector is None
                    or match_label_selector(self.selector, ex.nodes[si].metadata.labels)
                )
            ]
        cap = None
        if self.count is not None:
            cap = self.count
        if self.percent is not None:
            pct_cap = int(math.ceil(self.percent / 100.0 * len(out)))
            cap = pct_cap if cap is None else min(cap, pct_cap)
        return out[:cap] if cap is not None else out


class Step:
    """One typed campaign step. Subclasses are registered in
    :data:`STEP_TYPES` and implement ``parse`` + ``run``."""

    type_name = ""

    def __init__(self, index: int, name: str):
        self.index = index
        self.name = name or self.type_name
        self.where = _where(index, name)

    @classmethod
    def parse(cls, index: int, name: str, f: _Fields) -> "Step":
        raise NotImplementedError

    def run(self, ex: "_Executor", rep: "StepReport") -> None:
        raise NotImplementedError


def parse_steps(raw_steps: object) -> List[Step]:
    """``spec.steps`` → typed Step list. Every malformed shape is a
    :class:`CampaignError` naming the step and field. Step numbers are
    1-based and match the executed report's indices (the baseline scoring
    pass occupies index 0)."""
    if not isinstance(raw_steps, list) or not raw_steps:
        raise CampaignError("spec.steps must be a non-empty list", field="steps")
    if len(raw_steps) > max_steps():
        raise CampaignError(
            f"{len(raw_steps)} steps exceed OPENSIM_CAMPAIGN_MAX_STEPS={max_steps()}",
            field="steps",
        )
    steps: List[Step] = []
    for i, d in enumerate(raw_steps, start=1):
        if not isinstance(d, dict):
            raise CampaignError("step must be a mapping", step=str(i), field="steps")
        name = str(d.get("name") or "")
        where = _where(i, name)
        type_name = d.get("type")
        if not type_name:
            raise CampaignError("missing step type", step=where, field="type")
        cls = STEP_TYPES.get(str(type_name))
        if cls is None:
            raise CampaignError(
                f"unknown step type {type_name!r} (known: {', '.join(sorted(STEP_TYPES))})",
                step=where,
                field="type",
            )
        f = _Fields(d, where)
        step = cls.parse(i, name, f)
        f.done()
        steps.append(step)
    return steps


@dataclass
class CampaignSpec:
    """A parsed campaign file (``kind: Campaign``)."""

    name: str
    steps: List[Step]
    cluster: Dict[str, str] = field(default_factory=dict)  # customConfig | kubeConfig
    base_dir: str = ""


def load_campaign(path: str) -> CampaignSpec:
    import yaml

    try:
        with open(path) as fh:
            doc = yaml.safe_load(fh)
    except yaml.YAMLError as e:
        # CampaignError is a ValueError: CLI/REST surfaces render it as the
        # usual one-liner instead of a raw parser traceback
        raise CampaignError(f"{path}: invalid YAML: {e}") from e
    if not isinstance(doc, dict) or doc.get("kind") != "Campaign":
        raise CampaignError(f"{path}: not a simon Campaign document (kind: Campaign)")
    spec = doc.get("spec") or {}
    base_dir = os.path.dirname(os.path.abspath(path))
    prev = _BASE_DIR[0]
    _BASE_DIR[0] = base_dir
    try:
        steps = parse_steps(spec.get("steps"))
    finally:
        _BASE_DIR[0] = prev
    return CampaignSpec(
        name=(doc.get("metadata") or {}).get("name", "") or os.path.basename(path),
        steps=steps,
        cluster=dict(spec.get("cluster") or {}),
        base_dir=base_dir,
    )


#: base dir for relative paths inside step specs (set while parsing a file)
_BASE_DIR: List[str] = [""]

#: False while evaluating a campaign submitted over the REST API: a remote
#: caller must not make the SERVER dereference filesystem paths (the paths
#: are client-local anyway) — see :func:`remote_spec_context`
_ALLOW_PATHS: List[bool] = [True]


@contextlib.contextmanager
def remote_spec_context():
    """Evaluate a remotely-submitted campaign: any step field that names a
    filesystem path is rejected with a typed :class:`CampaignError`
    instead of being opened server-side (arbitrary-file-read hardening;
    REST campaigns inline their manifests)."""
    prev = _ALLOW_PATHS[0]
    _ALLOW_PATHS[0] = False
    try:
        yield
    finally:
        _ALLOW_PATHS[0] = prev


@validate.sanitizer
def _resolve_path(p: str) -> str:
    """The campaign planner's registered validator (OSL1603): every path
    a campaign YAML names passes through here — remote campaigns may not
    name server paths at all, control characters are rejected, and
    relative paths resolve against (and must stay under) the spec's
    directory. Rejections surface as :class:`CampaignError` so the
    CLI/REST surfaces keep the typed one-liner (400, not a generic 500)."""
    if not _ALLOW_PATHS[0]:
        raise CampaignError(
            "file paths are not allowed in campaigns submitted over the "
            "REST API (the server will not dereference them); inline the "
            "manifests instead",
            field="path",
        )
    try:
        return validate.child_path(_BASE_DIR[0], p, label="campaign path")
    except CampaignError:
        raise
    except ValueError as e:
        raise CampaignError(str(e), field="path") from e


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------


@dataclass
class StepReport:
    """Everything one step did and what it cost — placements delta,
    disruption budgets consumed, and the capacity observatory's sample."""

    index: int
    name: str
    type: str
    evicted: int = 0
    deleted: int = 0
    rescheduled: int = 0
    pods_added: int = 0
    waves: int = 0
    unschedulable: List[dict] = field(default_factory=list)  # {pod, reason}
    blocked: List[dict] = field(default_factory=list)  # {pod, pdb, node}
    nodes_cordoned: List[str] = field(default_factory=list)
    nodes_drained: List[str] = field(default_factory=list)
    nodes_removed: List[str] = field(default_factory=list)
    nodes_added: List[str] = field(default_factory=list)
    pdb_spent: Dict[str, int] = field(default_factory=dict)
    pdb_allowed: Dict[str, int] = field(default_factory=dict)
    checks: List[dict] = field(default_factory=list)  # scale-down-check verdicts
    capacity: dict = field(default_factory=dict)
    headroom_fit: Dict[str, int] = field(default_factory=dict)
    headroom_recovered: Dict[str, int] = field(default_factory=dict)
    fragmentation_delta: Dict[str, float] = field(default_factory=dict)
    journal_events: int = 0
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "name": self.name,
            "type": self.type,
            "evicted": self.evicted,
            "deleted": self.deleted,
            "rescheduled": self.rescheduled,
            "podsAdded": self.pods_added,
            "waves": self.waves,
            "unschedulable": list(self.unschedulable),
            "blocked": list(self.blocked),
            "nodesCordoned": list(self.nodes_cordoned),
            "nodesDrained": list(self.nodes_drained),
            "nodesRemoved": list(self.nodes_removed),
            "nodesAdded": list(self.nodes_added),
            "pdbSpent": dict(sorted(self.pdb_spent.items())),
            "pdbAllowed": dict(sorted(self.pdb_allowed.items())),
            "checks": list(self.checks),
            "capacity": dict(self.capacity),
            "headroomFit": dict(sorted(self.headroom_fit.items())),
            "headroomRecovered": dict(sorted(self.headroom_recovered.items())),
            "fragmentationDelta": {k: round(v, 6) for k, v in sorted(self.fragmentation_delta.items())},
            "journalEvents": self.journal_events,
            "fingerprint": self.fingerprint,
        }


@dataclass
class CampaignResult:
    name: str
    mode: str
    steps: List[StepReport]
    fingerprint: str = ""
    full_prepares: int = 0

    def to_dict(self) -> dict:
        from . import report as report_mod

        steps = [s.to_dict() for s in self.steps]
        out = {
            "name": self.name,
            "mode": self.mode,
            "steps": steps,
            "fingerprint": self.fingerprint,
            "fullPrepares": self.full_prepares,
        }
        # the SAME rows the text renderer prints (byte-parity contract —
        # every report table in this repo goes through planner/report.py)
        rows = report_mod.campaign_step_rows(steps)
        out["table"] = {"header": rows[0], "rows": rows[1:]}
        return out


# ---------------------------------------------------------------------------
# the executor: campaign state + warm/cold scheduling
# ---------------------------------------------------------------------------


class _Executor:
    """Campaign state machine. The pod/node books are arrays parallel to
    the campaign stream (pods in admission order); scheduling runs over a
    ``Prepared`` whose stream mirrors the book — persistent and delta-
    extended in warm mode, rebuilt from the materialized state per step in
    cold mode."""

    def __init__(self, cluster: ResourceTypes, mode: str):
        from ..engine.simulator import prepare

        if mode not in ("warm", "cold"):
            raise CampaignError(f"unknown execution mode {mode!r} (warm|cold)", field="mode")
        self.mode = mode
        self.cluster = cluster
        self.full_prepares = 0

        # -- node book (stable axis: rows never move; alive/sched flags flip)
        self.nodes: List[Node] = list(cluster.nodes)
        self.node_ids: List[str] = [n.metadata.name for n in self.nodes]
        self.node_by_name: Dict[str, int] = {n.metadata.name: i for i, n in enumerate(self.nodes)}
        self.node_alive = np.ones(len(self.nodes), dtype=bool)
        self.node_sched = np.ones(len(self.nodes), dtype=bool)

        # -- workload book (scale steps look templates up here)
        self.workloads: Dict[Tuple[str, str, str], Workload] = {}
        for w in (
            list(cluster.deployments)
            + list(cluster.replica_sets)
            + list(cluster.stateful_sets)
            + list(cluster.jobs)
        ):
            self.workloads[(w.kind, w.metadata.namespace or "default", w.metadata.name)] = w

        self.pdbs: List[PodDisruptionBudget] = [
            p for p in (self._as_pdb(obj) for obj in cluster.pdbs) if p is not None and p.selects()
        ]

        # -- the one full prepare of the campaign (warm mode keeps it; cold
        # mode re-prepares per step but starts from the same stream)
        prep = prepare(cluster, [])
        self.full_prepares += 1
        if prep is None and cluster.daemon_sets:
            raise CampaignError(
                "cluster expanded to no schedulable pods but carries DaemonSets; "
                "campaigns need at least one schedulable pod to anchor the stream"
            )
        if prep is None and self.mode == "warm":
            # a zero-pod cluster has no warm stream to keep: per-step
            # rebuilds are the only way to encode later admissions
            log.info("campaign cluster has no pods; warm mode degrades to cold rebuilds")
            self.mode = "cold"
        self.prep = prep

        # -- pod book, mirroring prep.ordered
        self.pods: List[Pod] = list(prep.ordered) if prep is not None else []
        P = len(self.pods)
        self.alive = np.ones(P, dtype=bool)
        self.assigned = np.full(P, -1, dtype=np.int32)
        self.forced = (
            np.array(prep.forced, dtype=bool, copy=True) if prep is not None else np.zeros(0, bool)
        )
        self.is_ds = (
            np.array([t >= 0 for t in prep.ds_target], dtype=bool)
            if prep is not None
            else np.zeros(0, bool)
        )
        gd = int(prep.ec_np.node_gpu_mem.shape[1]) if prep is not None else 0
        self.gpu_take = np.zeros((P, gd), dtype=np.float32)
        self.stable_ids: List[str] = []
        self._wl_ordinal: Dict[Tuple[str, str, str], int] = {}
        for p in self.pods:
            self.stable_ids.append(self._stable_id(p))

        # deterministic naming for campaign-added nodes: generated node
        # names differ per process run, so fingerprints use stable ids
        self._added_node_seq = 0
        self._prev_sample: Optional[dict] = None
        self._prev_headroom: Dict[str, int] = {}

    # -- identity -----------------------------------------------------------

    @staticmethod
    def _as_pdb(obj) -> Optional[PodDisruptionBudget]:
        if isinstance(obj, PodDisruptionBudget):
            return obj
        raw = getattr(obj, "raw", None)
        if isinstance(raw, dict) and raw.get("kind") == "PodDisruptionBudget":
            return PodDisruptionBudget.from_dict(raw)
        if isinstance(obj, dict) and obj.get("kind") == "PodDisruptionBudget":
            return PodDisruptionBudget.from_dict(obj)
        return None

    @staticmethod
    def _canon_workload(name: str) -> str:
        """Expansion-generated intermediate workloads (a Deployment's
        ReplicaSet, a CronJob's Job) carry a 10-hex process-counter suffix
        that differs between runs — strip it so ids stay run-stable."""
        import re

        m = re.match(r"^(.+)-[0-9a-f]{10}$", name)
        return m.group(1) if m else name

    def _stable_id(self, pod: Pod) -> str:
        """Run-independent pod identity: expansion-generated names carry a
        process-global random suffix, so workload-owned pods are identified
        by (workload, ordinal) and DaemonSet pods by (workload, target
        node) instead of the generated name."""
        kind = pod.metadata.annotations.get(ANNO_WORKLOAD_KIND, "")
        wname = self._canon_workload(pod.metadata.annotations.get(ANNO_WORKLOAD_NAME, ""))
        ns = pod.metadata.annotations.get(ANNO_WORKLOAD_NAMESPACE, "") or pod.metadata.namespace
        if kind == "DaemonSet" and wname:
            from ..engine.simulator import pinned_node_name

            pin = pinned_node_name(pod) or pod.spec.node_name
            si = self.node_by_name.get(pin)
            node_id = self.node_ids[si] if si is not None else pin
            return f"{ns}/DaemonSet/{wname}@{node_id}"
        if kind and wname:
            key = (ns, kind, wname)
            ordinal = self._wl_ordinal.get(key, 0)
            self._wl_ordinal[key] = ordinal + 1
            return f"{ns}/{kind}/{wname}#{ordinal}"
        return f"{pod.metadata.namespace}/{pod.metadata.name}"

    def _node_stable_id(self, si: int) -> str:
        return self.node_ids[si]

    # -- pdb ledger ---------------------------------------------------------

    def pdb_budgets(self) -> List[dict]:
        """``disruptionsAllowed`` per PDB from the CURRENT campaign state —
        the disruption controller's arithmetic over the live book (healthy
        = alive matching pods currently placed; expected = the alive stream
        pods sharing the matching pods' controllers, plus matching bare
        pods). Recomputed per wave so budgets recover as displaced pods
        land again."""
        out = []
        for pdb in self.pdbs:
            matching = [
                i for i in range(len(self.pods)) if self.alive[i] and pdb.matches(self.pods[i])
            ]
            healthy = sum(1 for i in matching if self.assigned[i] >= 0)
            owners = set()
            expected = 0
            for i in matching:
                p = self.pods[i]
                ctrl = next((r.uid for r in p.metadata.owner_references if r.controller), None)
                if ctrl is None:
                    expected += 1
                else:
                    owners.add((p.metadata.namespace, ctrl))
            if owners:
                for i in range(len(self.pods)):
                    if not self.alive[i]:
                        continue
                    p = self.pods[i]
                    ctrl = next((r.uid for r in p.metadata.owner_references if r.controller), None)
                    if ctrl is not None and (p.metadata.namespace, ctrl) in owners:
                        expected += 1
            out.append(
                {
                    "pdb": pdb,
                    "key": pdb.key(),
                    "allowed": pdb.disruptions_allowed(healthy, expected),
                    "matching": set(matching),
                }
            )
        return out

    def try_evict(self, idxs: List[int], rep: StepReport, respect_pdbs: bool = True) -> Tuple[List[int], List[int]]:
        """Attempt evictions in stream order against the current budgets.
        Returns ``(evicted, blocked)`` — blocked evictions are NEVER
        dropped: the caller carries them into the next wave and any
        still-blocked remainder lands loudly in ``rep.blocked``."""
        budgets = self.pdb_budgets() if respect_pdbs else []
        evicted: List[int] = []
        blocked: List[int] = []
        for i in sorted(set(idxs)):
            holds = [b for b in budgets if i in b["matching"]]
            if any(b["allowed"] <= 0 for b in holds):
                blocked.append(i)
                continue
            for b in holds:
                b["allowed"] -= 1
                rep.pdb_spent[b["key"]] = rep.pdb_spent.get(b["key"], 0) + 1
            self.displace(i)
            evicted.append(i)
        rep.evicted += len(evicted)
        return evicted, blocked

    # -- state mutations ----------------------------------------------------

    def _ensure_gpu_width(self, width: int) -> None:
        """Grow the gpu-take book when a prep's per-node GPU dim exceeds it
        (an add-nodes step introducing wider GPU nodes) — truncating takes
        would replay those devices as free."""
        if width > self.gpu_take.shape[1]:
            pad = np.zeros((self.gpu_take.shape[0], width - self.gpu_take.shape[1]), np.float32)
            self.gpu_take = np.concatenate([self.gpu_take, pad], axis=1)

    def displace(self, i: int) -> None:
        """Unbind a pod (eviction/node loss): it re-enters the pending set
        and schedules normally on the next scan (the template's old node
        pin no longer forces it — the defrag mask semantics)."""
        self.assigned[i] = -1
        self.forced[i] = False
        if self.gpu_take.shape[1]:
            self.gpu_take[i, :] = 0.0

    def delete_pod(self, i: int) -> None:
        self.alive[i] = False
        self.assigned[i] = -1
        if self.gpu_take.shape[1]:
            self.gpu_take[i, :] = 0.0

    def bound_on(self, si: int, include_ds: bool = False) -> List[int]:
        out = [
            i
            for i in range(len(self.pods))
            if self.alive[i] and int(self.assigned[i]) == si and (include_ds or not self.is_ds[i])
        ]
        return out

    # -- prep maintenance (the warm-delta / cold-rebuild split) -------------

    def _nodes_view(self) -> ResourceTypes:
        rt = ResourceTypes()
        rt.nodes = [n for i, n in enumerate(self.nodes) if self.node_alive[i]]
        return rt

    def _grow_books(self, new_pods: List[Pod], forced: List[bool], is_ds: bool = False) -> List[int]:
        lo = len(self.pods)
        n = len(new_pods)
        if not n:
            return []
        for p in new_pods:
            self.pods.append(p)
            self.stable_ids.append(self._stable_id(p))
        self.alive = np.concatenate([self.alive, np.ones(n, bool)])
        self.assigned = np.concatenate([self.assigned, np.full(n, -1, np.int32)])
        self.forced = np.concatenate([self.forced, np.array(forced, bool)])
        self.is_ds = np.concatenate([self.is_ds, np.full(n, is_ds, bool)])
        self.gpu_take = np.concatenate(
            [self.gpu_take, np.zeros((n, self.gpu_take.shape[1]), np.float32)]
        )
        return list(range(lo, len(self.pods)))

    def admit_app(self, name: str, rt: ResourceTypes, where: str) -> List[int]:
        """Append an app's expanded pods to the campaign stream — the
        deploy/scale-up/from-journal admission path. Warm mode delta
        re-encodes onto the cached arenas (``derive_with_app_slices``);
        cold mode runs the same expansion pipeline and lets the next
        rebuild encode them. Returns the new book indices."""
        from ..engine import prepcache
        from ..engine.simulator import AppResource

        if rt.daemon_sets:
            raise CampaignError(
                "app DaemonSets are not supported in campaign steps (the node-delta "
                "splice cannot reproduce their expansion order); model DaemonSets in "
                "the base cluster instead",
                step=where,
                field="app",
            )
        # deployed workloads join the scale-step lookup book, so a later
        # `scale` step can grow an app this campaign introduced
        for w in (
            list(rt.deployments) + list(rt.replica_sets)
            + list(rt.stateful_sets) + list(rt.jobs)
        ):
            self.workloads[(w.kind, w.metadata.namespace or "default", w.metadata.name)] = w
        app = AppResource(name, rt)
        if self.mode == "warm":
            got = prepcache.derive_with_app_slices(self.prep, self._nodes_view(), [app])
            if got is None:
                return []
            new_prep, slices = got
            lo, hi = slices[0]
            new_pods = list(new_prep.ordered[lo:hi])
            self.prep = new_prep
        else:
            new_pods = prepcache._expand_app(self._nodes_view(), app, use_greed=False)
        return self._grow_books(new_pods, [bool(p.spec.node_name) for p in new_pods])

    def add_nodes(self, new_nodes: List[Node], rep: StepReport, where: str) -> None:
        """Extend the node axis (autoscaler add / journal node ADDED) and
        run the new nodes' DaemonSet pods through their own scan first (a
        deterministic order both modes share: DS-major, node-minor)."""
        from ..engine import prepcache

        for n in new_nodes:
            if n.metadata.name in self.node_by_name:
                raise CampaignError(
                    f"node {n.metadata.name!r} already exists", step=where, field="nodes"
                )
        base = len(self.nodes)
        for k, n in enumerate(new_nodes):
            self.nodes.append(n)
            sid = n.metadata.name
            if n.metadata.labels.get(LABEL_NEW_NODE) is not None:
                # generated fake-node names differ per run: stable id by
                # admission ordinal instead
                sid = f"added#{self._added_node_seq}"
                self._added_node_seq += 1
            self.node_ids.append(sid)
            self.node_by_name[n.metadata.name] = base + k
            self.node_alive = np.append(self.node_alive, True)
            self.node_sched = np.append(self.node_sched, True)
            rep.nodes_added.append(sid)

        ds_idxs: List[int] = []
        if self.mode == "warm" and self.prep is not None:
            old_ids = {id(p): i for i, p in enumerate(self.prep.ordered)}
            new_prep = prepcache.extend_with_nodes(
                self.prep, new_nodes, self.cluster, [], use_greed=False
            )
            if new_prep is None:
                raise CampaignError(
                    "node delta declined (cluster DaemonSet set changed mid-campaign)",
                    step=where,
                    field="count",
                )
            # the splice reorders the stream: rebuild the books in the new
            # prep order, carrying each existing pod's row by identity
            order = []
            spliced_new: List[Pod] = []
            for p in new_prep.ordered:
                oi = old_ids.get(id(p))
                if oi is None:
                    spliced_new.append(p)
                    order.append(-1)
                else:
                    order.append(oi)
            self.prep = new_prep
            self._reorder_books(order, spliced_new, new_prep)
            ds_idxs = [i for i, o in enumerate(order) if o == -1]
        else:
            # cold: expand the new nodes' DS pods in the SAME order the warm
            # splice produces them (cluster.daemon_sets-major, node-minor)
            for ds in self.cluster.daemon_sets:
                pods_k = expand.pods_from_daemon_set(ds, new_nodes)
                ds_idxs.extend(self._grow_books(pods_k, [False] * len(pods_k), is_ds=True))
        if ds_idxs:
            self.run_scan(ds_idxs, rep, count_as="rescheduled")

    def _reorder_books(self, order: List[int], spliced_new: List[Pod], new_prep) -> None:
        """Re-index every book array to the new prep order (``order[j]`` =
        old index or -1 for a spliced-in DaemonSet pod)."""
        P = len(order)
        alive = np.ones(P, bool)
        assigned = np.full(P, -1, np.int32)
        forced = np.zeros(P, bool)
        is_ds = np.zeros(P, bool)
        gd = int(new_prep.ec_np.node_gpu_mem.shape[1])
        gpu = np.zeros((P, gd), np.float32)
        pods: List[Pod] = []
        ids: List[str] = []
        it_new = iter(spliced_new)
        for j, oi in enumerate(order):
            if oi >= 0:
                pods.append(self.pods[oi])
                ids.append(self.stable_ids[oi])
                alive[j] = self.alive[oi]
                assigned[j] = self.assigned[oi]
                forced[j] = self.forced[oi]
                is_ds[j] = self.is_ds[oi]
                w = min(gd, self.gpu_take.shape[1])
                if w:
                    gpu[j, :w] = self.gpu_take[oi, :w]
            else:
                p = next(it_new)
                pods.append(p)
                ids.append(self._stable_id(p))
                is_ds[j] = True
        self.pods, self.stable_ids = pods, ids
        self.alive, self.assigned, self.forced, self.is_ds, self.gpu_take = (
            alive, assigned, forced, is_ds, gpu,
        )

    def _materialize(self) -> Tuple[ResourceTypes, List[int], Dict[int, int]]:
        """The current campaign state as plain cluster objects: alive nodes
        in axis order, alive pods as bare (pre-bound where placed) pods in
        stream order. Also returns the state→materialized index maps."""
        rt = ResourceTypes()
        node_pos: Dict[int, int] = {}
        for si, n in enumerate(self.nodes):
            if self.node_alive[si]:
                node_pos[si] = len(rt.nodes)
                rt.nodes.append(n)
        pod_rows: List[int] = []
        for i, p in enumerate(self.pods):
            if not self.alive[i]:
                continue
            q = copy.copy(p)
            q.spec = copy.copy(p.spec)
            a = int(self.assigned[i])
            if a >= 0:
                q.spec.node_name = self.nodes[a].metadata.name
                q.phase = "Running"
            elif self.forced[i]:
                q.phase = "Pending"  # keep the spec pin: the bind is still owed
            else:
                q.spec.node_name = ""
                q.phase = "Pending"
            rt.pods.append(q)
            pod_rows.append(i)
        rt.pdbs = list(self.pdbs)
        return rt, pod_rows, node_pos

    def _rebuild_prep(self) -> Tuple[List[int], Dict[int, int]]:
        """Cold-mode prep: one full prepare of the materialized state.
        Returns the state-index list in prep order and the node map."""
        from ..engine.simulator import prepare

        rt, pod_rows, node_pos = self._materialize()
        prep = prepare(rt, [])
        self.full_prepares += 1
        self.prep = prep
        self._cold_rows = pod_rows
        self._cold_node_pos = node_pos
        return pod_rows, node_pos

    # -- the scan: one engine pass over the to-schedule set -----------------

    def run_scan(self, idxs: List[int], rep: StepReport, count_as: str = "rescheduled") -> None:
        """Schedule the given book indices (plus nothing else) against the
        current carry, in campaign stream order, and commit the placements.
        The carry is rebuilt host-side from the book (``replay_state``), so
        warm deltas and cold rebuilds see byte-identical initial state."""
        idxs = [i for i in sorted(set(idxs)) if self.alive[i] and self.assigned[i] < 0]
        if not idxs or self.prep is None and self.mode == "warm":
            self._report_pending(rep, idxs)
            return

        if self.mode == "cold":
            rows, node_pos = self._rebuild_prep()
        else:
            rows = list(range(len(self.pods)))
            node_pos = {si: si for si in range(len(self.nodes))}
        prep = self.prep
        if prep is None:
            self._report_pending(rep, idxs)
            return
        pos_of = {bi: j for j, bi in enumerate(rows)}

        P = len(prep.ordered)
        pod_valid = np.zeros(P, dtype=bool)
        forced_vec = np.zeros(P, dtype=bool)
        scan_set = [i for i in idxs if i in pos_of]
        for i in scan_set:
            pod_valid[pos_of[i]] = True
            forced_vec[pos_of[i]] = bool(self.forced[i])

        nv = np.array(np.asarray(prep.ec_np.node_valid), dtype=bool, copy=True)
        n_real = prep.meta.n_real_nodes
        for si in range(len(self.nodes)):
            pj = node_pos.get(si)
            if pj is not None and pj < n_real:
                nv[pj] = bool(self.node_alive[si] and self.node_sched[si])

        st0 = self._carry_state(prep, rows, pos_of)
        out = self._run_engine(prep, pod_valid, forced_vec, nv, st0)

        chosen = np.asarray(out.chosen)[:P]
        gpu = np.asarray(out.gpu_take)[:P]
        self._ensure_gpu_width(gpu.shape[1])
        inv_node = {pj: si for si, pj in node_pos.items()}
        placed = 0
        for i in scan_set:
            j = pos_of[i]
            c = int(chosen[j])
            if c >= 0:
                self.assigned[i] = inv_node.get(c, c)
                w = min(self.gpu_take.shape[1], gpu.shape[1])
                if w:
                    self.gpu_take[i, :w] = gpu[j, :w]
                placed += 1
        if count_as == "rescheduled":
            rep.rescheduled += placed
        self._report_pending(rep, scan_set, out=out, pos_of=pos_of, nv=nv)

    def _carry_state(self, prep, rows: List[int], pos_of: Dict[int, int]):
        from ..engine.explain import replay_state

        P = len(prep.ordered)
        chosen = np.full(P, -1, dtype=np.int32)
        gd = int(prep.ec_np.node_gpu_mem.shape[1])
        gpu = np.zeros((P, gd), np.float32)
        if self.mode == "cold":
            node_pos = self._cold_node_pos
        else:
            node_pos = None
        for j, bi in enumerate(rows):
            if not self.alive[bi]:
                continue
            a = int(self.assigned[bi])
            if a < 0:
                continue
            chosen[j] = a if node_pos is None else node_pos.get(a, -1)
            w = min(gd, self.gpu_take.shape[1])
            if w:
                gpu[j, :w] = self.gpu_take[bi, :w]
        return replay_state(prep, chosen, gpu, upto=P)

    def _run_engine(self, prep, pod_valid, forced_vec, nv, st0):
        """The same engine routing as ``simulate``'s segmented path: C++
        scan where applicable, the XLA scan otherwise."""
        from ..engine import nativepath

        if nativepath.why_not(prep, None, ()) is None:
            return nativepath.schedule(
                prep, pod_valid, node_valid=nv, forced=forced_vec, st0=st0
            )
        import jax
        import jax.numpy as jnp

        from ..encoding.state import ScanState
        from ..engine.scheduler import pad_pod_stream, scan_unroll, schedule_pods

        tmpl_p, valid_p, forced_p = pad_pod_stream(prep.tmpl_ids, pod_valid, forced_vec)
        ec_run = prep.ec._replace(node_valid=jnp.asarray(nv))
        st_dev = ScanState(*[jnp.asarray(a) for a in st0])
        out = schedule_pods(
            ec_run, st_dev, tmpl_p, valid_p, forced_p,
            features=prep.features, unroll=scan_unroll(),
        )
        jax.block_until_ready(out.chosen)
        P = len(prep.ordered)
        return out._replace(
            chosen=np.asarray(out.chosen)[:P],
            fail_counts=np.asarray(out.fail_counts)[:P],
            insufficient=np.asarray(out.insufficient)[:P],
            gpu_take=np.asarray(out.gpu_take)[:P],
        )

    def _report_pending(self, rep: StepReport, scan_set: List[int], out=None, pos_of=None, nv=None) -> None:
        """Record every scanned-but-unplaced pod with its engine-attributed
        reason (the ``engine/explain`` failure rows) in the step report."""
        n_nodes = int(nv.sum()) if nv is not None else int(self.node_alive.sum())
        for i in scan_set:
            if self.assigned[i] >= 0 or not self.alive[i]:
                continue
            pod = self.pods[i]
            if self.forced[i]:
                reason = reasons.node_not_found(pod.spec.node_name)
            elif out is not None and pos_of is not None and i in pos_of:
                j = pos_of[i]
                prep = self.prep
                sf = np.asarray(out.static_fail)
                sf_row = sf[int(prep.tmpl_ids[j])] if sf.ndim == 2 else sf
                counts = reasons.counts_from_rows(
                    sf_row,
                    np.asarray(out.fail_counts)[j],
                    np.asarray(out.insufficient)[j],
                    prep.meta.resource_names,
                )
                reason = reasons.render_unschedulable(n_nodes, counts)
            else:
                reason = reasons.render_unschedulable(n_nodes, [])
            rep.unschedulable.append({"pod": self.stable_ids[i], "reason": reason})

    def pending_idxs(self) -> List[int]:
        return [
            i
            for i in range(len(self.pods))
            if self.alive[i] and self.assigned[i] < 0 and not self.is_ds[i]
        ]

    # -- scoring ------------------------------------------------------------

    def score(self, rep: StepReport) -> None:
        """Per-step capacity sample + resource-fit headroom through the
        capacity observatory (``obs/capacity.py``) — utilization, spread,
        fragmentation and headroom deltas are measured quantities, not
        estimates."""
        from ..obs.capacity import CapacityEngine, headroom_profiles

        eng = CapacityEngine(topk=0)
        view, _, _ = self._materialize()
        eng.bootstrap(view, generation=rep.index)
        sample = eng.sample()
        cap = sample.to_dict() if sample is not None else {}
        cap.pop("ts", None)
        cap.pop("hottest", None)
        cap.pop("headroom", None)
        rep.capacity = cap
        rep.headroom_fit = {p.name: eng.fit_upper_bound(p) for p in headroom_profiles()}
        if self._prev_headroom:
            rep.headroom_recovered = {
                k: v - self._prev_headroom.get(k, 0) for k, v in rep.headroom_fit.items()
            }
        if self._prev_sample:
            prev_frag = self._prev_sample.get("fragmentation") or {}
            rep.fragmentation_delta = {
                k: v - prev_frag.get(k, 0.0)
                for k, v in (cap.get("fragmentation") or {}).items()
            }
        for b in self.pdb_budgets():
            rep.pdb_allowed[b["key"]] = b["allowed"]
        self._prev_sample = cap
        self._prev_headroom = dict(rep.headroom_fit)
        rep.fingerprint = self.fingerprint()

    def fingerprint(self) -> str:
        """Bit-stable digest of the campaign state: placements by stable
        pod id onto stable node ids, plus node liveness. Sorted, so warm
        splices and cold appends hash identically."""
        lines = []
        for i in range(len(self.pods)):
            if not self.alive[i]:
                continue
            a = int(self.assigned[i])
            where = self._node_stable_id(a) if a >= 0 else "<pending>"
            lines.append(f"p|{self.stable_ids[i]}|{where}")
        for si in range(len(self.nodes)):
            lines.append(
                f"n|{self.node_ids[si]}|{int(self.node_alive[si])}{int(self.node_sched[si])}"
            )
        h = hashlib.blake2b(digest_size=16)
        for line in sorted(lines):
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()

    # -- what-if: is node si removable from the current state? --------------

    def check_node_removable(self, si: int) -> dict:
        """Scale-down safety check (autoscaler what-if): evict node ``si``'s
        non-DaemonSet pods against a copy of the current carry and see
        whether every one reschedules — without committing anything."""
        bound = self.bound_on(si)
        budgets = self.pdb_budgets()
        pdb_blocked = 0
        for i in bound:
            holds = [b for b in budgets if i in b["matching"]]
            if any(b["allowed"] <= 0 for b in holds):
                pdb_blocked += 1
            else:
                for b in holds:
                    b["allowed"] -= 1
        unschedulable = 0
        if bound:
            saved = (
                self.assigned.copy(), self.forced.copy(), self.gpu_take.copy(),
                self.node_sched.copy(), self.node_alive.copy(),
            )
            try:
                for i in bound:
                    self.displace(i)
                self.node_sched[si] = False
                self.node_alive[si] = False
                probe = StepReport(index=-1, name="check", type="check")
                self.run_scan(bound, probe)
                unschedulable = sum(1 for i in bound if self.assigned[i] < 0)
            finally:
                (self.assigned, self.forced, self.gpu_take,
                 self.node_sched, self.node_alive) = saved
        node = self.nodes[si]
        return {
            "node": self._node_stable_id(si),
            "pods": len(bound),
            "fits": unschedulable == 0,
            "pdbBlocked": pdb_blocked,
            "unschedulable": unschedulable,
            "removable": unschedulable == 0 and pdb_blocked == 0,
            "freedCpu": float(node.allocatable.get("cpu", 0.0)),
            "freedMemory": float(node.allocatable.get("memory", 0.0)),
        }

    # -- drain machinery (shared by drain-wave and defrag) ------------------

    def drain(
        self,
        targets: List[int],
        wave_size: int,
        rep: StepReport,
        respect_pdbs: bool = True,
    ) -> None:
        """Rolling drain: cordon a wave, evict within budgets, reschedule
        the displaced pods, carry blocked evictions into the next wave.
        After the last wave, blocked evictions retry in extra passes until
        they drain or stop making progress (bounded by
        ``OPENSIM_CAMPAIGN_MAX_WAVES``); any remainder is reported loudly
        and its nodes stay cordoned — never silently dropped."""
        waves = [targets[k : k + wave_size] for k in range(0, len(targets), wave_size)]
        if len(waves) > max_waves():
            # refuse up front rather than silently abandoning the tail of
            # the target list mid-step: the bound is a spec-size guard
            raise CampaignError(
                f"{len(waves)} waves exceed OPENSIM_CAMPAIGN_MAX_WAVES="
                f"{max_waves()} (raise the knob or widen the wave size)",
                step=_where(rep.index, rep.name),
                field="wave",
            )
        blocked_carry: List[int] = []
        cordoned: set = set()
        passes = 0
        wave_iter = list(waves)
        while wave_iter or blocked_carry:
            passes += 1
            if passes > max_waves():
                break  # blocked-retry backstop; the carry is reported below
            wave = wave_iter.pop(0) if wave_iter else []
            for si in wave:
                self.node_sched[si] = False
                cordoned.add(si)
                rep.nodes_cordoned.append(self._node_stable_id(si))
            to_evict = list(blocked_carry)
            for si in wave:
                to_evict.extend(self.bound_on(si))
            if not to_evict and not wave:
                break
            before_blocked = len(blocked_carry)
            evicted, blocked_carry = self.try_evict(to_evict, rep, respect_pdbs=respect_pdbs)
            rep.waves += 1
            self.run_scan(evicted + self.pending_idxs(), rep)
            if not wave_iter and blocked_carry and not evicted and len(blocked_carry) >= before_blocked:
                break  # no progress: stop retrying, report below
        # finalize: empty cordoned targets are drained and leave the
        # cluster; nodes still holding blocked pods stay cordoned
        budgets = self.pdb_budgets()
        for i in blocked_carry:
            holds = [b["key"] for b in budgets if i in b["matching"] and b["allowed"] <= 0]
            a = int(self.assigned[i])
            rep.blocked.append(
                {
                    "pod": self.stable_ids[i],
                    "pdb": ",".join(sorted(holds)) or "?",
                    "node": self._node_stable_id(a) if a >= 0 else "<pending>",
                }
            )
        for si in targets:
            if si not in cordoned:
                continue  # never reached (retry backstop): stays untouched
            if not self.bound_on(si):
                # DaemonSet pods die with the node (kube drain ignores
                # them; the upgrade takes the node away underneath)
                for i in range(len(self.pods)):
                    if self.alive[i] and self.is_ds[i] and int(self.assigned[i]) == si:
                        self.delete_pod(i)
                        rep.deleted += 1
                self.node_alive[si] = False
                rep.nodes_drained.append(self._node_stable_id(si))


# ---------------------------------------------------------------------------
# step implementations
# ---------------------------------------------------------------------------


@register_step("drain-wave")
class DrainWaveStep(Step):
    """Rolling node drain/upgrade: cordon + PDB-respecting eviction +
    reschedule of the displaced pods, ``wave`` nodes at a time."""

    def __init__(self, index, name, selection, wave, wave_percent, respect_pdbs):
        super().__init__(index, name)
        self.selection = selection
        self.wave = wave
        self.wave_percent = wave_percent
        self.respect_pdbs = respect_pdbs

    @classmethod
    def parse(cls, index, name, f):
        where = f.where
        selection = NodeSelection.parse(f)
        wave = f.take("wave")
        wave_percent = f.take("wavePercent")
        if wave is not None:
            try:
                wave = int(wave)
            except (TypeError, ValueError):
                raise CampaignError("must be an integer", step=where, field="wave") from None
            if wave < 1:
                raise CampaignError("must be >= 1", step=where, field="wave")
        if wave_percent is not None:
            try:
                wave_percent = float(wave_percent)
            except (TypeError, ValueError):
                raise CampaignError("must be a number", step=where, field="wavePercent") from None
            if not 0.0 < wave_percent <= 100.0:
                raise CampaignError("must be in (0, 100]", step=where, field="wavePercent")
        respect = f.take("respectPdbs", True)
        if not isinstance(respect, bool):
            raise CampaignError("must be true or false", step=where, field="respectPdbs")
        return cls(index, name, selection, wave, wave_percent, respect)

    def run(self, ex, rep):
        targets = self.selection.resolve(ex, self.where)
        if not targets:
            return
        size = self.wave or 0
        if self.wave_percent is not None:
            size = max(size, int(math.ceil(self.wave_percent / 100.0 * len(targets))))
        ex.drain(targets, size or len(targets), rep, respect_pdbs=self.respect_pdbs)


@register_step("reclaim-storm")
class ReclaimStormStep(Step):
    """Spot/preemptible reclaim: the selected nodes vanish AT ONCE (the
    ``pkg/simulator`` delete-path inverse) — no cordon, no PDB protection
    (budgets don't guard against node failure), displaced pods reschedule
    in one pass."""

    def __init__(self, index, name, selection):
        super().__init__(index, name)
        self.selection = selection

    @classmethod
    def parse(cls, index, name, f):
        return cls(index, name, NodeSelection.parse(f))

    def run(self, ex, rep):
        targets = self.selection.resolve(ex, self.where, sched_only=False)
        displaced: List[int] = []
        for si in targets:
            for i in ex.bound_on(si, include_ds=True):
                if ex.is_ds[i]:
                    ex.delete_pod(i)  # DaemonSet pods die with their node
                    rep.deleted += 1
                else:
                    ex.displace(i)
                    displaced.append(i)
                    rep.evicted += 1
            ex.node_sched[si] = False
            ex.node_alive[si] = False
            rep.nodes_removed.append(ex._node_stable_id(si))
        ex.run_scan(displaced + ex.pending_idxs(), rep)


@register_step("deploy")
class DeployStep(Step):
    """Deploy an app (yaml dir / chart / inline manifests) onto the current
    state — the ``simon apply`` admission pipeline as one campaign step."""

    def __init__(self, index, name, app_name, path, chart, resources):
        super().__init__(index, name)
        self.app_name = app_name
        self.path = path
        self.chart = chart
        self.resources = resources

    @classmethod
    def parse(cls, index, name, f):
        where = f.where
        app = f.take("app")
        resources = f.take("resources")
        if app is not None and not isinstance(app, dict):
            raise CampaignError("must be a mapping {name, path[, chart]}", step=where, field="app")
        if app is None and resources is None:
            raise CampaignError("needs 'app' (name+path) or inline 'resources'", step=where, field="app")
        if resources is not None and not isinstance(resources, list):
            raise CampaignError("must be a list of manifests", step=where, field="resources")
        app = app or {}
        app_name = str(app.get("name") or name or f"deploy-{index}")
        path = app.get("path", "")
        if app and not path and resources is None:
            raise CampaignError("app needs a 'path'", step=where, field="app.path")
        return cls(index, name, app_name, path, bool(app.get("chart")), resources)

    def _load(self) -> ResourceTypes:
        if self.resources is not None:
            rt, _ = expand.resources_from_dicts(list(self.resources))
            return rt
        path = _resolve_path(self.path)
        if self.chart:
            from ..chart.render import process_chart

            docs = expand.decode_yaml_strings(process_chart(self.app_name, path))
        else:
            docs = expand.load_yaml_objects(path)
        rt, _ = expand.resources_from_dicts(docs)
        return rt

    def run(self, ex, rep):
        rt = self._load()
        for pdb in list(rt.pdbs):
            p = ex._as_pdb(pdb)
            if p is not None and p.selects():
                ex.pdbs.append(p)
        new = ex.admit_app(self.app_name, rt, self.where)
        rep.pods_added += len(new)
        ex.run_scan(new + ex.pending_idxs(), rep)


@register_step("scale")
class ScaleStep(Step):
    """Scale an existing workload to N replicas: scale-down deletes the
    trailing expansion pods (a voluntary delete, not an eviction — PDBs
    gate evictions, not ``kubectl scale``); scale-up expands new replicas
    from the workload's template and schedules them."""

    def __init__(self, index, name, kind, namespace, wl_name, replicas):
        super().__init__(index, name)
        self.kind = kind
        self.namespace = namespace
        self.wl_name = wl_name
        self.replicas = replicas

    @classmethod
    def parse(cls, index, name, f):
        where = f.where
        wl = f.take("workload")
        if not isinstance(wl, dict) or not wl.get("name"):
            raise CampaignError(
                "needs workload: {kind, name[, namespace]}", step=where, field="workload"
            )
        replicas = f.take("replicas")
        try:
            replicas = int(replicas)
        except (TypeError, ValueError):
            raise CampaignError("must be an integer", step=where, field="replicas") from None
        if replicas < 0:
            raise CampaignError("must be >= 0", step=where, field="replicas")
        return cls(
            index, name,
            str(wl.get("kind") or "Deployment"),
            str(wl.get("namespace") or "default"),
            str(wl["name"]),
            replicas,
        )

    #: expansion inserts intermediate owners (Deployment → generated
    #: ReplicaSet, CronJob → Job); a scale target owns those pods too
    _OWNED_KINDS = {
        "Deployment": ("Deployment", "ReplicaSet"),
        "CronJob": ("CronJob", "Job"),
    }

    def _owned(self, ex) -> List[int]:
        kinds = self._OWNED_KINDS.get(self.kind, (self.kind,))
        out = []
        for i in range(len(ex.pods)):
            if not ex.alive[i]:
                continue
            p = ex.pods[i]
            if (
                p.metadata.annotations.get(ANNO_WORKLOAD_KIND) in kinds
                and ex._canon_workload(p.metadata.annotations.get(ANNO_WORKLOAD_NAME, ""))
                == self.wl_name
                and (p.metadata.annotations.get(ANNO_WORKLOAD_NAMESPACE) or p.metadata.namespace)
                == self.namespace
            ):
                out.append(i)
        return out

    def run(self, ex, rep):
        owned = self._owned(ex)
        cur = len(owned)
        if self.replicas < cur:
            for i in owned[self.replicas :]:
                ex.delete_pod(i)
                rep.deleted += 1
            ex.run_scan(ex.pending_idxs(), rep)
            return
        if self.replicas == cur:
            return
        wl = ex.workloads.get((self.kind, self.namespace, self.wl_name))
        if wl is None:
            raise CampaignError(
                f"no {self.kind} {self.namespace}/{self.wl_name} in the cluster or "
                "deployed earlier in this campaign",
                step=self.where,
                field="workload",
            )
        clone = copy.copy(wl)
        clone.replicas = self.replicas - cur
        rt = ResourceTypes()
        rt.add(clone)
        new = ex.admit_app(self.wl_name, rt, self.where)
        rep.pods_added += len(new)
        ex.run_scan(new + ex.pending_idxs(), rep)


@register_step("add-nodes")
class AddNodesStep(Step):
    """Autoscaler add: clone ``count`` nodes from a template (a yaml dir
    like ``spec.newNode``, or an existing node by name) into the cluster;
    their DaemonSet pods land immediately and pending pods retry."""

    def __init__(self, index, name, count, path, clone_of):
        super().__init__(index, name)
        self.count = count
        self.path = path
        self.clone_of = clone_of

    @classmethod
    def parse(cls, index, name, f):
        where = f.where
        count = f.take("count", 1)
        try:
            count = int(count)
        except (TypeError, ValueError):
            raise CampaignError("must be an integer", step=where, field="count") from None
        if count < 1:
            raise CampaignError("must be >= 1", step=where, field="count")
        template = f.take("template")
        if not isinstance(template, dict) or not (template.get("path") or template.get("node")):
            raise CampaignError(
                "needs template: {path: <newNode yaml dir>} or {node: <existing node name>}",
                step=where,
                field="template",
            )
        return cls(index, name, count, template.get("path", ""), template.get("node", ""))

    def run(self, ex, rep):
        if self.path:
            rt = expand.load_cluster_from_dir(_resolve_path(self.path))
            if not rt.nodes:
                raise CampaignError(
                    f"no Node manifest under {self.path!r}", step=self.where, field="template.path"
                )
            template = rt.nodes[0]
        else:
            si = ex.node_by_name.get(self.clone_of)
            if si is None:
                raise CampaignError(
                    f"unknown template node {self.clone_of!r}", step=self.where, field="template.node"
                )
            template = ex.nodes[si]
        new_nodes = expand.new_fake_nodes(template, self.count)
        ex.add_nodes(new_nodes, rep, self.where)
        ex.run_scan(ex.pending_idxs(), rep)


@register_step("scale-down-check")
class ScaleDownCheckStep(Step):
    """Autoscaler what-if: for each candidate node, is it removable without
    creating unschedulable pods or breaking a disruption budget? Pure
    analysis — the state is untouched."""

    def __init__(self, index, name, selection):
        super().__init__(index, name)
        self.selection = selection

    @classmethod
    def parse(cls, index, name, f):
        return cls(index, name, NodeSelection.parse(f, require=False))

    def run(self, ex, rep):
        targets = self.selection.resolve(ex, self.where)
        for si in targets:
            rep.checks.append(ex.check_node_removable(si))


@register_step("defrag")
class DefragStep(Step):
    """``planner/defrag.plan_drains`` generalized from a single-step
    what-if to a scheduled plan: evaluate the candidates from the CURRENT
    state, pick up to ``maxNodes`` removable ones (emptiest first), and
    execute the drains wave by wave under the PDB ledger."""

    def __init__(self, index, name, selection, max_nodes, wave):
        super().__init__(index, name)
        self.selection = selection
        self.max_nodes = max_nodes
        self.wave = wave

    @classmethod
    def parse(cls, index, name, f):
        where = f.where
        selection = NodeSelection.parse(f, require=False)
        max_nodes = f.take("maxNodes", 1)
        try:
            max_nodes = int(max_nodes)
        except (TypeError, ValueError):
            raise CampaignError("must be an integer", step=where, field="maxNodes") from None
        if max_nodes < 1:
            raise CampaignError("must be >= 1", step=where, field="maxNodes")
        wave = f.take("wave", 1)
        try:
            wave = int(wave)
        except (TypeError, ValueError):
            raise CampaignError("must be an integer", step=where, field="wave") from None
        if wave < 1:
            raise CampaignError("must be >= 1", step=where, field="wave")
        return cls(index, name, selection, max_nodes, wave)

    def run(self, ex, rep):
        verdicts = [
            (si, ex.check_node_removable(si))
            for si in self.selection.resolve(ex, self.where)
        ]
        rep.checks.extend(v for _, v in verdicts)
        removable = [
            (v["pods"], v["node"], si) for si, v in verdicts if v["removable"]
        ]
        removable.sort()  # emptiest first, stable-id tie-break
        chosen = [si for _, _, si in removable[: self.max_nodes]]
        if chosen:
            ex.drain(chosen, self.wave, rep)


@register_step("from-journal")
class FromJournalStep(Step):
    """Replay a recorded generation range (``simon server --journal``)
    through the campaign's apply path: node ADDED/DELETED become node
    mutations, pod ADDED/MODIFIED/DELETED become admissions/deletions, and
    unbound arrivals schedule through the same scan as a deploy step."""

    def __init__(self, index, name, journal, gen_from, gen_to):
        super().__init__(index, name)
        self.journal = journal
        self.gen_from = gen_from
        self.gen_to = gen_to

    @classmethod
    def parse(cls, index, name, f):
        where = f.where
        journal = f.take("journal")
        if not journal:
            raise CampaignError("needs the journal directory path", step=where, field="journal")
        gen_from = f.take("fromGeneration", 0)
        gen_to = f.take("toGeneration")
        try:
            gen_from = int(gen_from)
            gen_to = None if gen_to is None else int(gen_to)
        except (TypeError, ValueError):
            raise CampaignError(
                "generations must be integers", step=where, field="fromGeneration"
            ) from None
        return cls(index, name, str(journal), gen_from, gen_to)

    def run(self, ex, rep):
        from ..server.journal import iter_records

        path = _resolve_path(self.journal)
        if not os.path.isdir(path):
            raise CampaignError(
                f"{path!r} is not a journal directory", step=self.where, field="journal"
            )
        # NET effect of the range, per object key in record order: the last
        # event wins (an add later deleted inside the window never
        # materializes) — the replayed state at toGeneration, applied
        # through the campaign's own admission/scan path.
        node_final: Dict[str, Optional[Node]] = {}
        pod_final: Dict[Tuple[str, str], Optional[dict]] = {}
        n_events = 0
        for rec in iter_records(path):
            if rec.get("t") != "ev":
                continue
            gen = int(rec.get("gen") or 0)
            if gen <= self.gen_from or (self.gen_to is not None and gen > self.gen_to):
                continue
            f_res, kind, obj = rec.get("f"), rec.get("k"), rec.get("o") or {}
            meta = obj.get("metadata") or {}
            if f_res == "nodes":
                n_events += 1
                name = str(meta.get("name") or "")
                if kind == "DELETED":
                    node_final[name] = None
                elif kind in ("ADDED", "MODIFIED"):
                    node_final[name] = Node.from_dict(obj)
            elif f_res == "pods":
                n_events += 1
                key = (str(meta.get("namespace") or ""), str(meta.get("name") or ""))
                if kind == "DELETED":
                    pod_final[key] = None
                elif kind in ("ADDED", "MODIFIED"):
                    phase = (obj.get("status") or {}).get("phase", "")
                    pod_final[key] = None if phase in ("Succeeded", "Failed") else obj
        rep.journal_events = n_events
        if not n_events:
            return

        fresh_adds = []
        for name, node in node_final.items():
            if node is None:
                continue
            si = ex.node_by_name.get(name)
            if si is None:
                fresh_adds.append(node)
            elif ex.node_alive[si]:
                # MODIFIED of a node the campaign already tracks: capacity
                # changes need a rebase, not a delta — reported loudly as a
                # skipped event, never silently replayed with stale alloc
                rep.unschedulable.append(
                    {
                        "pod": f"<node {ex._node_stable_id(si)}>",
                        "reason": "journal node MODIFIED skipped: in-place node "
                        "capacity changes are outside the campaign delta envelope "
                        "(replay from a checkpoint at this generation instead)",
                    }
                )
        if fresh_adds:
            ex.add_nodes(fresh_adds, rep, self.where)
        displaced: List[int] = []
        for name, node in node_final.items():
            if node is not None:
                continue
            si = ex.node_by_name.get(name)
            if si is None or not ex.node_alive[si]:
                continue
            for i in ex.bound_on(si, include_ds=True):
                if ex.is_ds[i]:
                    ex.delete_pod(i)
                    rep.deleted += 1
                else:
                    ex.displace(i)
                    displaced.append(i)
            ex.node_sched[si] = False
            ex.node_alive[si] = False
            rep.nodes_removed.append(ex._node_stable_id(si))
        key_to_idx = {
            (p.metadata.namespace, p.metadata.name): i
            for i, p in enumerate(ex.pods)
            if ex.alive[i]
        }
        pod_adds: List[Pod] = []
        for key, obj in pod_final.items():
            i = key_to_idx.pop(key, None)
            if i is not None:
                # replace-or-delete of a pod the campaign already tracks
                ex.delete_pod(i)
                rep.deleted += 1
            if obj is not None:
                pod_adds.append(Pod.from_dict(obj))
        new: List[int] = []
        if pod_adds:
            rt = ResourceTypes()
            rt.pods = pod_adds
            new = ex.admit_app(f"journal-{self.index}", rt, self.where)
            rep.pods_added += len(new)
        ex.run_scan(displaced + new + ex.pending_idxs(), rep)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def run_campaign(
    cluster: ResourceTypes,
    spec_or_steps,
    mode: Optional[str] = None,
    name: str = "",
) -> CampaignResult:
    """Execute a campaign against a cluster. ``spec_or_steps`` is a parsed
    :class:`CampaignSpec`, a typed step list, or a raw ``spec.steps`` list
    (the REST body shape). The baseline (step -1 semantics folded into
    step reports as index 0 of execution: the initial placement of the
    cluster's own pods) always runs first so every later step starts from
    a fully-placed state."""
    if isinstance(spec_or_steps, CampaignSpec):
        steps = spec_or_steps.steps
        name = name or spec_or_steps.name
        base = spec_or_steps.base_dir
    elif spec_or_steps and isinstance(spec_or_steps[0], Step):
        steps = list(spec_or_steps)
        base = ""
    else:
        steps = parse_steps(spec_or_steps)
        base = ""
    mode = mode or exec_mode()
    prev = _BASE_DIR[0]
    if base:
        _BASE_DIR[0] = base
    try:
        ex = _Executor(cluster, mode)
        reports: List[StepReport] = []

        baseline = StepReport(index=0, name="baseline", type="baseline")
        ex.run_scan(list(range(len(ex.pods))), baseline, count_as="rescheduled")
        baseline.rescheduled = 0  # the initial placement is not a reschedule
        ex.score(baseline)
        reports.append(baseline)

        for step in steps:
            rep = StepReport(index=len(reports), name=step.name, type=step.type_name)
            step.run(ex, rep)
            ex.score(rep)
            reports.append(rep)

        h = hashlib.blake2b(digest_size=16)
        for rep in reports:
            h.update(rep.fingerprint.encode())
        return CampaignResult(
            name=name or "campaign",
            mode=mode,
            steps=reports,
            fingerprint=h.hexdigest(),
            full_prepares=ex.full_prepares,
        )
    finally:
        _BASE_DIR[0] = prev


def _cluster_path(base: str, p: str, field: str) -> str:
    try:
        return validate.child_path(base, p, label=field)
    except ValueError as e:
        raise CampaignError(str(e), field="cluster") from e


def load_campaign_cluster(spec: CampaignSpec) -> ResourceTypes:
    """The cluster a file-based campaign runs against (``spec.cluster``:
    ``customConfig`` yaml dir or ``kubeConfig``)."""
    custom = spec.cluster.get("customConfig", "")
    kube = spec.cluster.get("kubeConfig", "")
    if custom:
        path = _cluster_path(spec.base_dir, custom, "spec.cluster.customConfig")
        return expand.load_cluster_from_dir(path)
    if kube:
        from ..server.snapshot import cluster_from_kubeconfig

        path = _cluster_path(spec.base_dir, kube, "spec.cluster.kubeConfig")
        return cluster_from_kubeconfig(path)
    raise CampaignError(
        "spec.cluster needs customConfig or kubeConfig (or run the campaign "
        "against a live server: simon campaign --url)",
        field="cluster",
    )
