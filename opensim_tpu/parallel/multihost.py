"""Multi-host execution over DCN.

The reference is a single-process CLI (SURVEY.md §2.3 — no collectives, no
multi-node execution). This framework's scale-out model:

- **intra-host / ICI**: scenario batches shard across local TPU cores via
  the one-axis mesh in ``scenarios.sweep`` (collectives ride ICI).
- **inter-host / DCN**: ``initialize()`` joins a ``jax.distributed`` job;
  ``global_mesh()`` then spans every process's devices, and the same sweep
  shards the scenario axis across hosts — XLA partitions the batch so each
  host scans its scenario shard locally and only the small per-scenario
  summaries (unscheduled counts, usage sums) cross DCN.

Typical launch (one process per host):
    JAX_COORDINATOR=host0:1234 python -m opensim_tpu apply -f cfg.yaml
with ``initialize()`` called from the planner when the env is present.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


_initialized = False


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join a multi-host jax.distributed job. Parameters default from the
    JAX_COORDINATOR / JAX_NUM_PROCESSES / JAX_PROCESS_ID env vars; returns
    False (no-op) when unset so single-host runs need nothing. Idempotent:
    the planner calls this on every run, and a library caller may already
    have joined the job before invoking the planner — a second call is a
    no-op (jax.distributed.initialize itself raises on reuse)."""
    global _initialized
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR", "")
    if not coordinator:
        return False
    if _initialized:
        return True
    try:
        # a library caller may have joined jax.distributed directly — honor
        # that instead of crashing on the double-initialize
        from jax._src.distributed import global_state as _gs

        if getattr(_gs, "client", None) is not None:
            _initialized = True
            return True
    except ImportError:
        pass
    num_processes = int(num_processes or os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = int(process_id if process_id is not None else os.environ.get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def global_mesh() -> Optional[Mesh]:
    """One-axis mesh over every device of every process: after
    ``initialize()``, ``jax.devices()`` spans all hosts, so the scenario
    mesh used by sweeps is automatically global."""
    from .scenarios import default_mesh

    return default_mesh()
