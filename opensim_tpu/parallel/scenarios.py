"""Scenario-parallel what-if evaluation.

The reference's capacity planner re-runs the whole simulation once per
candidate node count, interactively (``pkg/apply/apply.go:203-259``). Here a
*batch* of scenarios — node counts, drain plans — evaluates in one jitted,
sharded computation: every scenario shares the same EncodedCluster tensors
and differs only in its ``node_valid`` / ``pod_valid`` masks, so the whole
sweep is one ``vmap`` over masks, sharded across TPU cores over ICI with a
``jax.sharding.Mesh``. This is §2.3 of SURVEY.md: the distributed backend of
this framework is XLA collectives over the scenario axis, not NCCL/MPI.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..encoding.state import EncodedCluster, ScanState
from ..utils import envknobs
# the sweep bodies run UNDER tracing (vmapped inside the jitted sweeps):
# they call the raw jit entry, never the observed schedule_pods wrapper —
# the compile watch's host bookkeeping must stay outside the trace (OSL1601)
from ..engine.scheduler import _schedule_pods_jit as _schedule_pods_traced
from ..engine.scheduler import scan_unroll


class SweepResult(NamedTuple):
    unscheduled: jnp.ndarray  # [S] i32 — unscheduled pod count per scenario
    used: jnp.ndarray  # [S, N, R] f32 — final per-node usage
    chosen: jnp.ndarray  # [S, P] i32
    vg_used: jnp.ndarray  # [S] f32 — total VG bytes allocated


def _one_scenario(ec: EncodedCluster, st0: ScanState, tmpl_ids, forced, node_valid, pod_valid, features, config, unroll):  # opensim-lint: jit-region
    out = _schedule_pods_traced(
        ec._replace(node_valid=node_valid),
        st0,
        tmpl_ids,
        pod_valid,
        forced,
        features=features,
        config=config,
        unroll=unroll,
    )
    unscheduled = jnp.sum(pod_valid & (out.chosen < 0))
    vg_used = jnp.sum(
        jnp.where(node_valid[:, None], st0.vg_free - out.final_state.vg_free, 0.0)
    )
    return unscheduled.astype(jnp.int32), out.final_state.used, out.chosen, vg_used


@functools.partial(jax.jit, static_argnames=("features", "config", "unroll"))
def _sweep_impl(
    ec, st0, tmpl_ids, node_valid_masks, pod_valid_masks, forced_masks, features, config=None, unroll=1
):
    """Module-level jitted sweep so repeat invocations hit the jit cache
    (a fresh closure per call would retrace every time)."""
    return jax.vmap(
        lambda nv, pv, fm: _one_scenario(ec, st0, tmpl_ids, fm, nv, pv, features, config, unroll)
    )(node_valid_masks, pod_valid_masks, forced_masks)


def sweep_counts(
    prep, n_real: int, ks, config=None
) -> "tuple[SweepResult, np.ndarray]":
    """Candidate new-node count sweep directly over a prepared (possibly
    cached/delta-derived) arena: scenario s enables the first ``n_real +
    ks[s]`` nodes of the prepared node axis, and DaemonSet pods pinned to
    disabled candidate nodes are masked out of that scenario (a smaller
    expansion would never have created them). This is the mask-flip
    materialization of the planner's sweep — the encoded tensors are built
    once (or delta re-encoded from a cached base) and every probe is just a
    pair of boolean masks. Returns (SweepResult, node_valid_masks)."""
    N = int(np.asarray(prep.ec_np.node_valid).shape[0])
    P = len(prep.ordered)
    S = len(ks)
    node_valid = np.zeros((S, N), dtype=bool)
    for s, k in enumerate(ks):
        node_valid[s, : n_real + k] = True
    pod_valid = np.ones((S, P), dtype=bool)
    for p, target in enumerate(prep.ds_target):
        if target >= n_real:  # DaemonSet pod pinned to a candidate node
            pod_valid[:, p] = node_valid[:, target]
    return sweep_auto(prep, node_valid, pod_valid, config=config), node_valid


def sweep_auto(
    prep,
    node_valid_masks: np.ndarray,
    pod_valid_masks: np.ndarray,
    forced_masks: Optional[np.ndarray] = None,
    config=None,
) -> SweepResult:
    """Route a scenario sweep: on a single device, run ALL scenarios in one
    batched Pallas dispatch (vmap prepends a scenario axis to the kernel
    grid — no per-scenario dispatch overhead); on a multi-device mesh,
    shard the vmapped XLA scan across devices instead."""
    S = node_valid_masks.shape[0]
    if forced_masks is None:
        forced_masks = np.broadcast_to(prep.forced, (S, len(prep.forced)))
    if config is not None:
        # multi-profile config: same routing as simulate() — unknown-profile
        # pods are masked out of every scenario (they can never schedule, so
        # capacity sweeps must not count them). DIFFERING profiles used to
        # raise here (the NOTES.md rough edge); they now route through
        # per-segment scans sharing the scheduling carry (ISSUE 8
        # satellite), exactly like simulate()'s segmented path — so the
        # request-axis batcher and the planner can sweep mixed-profile
        # streams.
        from ..engine.schedconfig import DEFAULT_CONFIG, resolve_profile_segments

        segs, invalid = resolve_profile_segments(
            config, prep.ordered, prep.meta.resource_names, forced=prep.forced
        )
        if invalid:
            pod_valid_masks = np.array(pod_valid_masks, copy=True)
            for i in invalid:
                pod_valid_masks[:, i] = False
        distinct = {c for c, _, _ in segs if c is not None and c != DEFAULT_CONFIG}
        if len(segs) > 1 and distinct:
            return sweep_segmented(
                prep, segs, node_valid_masks, pod_valid_masks,
                np.asarray(forced_masks, dtype=bool),
            )
        config = distinct.pop() if distinct else None
    from ..engine import nativepath

    if len(jax.devices()) == 1 and nativepath.applicable(prep, config):
        # accelerator-less (or --backend native): sequential C++ scans —
        # no XLA scan compile; the incremental template cache makes each
        # scenario ms-scale on small configs (VERDICT r3 weak #4)
        unscheduled, used, chosen, vg_used = nativepath.sweep(
            prep, node_valid_masks, pod_valid_masks, forced_masks, config=config
        )
        return SweepResult(
            unscheduled=jnp.asarray(unscheduled), used=jnp.asarray(used),
            chosen=jnp.asarray(chosen), vg_used=jnp.asarray(vg_used),
        )
    if (
        len(jax.devices()) == 1
        and config is None
        and (
            jax.default_backend() == "tpu"
            or envknobs.raw("OPENSIM_FASTPATH") == "interpret"
        )
    ):
        from ..engine import fastpath

        miss = fastpath.why_not(prep)
        if miss is None:
            try:
                unscheduled, used, chosen, vg_used = fastpath.sweep(
                    prep, node_valid_masks, pod_valid_masks, forced_masks
                )
                return SweepResult(
                    unscheduled=unscheduled, used=used, chosen=chosen, vg_used=vg_used
                )
            except Exception as e:
                # a Mosaic compile failure on the batched kernel must not
                # kill the sweep — the XLA path below computes the same —
                # unless --backend tpu explicitly demanded the TPU engine
                import logging

                if envknobs.raw("OPENSIM_FASTPATH") == "interpret":
                    raise  # test/CI mode: fail loudly, don't validate the fallback
                if envknobs.raw("OPENSIM_REQUIRE_TPU") == "1":
                    raise RuntimeError(
                        "--backend tpu: the batched megakernel sweep failed "
                        f"({type(e).__name__}: {e}); refusing to silently "
                        "fall back to the XLA sweep"
                    ) from e
                logging.getLogger("opensim_tpu").warning(
                    "megakernel sweep failed (%s: %s); falling back to the "
                    "XLA sweep", type(e).__name__, e,
                )
        else:
            import logging

            logging.getLogger("opensim_tpu").info(
                "megakernel sweep envelope miss: %s", miss
            )
    return sweep(
        prep.ec,
        prep.st0,
        prep.tmpl_ids,
        prep.forced,
        node_valid_masks,
        pod_valid_masks,
        mesh=default_mesh(),
        features=prep.features,
        forced_masks=np.asarray(forced_masks),
        config=config,
    )


@functools.partial(jax.jit, static_argnames=("features", "config", "unroll"))
def _sweep_segment_impl(
    ec, st_batch, tmpl_ids, node_valid_masks, pod_valid_masks, forced_masks,
    features, config=None, unroll=1,
):
    """One segment of a segmented sweep: vmap over scenarios with a
    PER-SCENARIO carry (st_batch has a leading scenario axis — segment k's
    final states seed segment k+1)."""

    def one(st, nv, pv, fm):
        out = _schedule_pods_traced(
            ec._replace(node_valid=nv), st, tmpl_ids, pv, fm,
            features=features, config=config, unroll=unroll,
        )
        return out.chosen, out.final_state

    return jax.vmap(one)(st_batch, node_valid_masks, pod_valid_masks, forced_masks)


def sweep_segmented(
    prep,
    segments,
    node_valid_masks: np.ndarray,
    pod_valid_masks: np.ndarray,
    forced_masks: np.ndarray,
) -> SweepResult:
    """Scenario sweep over a MIXED-PROFILE stream: consecutive scans per
    contiguous same-profile segment, sharing each scenario's scheduling
    carry — ``simulate()``'s segmented path (``utils.go:304-381``) lifted
    to the scenario axis. Out-of-segment pods are mask-invalid per scan, so
    binds happen in exact stream order and placements per scenario equal a
    solo segmented simulate of that scenario (gated by
    tests/test_parallel.py). Routing matches ``sweep_auto``: sequential C++
    scans on accelerator-less hosts (chaining ``st0`` between segments),
    the vmapped XLA scan with a batched carry otherwise."""
    from ..engine import nativepath
    from ..engine.schedconfig import DEFAULT_CONFIG

    S = node_valid_masks.shape[0]
    P = len(prep.ordered)
    segments = [
        (None if c == DEFAULT_CONFIG else c, lo, hi) for c, lo, hi in segments
    ]
    chosen = np.full((S, P), -1, dtype=np.int32)
    use_native = len(jax.devices()) == 1 and all(
        nativepath.applicable(prep, cfg) for cfg, _, _ in segments
    )
    vg0 = np.asarray(prep.st0.vg_free)
    nv_np = np.asarray(node_valid_masks, dtype=bool)
    if use_native:
        used = np.zeros((S,) + np.asarray(prep.st0.used).shape, np.float32)
        vg_used = np.zeros((S,), np.float32)
        for s in range(S):
            st = prep.st0
            pv_s = np.asarray(pod_valid_masks[s], dtype=bool)
            for cfg, lo, hi in segments:
                seg_valid = np.zeros((P,), dtype=bool)
                seg_valid[lo:hi] = pv_s[lo:hi]
                out = nativepath.schedule(
                    prep, seg_valid, config=cfg, node_valid=nv_np[s],
                    forced=np.asarray(forced_masks[s], bool), st0=st,
                )
                chosen[s, lo:hi] = np.asarray(out.chosen)[lo:hi]
                st = out.final_state
            used[s] = np.asarray(st.used)
            vg_used[s] = float(
                ((vg0 - np.asarray(st.vg_free)) * nv_np[s][:, None]).sum()
            )
        unscheduled = (
            (chosen < 0) & np.asarray(pod_valid_masks, bool)
        ).sum(axis=1).astype(np.int32)
        return SweepResult(
            unscheduled=jnp.asarray(unscheduled), used=jnp.asarray(used),
            chosen=jnp.asarray(chosen), vg_used=jnp.asarray(vg_used),
        )
    # XLA path: batched carry across segments (each segment is one vmapped
    # dispatch; S scenarios advance in lockstep through the profile chain)
    st_batch = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(jnp.asarray(a), (S,) + jnp.asarray(a).shape),
        prep.st0,
    )
    nv_dev = jnp.asarray(nv_np)
    fm_dev = jnp.asarray(np.asarray(forced_masks, dtype=bool))
    final = None
    for cfg, lo, hi in segments:
        seg = np.zeros((S, P), dtype=bool)
        seg[:, lo:hi] = np.asarray(pod_valid_masks, bool)[:, lo:hi]
        from ..obs.profile import observed_jit_call

        seg_chosen, st_batch = observed_jit_call(
            "sweep_segment",
            _sweep_segment_impl,
            args=(
                prep.ec, st_batch, jnp.asarray(prep.tmpl_ids), nv_dev,
                jnp.asarray(seg), fm_dev,
            ),
            static={"features": prep.features, "config": cfg, "unroll": scan_unroll()},
        )
        chosen[:, lo:hi] = np.asarray(seg_chosen)[:, lo:hi]
        final = st_batch
    unscheduled = (
        (chosen < 0) & np.asarray(pod_valid_masks, bool)
    ).sum(axis=1).astype(np.int32)
    used = np.asarray(final.used)
    vg_used = (
        (vg0[None] - np.asarray(final.vg_free)) * nv_np[:, :, None]
    ).sum(axis=(1, 2)).astype(np.float32)
    return SweepResult(
        unscheduled=jnp.asarray(unscheduled), used=jnp.asarray(used),
        chosen=jnp.asarray(chosen), vg_used=jnp.asarray(vg_used),
    )


def sweep(
    ec: EncodedCluster,
    st0: ScanState,
    tmpl_ids: np.ndarray,
    forced: np.ndarray,
    node_valid_masks: np.ndarray,  # [S, N]
    pod_valid_masks: np.ndarray,  # [S, P]
    mesh: Optional[Mesh] = None,
    features=None,
    forced_masks: Optional[np.ndarray] = None,  # [S, P] — per-scenario override
    config=None,
) -> SweepResult:
    """Evaluate S scenarios in one compiled computation. With a mesh, the
    scenario axis is sharded across devices (pad S to a device multiple).
    `forced_masks` lets each scenario choose which pods stay pre-bound
    (defragmentation: a drained node's pods become schedulable again)."""
    from ..ops.kernels import ALL_FEATURES

    features = features or ALL_FEATURES
    S = node_valid_masks.shape[0]
    if forced_masks is None:
        forced_masks = np.broadcast_to(np.asarray(forced, dtype=bool), (S, len(forced))).copy()
    arrays = (node_valid_masks, pod_valid_masks, forced_masks)
    if mesh is not None:
        n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        pad = (-S) % n_dev
        if pad:
            arrays = tuple(np.concatenate([a, a[-1:].repeat(pad, 0)]) for a in arrays)
        shard = NamedSharding(mesh, P(mesh.axis_names[0]))
        if jax.process_count() > 1:
            # DCN path: the mesh spans processes, so scenario shards must be
            # assembled from each host's addressable slice (every host holds
            # the same full mask arrays — the planner builds them
            # deterministically) and the small per-scenario summaries are
            # gathered back to every host afterwards.
            arrays = tuple(
                jax.make_array_from_callback(
                    a.shape, shard, lambda idx, a=a: np.asarray(a)[idx]
                )
                for a in arrays
            )
            rep = NamedSharding(mesh, P())

            def _replicate(a):
                a = np.asarray(a)
                return jax.make_array_from_callback(a.shape, rep, lambda idx, a=a: a[idx])

            out = _sweep_impl(
                type(ec)(*[_replicate(x) for x in ec]),
                type(st0)(*[_replicate(x) for x in st0]),
                _replicate(np.asarray(tmpl_ids)),
                *arrays,
                features=features,
                config=config,
                unroll=scan_unroll(),
            )
            from jax.experimental import multihost_utils

            out = multihost_utils.process_allgather(out, tiled=True)
        else:
            arrays = tuple(jax.device_put(jnp.asarray(a), shard) for a in arrays)
            out = _sweep_impl(
                ec, st0, jnp.asarray(tmpl_ids), *arrays,
                features=features, config=config, unroll=scan_unroll(),
            )
        out = jax.tree_util.tree_map(lambda a: a[:S], out)
    else:
        from ..obs.profile import observed_jit_call

        out = observed_jit_call(
            "sweep",
            _sweep_impl,
            args=(ec, st0, jnp.asarray(tmpl_ids), *(jnp.asarray(a) for a in arrays)),
            static={"features": features, "config": config, "unroll": scan_unroll()},
        )
    return SweepResult(*out)


def default_mesh() -> Optional[Mesh]:
    """One-axis mesh over all local devices (scenario data parallelism)."""
    devices = jax.devices()
    if len(devices) <= 1:
        return None
    return Mesh(np.array(devices), ("s",))
