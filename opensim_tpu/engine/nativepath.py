"""Native-engine selection and marshalling.

``applicable()`` decides whether a prepared simulation should run on the
C++ scan engine (``opensim_tpu/native``); ``schedule()`` marshals the
encoded cluster into its flat-buffer ABI and returns a full
``ScheduleOutput`` — including a completely populated final ``ScanState``
and exact per-pod failure attribution, so no XLA re-scan is ever needed.

Selection policy: the Pallas megakernel owns the TPU; the native engine
owns hosts without an accelerator (the reference itself is a CPU program —
its engine is the vendored Go scheduler, SURVEY.md §2.2). On a TPU backend
the native engine only runs when OPENSIM_NATIVE=1 explicitly asks for it.
Unlike the megakernel it has no feature envelope: every workload the XLA
scan handles (including --default-scheduler-config weight/disable merges)
runs natively; only out-of-tree ``extra_plugins`` (arbitrary jittable
callables) force the XLA path.
"""

from __future__ import annotations

import functools

import numpy as np

from ..encoding import vocab as V
from ..encoding.state import ScanState
from ..ops import kernels
from ..utils import envknobs
from .schedconfig import DEFAULT_CONFIG


@functools.lru_cache(maxsize=None)
def _warn_native_unavailable() -> None:
    import logging

    from .. import native

    logging.getLogger("opensim_tpu").warning(
        "OPENSIM_NATIVE=1 but the native engine is unavailable "
        "(falling back to the XLA scan): %s",
        native.load_error() or "engine not built",
    )


def applicable(prep, config=None, extra_plugins: tuple = ()) -> bool:
    return why_not(prep, config, extra_plugins) is None


def why_not(prep, config=None, extra_plugins: tuple = (), tie_seed=None):
    """Selection check for the C++ engine: returns None when it should run,
    else a one-line reason (engine attribution — VERDICT r4 #3). tie_seed
    is accepted: the engine implements the seeded sampled tie-break."""
    if extra_plugins:
        return "out-of-tree extra_plugins are jittable callables (XLA scan only)"
    if config is not None and getattr(config, "fit_ignored_cols", ()):
        # NodeResourcesFitArgs ignored columns are an XLA-scan feature; the
        # C++ fit loop has no per-column skip (rare config — not worth ABI)
        return "NodeResourcesFitArgs ignoredResources need the XLA scan's per-column skip"
    if envknobs.raw("OPENSIM_DISABLE_NATIVE"):
        return "disabled by --backend xla (OPENSIM_DISABLE_NATIVE)"
    from .. import native

    if envknobs.raw("OPENSIM_NATIVE") == "1":
        if not native.available():
            _warn_native_unavailable()
            return f"engine not built: {native.load_error() or 'unknown'}"
        return None
    import jax

    if jax.default_backend() == "tpu":
        return "TPU backend present (the megakernel/XLA scan own the accelerator)"
    if not native.available():
        return f"engine not built: {native.load_error() or 'unknown'}"
    return None


def _stat_np(prep, config, node_valid=None):
    """Static tables via the numpy mirror (kernels.precompute_static_np):
    bitwise-equal to the jitted tables with ZERO XLA compiles, keeping
    `--backend native` ms-scale cold. `node_valid` overrides the encoder's
    mask — only the valid-set-dependent fold (static_pass, static_fail,
    spread weights) recomputes per scenario; the expensive per-template
    core is computed once per Prepared and cached on it."""
    ec = prep.ec_np
    core = getattr(prep, "_np_core", None)
    if core is None:
        core = kernels.precompute_core_np(ec)
        try:
            prep._np_core = core
        except AttributeError:
            pass
    if node_valid is not None:
        # scenario sweeps: every mask is distinct — caching the [U, N]-scale
        # fold per mask would trade unbounded memory for nothing
        ec = ec._replace(node_valid=np.ascontiguousarray(node_valid, dtype=bool))
        return kernels.precompute_static_np(ec, config, core=core)
    # per-config fold cache: segmented multi-profile runs revisit the same
    # few configs once per segment; identical folds are reused
    cache = getattr(prep, "_np_stat_cache", None)
    if cache is None:
        cache = {}
        try:
            prep._np_stat_cache = cache
        except AttributeError:
            pass
    stat = cache.get(config)
    if stat is None:
        stat = cache[config] = kernels.precompute_static_np(ec, config, core=core)
    return stat


def schedule(prep, pod_valid: np.ndarray, config=None, node_valid=None, forced=None,
             tie_seed=None, st0=None, explain=False):
    """Run the whole pod stream through the C++ engine. Returns a
    ``ScheduleOutput`` (numpy arrays throughout). `node_valid`/`forced`
    override the prepared masks (scenario sweeps). `tie_seed` switches
    selection to seeded uniform sampling over the score maxima (the
    reference's selectHost reservoir distribution). `st0` overrides the
    initial carry (segmented multi-profile runs chain scans). `explain`
    (decision audit, ISSUE 7) forces the generic path, fills the per-pod
    fail rows for every step, and accumulates the 11-slot per-filter
    reject totals in-engine (ScanArgs.filter_rejects, abi v4)."""
    from .. import native
    from ..resilience import faults
    from .scheduler import ScheduleOutput

    _LAST_PROFILE[0] = None  # never inherit a previous run's timings
    # runtime-failure injection (chaos suite): a fault here stands in for
    # ABI drift / a .so crash; simulate()'s ladder demotes to the XLA scan
    faults.fault_point("engine.compile")

    cfg = config or DEFAULT_CONFIG
    ec = prep.ec_np
    if st0 is None:
        st0 = prep.st0
    feat = prep.features
    stat = _stat_np(prep, config, node_valid=node_valid)
    node_valid_arr = ec.node_valid if node_valid is None else node_valid
    forced_arr = prep.forced if forced is None else forced

    def f32(x):
        return np.ascontiguousarray(x, dtype=np.float32)

    def i32(x):
        return np.ascontiguousarray(x, dtype=np.int32)

    def u8(x):
        return np.ascontiguousarray(x, dtype=np.uint8)

    N, R = ec.alloc.shape
    U = ec.req.shape[0]
    P = len(prep.tmpl_ids)
    Gd = ec.node_gpu_mem.shape[1]

    state = {
        "used": f32(np.array(st0.used, copy=True)),
        "port_used": f32(np.array(st0.port_used, copy=True)),
        "dom_sel": f32(np.array(st0.dom_sel, copy=True)),
        "dom_anti": f32(np.array(st0.dom_anti, copy=True)),
        "dom_prefw": f32(np.array(st0.dom_prefw, copy=True)),
        "gpu_free": f32(np.array(st0.gpu_free, copy=True)),
        "vg_free": f32(np.array(st0.vg_free, copy=True)),
        "dev_free": f32(np.array(st0.dev_free, copy=True)),
    }
    outputs = {
        "chosen": np.zeros(P, np.int32),
        "fail_counts": np.zeros((P, kernels.NUM_FILTERS - kernels.F_PORTS), np.int32),
        "insufficient": np.zeros((P, R), np.int32),
        "gpu_take": np.zeros((P, Gd), np.float32),
        # path attribution + OPENSIM_NATIVE_PROFILE phase timings
        "path_counts": np.zeros(3, np.int32),
        "profile_out": np.zeros(12, np.float64),
        # decision audit (explain=1): per-filter reject totals, kernel
        # filter-index order (always marshalled; only written under explain)
        "filter_rejects": np.zeros(kernels.NUM_FILTERS, np.int64),
        # incremental-carry attribution (abi v5): why the envelope
        # disengaged (_BAIL_REASONS order) + which carry classes served
        # incremental steps (_CARRY_CLASSES order)
        "bail_out": np.zeros(len(_BAIL_REASONS), np.int64),
        "class_steps": np.zeros(len(_CARRY_CLASSES), np.int64),
    }

    dims = {
        "N": N, "R": R, "U": U, "P": P,
        "Tk": ec.node_domain.shape[1], "Dp1": ec.domain_topo.shape[0],
        "A": ec.matches_sel.shape[1], "Hp": ec.ports.shape[1],
        "Hports": st0.port_used.shape[1], "Cs": ec.spr_topo.shape[1],
        "Ti": ec.at_sel.shape[1], "Tn": ec.an_sel.shape[1],
        "Tpp": ec.pt_sel.shape[1], "G": ec.anti_g_sel.shape[0],
        "Gp": ec.prefg_sel.shape[0], "Gd": Gd,
        "Vg": ec.node_vg_cap.shape[1], "Dv": ec.node_dev_cap.shape[1],
        "Mv": ec.dev_req_sizes.shape[2],
        "res_cpu": V.RES_CPU, "res_mem": V.RES_MEMORY,
        "res_gc": kernels.gc_row_of(ec),
        "ft_ports": feat.ports, "ft_gpu": feat.gpu, "ft_local": feat.local,
        "ft_interpod": feat.interpod, "ft_prefg": feat.prefg,
        "ft_spread_hard": feat.spread_hard, "ft_spread_soft": feat.spread_soft,
        "ft_pref_na": feat.pref_node_affinity,
        "ft_pref_taints": feat.prefer_taints,
        "ft_prefer_avoid": feat.prefer_avoid,
        "ft_gc_dyn": feat.gc_dyn,
        "cf_ports": cfg.f_ports, "cf_fit": cfg.f_fit, "cf_spread": cfg.f_spread,
        "cf_interpod": cfg.f_interpod, "cf_gpu": cfg.f_gpu, "cf_local": cfg.f_local,
        "tie_sample": tie_seed is not None, "tie_seed": tie_seed or 0,
        "explain": bool(explain),
    }
    weights = {k: getattr(cfg, k) for k in (
        "w_balanced", "w_least", "w_node_affinity", "w_taint_toleration",
        "w_interpod", "w_spread", "w_prefer_avoid", "w_simon", "w_gpu_share",
        "w_local",
    )}
    buffers = {
        "node_valid": u8(node_valid_arr), "alloc": f32(ec.alloc),
        "node_domain": i32(ec.node_domain), "domain_topo": i32(ec.domain_topo),
        "req": f32(ec.req), "ports": i32(ec.ports),
        "port_conflict": u8(ec.port_conflict),
        "spr_topo": i32(ec.spr_topo), "spr_sel": i32(ec.spr_sel),
        "spr_skew": i32(ec.spr_skew), "spr_hard": u8(ec.spr_hard),
        "at_sel": i32(ec.at_sel), "at_topo": i32(ec.at_topo),
        "an_sel": i32(ec.an_sel), "an_topo": i32(ec.an_topo),
        "pt_sel": i32(ec.pt_sel), "pt_topo": i32(ec.pt_topo), "pt_w": f32(ec.pt_w),
        "matches_sel": u8(ec.matches_sel), "anti_g": u8(ec.anti_g),
        "anti_g_sel": i32(ec.anti_g_sel), "anti_g_topo": i32(ec.anti_g_topo),
        "prefg_w": f32(ec.prefg_w), "prefg_sel": i32(ec.prefg_sel),
        "prefg_topo": i32(ec.prefg_topo),
        "gpu_mem": f32(ec.gpu_mem), "gpu_count": i32(ec.gpu_count),
        "node_gpu_cap": f32(ec.node_gpu_mem),
        "avoid_score": f32(ec.avoid_score),
        "lvm_req": f32(ec.lvm_req), "dev_req": f32(ec.dev_req),
        "dev_req_count": i32(ec.dev_req_count),
        "dev_req_sizes": f32(ec.dev_req_sizes),
        "node_vg_cap": f32(ec.node_vg_cap), "node_dev_cap": f32(ec.node_dev_cap),
        "node_dev_media": i32(ec.node_dev_media), "pin": i32(ec.pin),
        "static_pass": u8(stat.static_pass), "aff_mask": u8(stat.aff_mask),
        "na_raw": f32(stat.na_raw), "tt_raw": f32(stat.tt_raw),
        "share_raw": f32(stat.share_raw), "spread_weight": f32(stat.spread_weight),
        "tmpl_ids": i32(prep.tmpl_ids), "forced": u8(forced_arr),
        "pod_valid": u8(pod_valid),
        "static_fail": i32(stat.static_fail),
        **state,
        **outputs,
    }
    native.run_scan(dims, weights, buffers)

    stats = _path_stats(outputs["path_counts"], outputs["profile_out"],
                        outputs["bail_out"], outputs["class_steps"])
    _attach_profile_spans(stats, P)
    return ScheduleOutput(
        chosen=outputs["chosen"],
        fail_counts=outputs["fail_counts"],
        insufficient=outputs["insufficient"],
        gpu_take=outputs["gpu_take"],
        static_fail=np.asarray(stat.static_fail),
        final_state=ScanState(**state),
        native_stats=stats,
        filter_rejects=outputs["filter_rejects"] if explain else None,
    )


def _attach_profile_spans(stats: dict, n_pods: int) -> None:
    """OPENSIM_NATIVE_PROFILE phase timings as child spans of the ambient
    engine span (ISSUE 5): the C++ scan's internal time lands in the same
    request tree as host prep. The .so measures durations, not timestamps,
    so the children are laid out sequentially from the span's start.

    Only attaches when the ambient span IS an engine span: sweep callers
    (``nativepath.sweep``, one schedule() per scenario) run with the trace
    root ambient, and stamping hundreds of per-scenario stats/children onto
    the root would mis-attribute the whole run to the last scenario."""
    from ..obs import trace as obs

    cur = obs.current_span()
    if not getattr(cur, "name", "").startswith("engine."):
        return
    cur.set(
        native_path=stats["path"],
        steps_incremental=stats["steps"]["incremental"],
        steps_generic=stats["steps"]["generic"],
        pods=int(n_pods),
    )
    for phase, rec in (stats.get("profile") or {}).items():
        cur.child_from_seconds(
            f"native.{phase}", rec["seconds"], steps=rec["steps"]
        )


_PROFILE_PHASES = ("delta", "full_eval", "argmax", "bind", "fail", "generic")

# scan_engine.cc `enum Bail` slot order (abi v5): the three whole-scan
# envelope gates, then the per-delta bail classes. A nonzero count names
# exactly which gate closed the incremental path for a workload.
_BAIL_REASONS = (
    "force_generic", "explain", "cs",
    "ports", "gpu", "local", "gc_dyn", "fit", "spread", "interpod", "pending",
)

# ScanArgs.class_steps slot order: incremental steps served with each
# resource-class carry active (score = dynamic share and/or local score)
_CARRY_CLASSES = ("ports", "gpu", "local", "score")

# most recent scan's per-phase timings (OPENSIM_NATIVE_PROFILE only) — read
# by bench.py to put a structured `native_profile` field on its JSON line.
# Cleared at the start of every schedule() call so a run that never reached
# the C++ engine can't inherit a previous run's numbers; a segmented
# multi-profile run leaves the LAST segment's scan here.
_LAST_PROFILE: list = [None]


def last_profile():
    """Per-phase {seconds, steps} of the most recent C++ engine scan in
    this process, or None when OPENSIM_NATIVE_PROFILE was not set or no
    native scan has run since the last schedule() attempt."""
    return _LAST_PROFILE[0]


def _path_stats(path_counts: np.ndarray, profile_out: np.ndarray,
                bail_out: np.ndarray = None, class_steps: np.ndarray = None) -> dict:
    """Engine path attribution (ISSUE 4 satellite: a silent incremental-cache
    disengage must be visible): which evaluation path served the scheduled
    steps, plus the per-phase OPENSIM_NATIVE_PROFILE timings when enabled.
    abi v5 adds *why* attribution: nonzero bail-reason counts under
    ``steps["bails"]`` and per-carry-class engagement under
    ``steps["classes"]`` (additive keys — rest._Metrics.record() only reads
    the incremental/generic pair, so older consumers are unaffected)."""
    inc, gen, full = (int(x) for x in path_counts)
    if inc and gen:
        path = "mixed"
    elif inc:
        path = "incremental"
    elif gen:
        path = "generic"
    else:
        path = "none"  # every pod forced/invalid: no scheduling step ran
    stats = {
        "path": path,
        "steps": {"incremental": inc, "generic": gen, "full_evals": full},
    }
    if bail_out is not None:
        bails = {_BAIL_REASONS[k]: int(v) for k, v in enumerate(bail_out) if v}
        if bails:
            stats["steps"]["bails"] = bails
    if class_steps is not None:
        classes = {_CARRY_CLASSES[k]: int(v) for k, v in enumerate(class_steps) if v}
        if classes:
            stats["steps"]["classes"] = classes
    if profile_out.any():
        stats["profile"] = {
            _PROFILE_PHASES[k]: {
                "seconds": round(float(profile_out[2 * k]), 6),
                "steps": int(profile_out[2 * k + 1]),
            }
            for k in range(len(_PROFILE_PHASES))
            if profile_out[2 * k + 1] > 0
        }
        _LAST_PROFILE[0] = stats["profile"]
    return stats


def sweep(prep, node_valid_masks, pod_valid_masks, forced_masks, config=None):
    """Scenario sweep on the C++ engine: one sequential scan per scenario
    — the accelerator-less counterpart of the batched Pallas/XLA sweeps, so
    `simon apply`/`simon defrag` under --backend native never touch an XLA
    scan compile (the reference's capacity loop is ms-scale serial re-runs,
    apply.go:203-259). Returns (unscheduled [S], used [S,N,R], chosen
    [S,P], vg_used [S]) matching parallel.scenarios.SweepResult."""
    S = node_valid_masks.shape[0]
    vg0 = np.asarray(prep.st0.vg_free)
    unscheduled = np.zeros((S,), np.int32)
    used, chosen, vg_used = [], [], np.zeros((S,), np.float32)
    for s in range(S):
        nv = np.asarray(node_valid_masks[s], bool)
        pv = np.asarray(pod_valid_masks[s], bool)
        out = schedule(
            prep, pv, config=config, node_valid=nv,
            forced=np.asarray(forced_masks[s], bool),
        )
        ch = np.asarray(out.chosen)
        chosen.append(ch)
        unscheduled[s] = int((pv & (ch < 0)).sum())
        used.append(np.asarray(out.final_state.used))
        vg_used[s] = float(
            ((vg0 - np.asarray(out.final_state.vg_free)) * nv[:, None]).sum()
        )
    return unscheduled, np.stack(used), np.stack(chosen), vg_used
