"""Scheduler configuration — the KubeSchedulerConfiguration subset.

The reference merges an optional scheduler config file over its default
profile (``InitKubeSchedulerConfiguration`` + ``GetAndSetSchedulerConfig``,
``pkg/simulator/utils.go:277-381``). Here the same file adjusts score-plugin
weights and disables filter/score plugins; the result is a hashable
``SchedulerConfig`` passed statically into the jitted scan, so each distinct
config compiles its own specialized pipeline.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

# kube plugin names → kernel slots
SCORE_PLUGINS = {
    "NodeResourcesBalancedAllocation": "balanced",
    "NodeResourcesLeastAllocated": "least",
    "NodeAffinity": "node_affinity",
    "TaintToleration": "taint_toleration",
    "InterPodAffinity": "interpod",
    "PodTopologySpread": "spread",
    "Simon": "simon",
    "Open-Gpu-Share": "gpu_share",
    "Open-Local": "local",
    "NodePreferAvoidPods": "prefer_avoid",
    # present in the default profile but structurally zero in a simulation
    # (nodes carry no images)
    "ImageLocality": None,
}

FILTER_PLUGINS = {
    "NodeUnschedulable": "unschedulable",
    "NodeName": "node_name",
    "TaintToleration": "taints",
    "NodeAffinity": "node_affinity",
    "NodePorts": "ports",
    "NodeResourcesFit": "fit",
    "PodTopologySpread": "spread",
    "InterPodAffinity": "interpod",
    "Open-Gpu-Share": "gpu",
    "Open-Local": "local",
}


class SchedulerConfig(NamedTuple):
    """Score weights (0 disables a score plugin) and filter disables.
    Defaults mirror algorithmprovider/registry.go:119-132 plus the three
    simulator plugins at weight 1."""

    w_balanced: float = 1.0
    w_least: float = 1.0
    w_node_affinity: float = 1.0
    w_taint_toleration: float = 1.0
    w_interpod: float = 1.0
    w_spread: float = 2.0
    w_prefer_avoid: float = 10000.0
    w_simon: float = 1.0
    w_gpu_share: float = 1.0
    w_local: float = 1.0
    f_taints: bool = True
    f_node_affinity: bool = True
    f_ports: bool = True
    f_fit: bool = True
    f_spread: bool = True
    f_interpod: bool = True
    f_gpu: bool = True
    f_local: bool = True
    f_unschedulable: bool = True


DEFAULT_CONFIG = SchedulerConfig()


def load_scheduler_config(path: str) -> SchedulerConfig:
    """Parse a KubeSchedulerConfiguration yaml and apply profile[0]'s
    score/filter plugin overrides over the defaults."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if doc.get("kind") not in ("KubeSchedulerConfiguration", None):
        raise ValueError(f"{path}: not a KubeSchedulerConfiguration")
    profiles = doc.get("profiles") or []
    if not profiles:
        return DEFAULT_CONFIG
    plugins = profiles[0].get("plugins") or {}
    cfg = DEFAULT_CONFIG._asdict()

    # kube merge semantics (vendored mergePluginSets): disabled entries
    # filter the defaults FIRST, then user-enabled entries are appended —
    # so `disabled: "*"` + `enabled: [X]` leaves only X.
    score = plugins.get("score") or {}
    for entry in score.get("disabled") or []:
        name = str(entry.get("name", ""))
        if name == "*":
            for k in list(cfg):
                if k.startswith("w_"):
                    cfg[k] = 0.0
            continue
        slot = SCORE_PLUGINS.get(name)
        if slot:
            cfg[f"w_{slot}"] = 0.0
    for entry in score.get("enabled") or []:
        slot = SCORE_PLUGINS.get(str(entry.get("name", "")))
        if slot:
            cfg[f"w_{slot}"] = float(entry.get("weight", 1) or 1)


    filt = plugins.get("filter") or {}
    for entry in filt.get("disabled") or []:
        name = str(entry.get("name", ""))
        if name == "*":
            for k in list(cfg):
                if k.startswith("f_"):
                    cfg[k] = False
            continue
        slot = FILTER_PLUGINS.get(name)
        if slot and slot != "node_name":
            cfg[f"f_{slot}"] = False
    return SchedulerConfig(**cfg)
