"""Scheduler configuration — KubeSchedulerConfiguration support.

The reference loads an optional scheduler-config file through the kube
scheduler's own options machinery (``GetAndSetSchedulerConfig`` +
``InitKubeSchedulerConfiguration``, ``pkg/simulator/utils.go:277-381``),
which accepts the full v1beta1 surface: multiple profiles (pods select one
via ``spec.schedulerName``), per-plugin ``pluginConfig`` args, and plugin
enable/disable sets per extension point. Here the same file parses into one
``SchedulerConfig`` per profile; ``resolve_profiles`` routes the pod stream
(all pods referencing one effective config — the reference's own usage, as
``MakeValidPod`` defaults every pod to ``default-scheduler``) and the result
is a hashable static argument to the jitted scan.

What maps is implemented; what would silently change semantics fails
LOUDLY naming the field (the policy VERDICT r3 #7 asks for):

- score/filter ``enabled``/``disabled`` (incl. ``"*"``) with weights — full
  kube merge semantics per profile;
- ``NodeResourcesFitArgs.ignoredResources`` / ``ignoredResourceGroups`` —
  the fit filter skips those resource columns;
- ``InterPodAffinityArgs.hardPodAffinityWeight`` — accepted at the default
  (1), rejected otherwise (the weight is encoded at template-build time);
- args that cannot change a simulation's outcome in either implementation
  (``DefaultPreemption``, volume plugins — vacuous, see PARITY.md) are
  accepted;
- everything else — unknown plugins, unknown extension points,
  ``percentageOfNodesToScore`` ≠ 100 (the reference forces 100,
  utils.go:370), outcome-changing args like
  ``PodTopologySpreadArgs.defaultConstraints`` — raises ``ValueError``
  naming the offender.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

# kube plugin names → kernel slots
SCORE_PLUGINS = {
    "NodeResourcesBalancedAllocation": "balanced",
    "NodeResourcesLeastAllocated": "least",
    "NodeAffinity": "node_affinity",
    "TaintToleration": "taint_toleration",
    "InterPodAffinity": "interpod",
    "PodTopologySpread": "spread",
    "Simon": "simon",
    "Open-Gpu-Share": "gpu_share",
    "Open-Local": "local",
    "NodePreferAvoidPods": "prefer_avoid",
    # present in the default profile but structurally zero in a simulation
    # (nodes carry no images)
    "ImageLocality": None,
    "SelectorSpread": None,  # disabled by default in 1.21 (PodTopologySpread)
}

FILTER_PLUGINS = {
    "NodeUnschedulable": "unschedulable",
    "NodeName": "node_name",
    "TaintToleration": "taints",
    "NodeAffinity": "node_affinity",
    "NodePorts": "ports",
    "NodeResourcesFit": "fit",
    "PodTopologySpread": "spread",
    "InterPodAffinity": "interpod",
    "Open-Gpu-Share": "gpu",
    "Open-Local": "local",
}

# volume filters are structurally vacuous in BOTH implementations
# (MakeValidPod rewrites every PVC to a hostPath — PARITY.md #7), and the
# remaining names are kube 1.21 defaults whose behavior the simulation
# either folds elsewhere (DefaultBinder → the bind step, PrioritySort →
# stream order, DefaultPreemption → never fires, simulator.go:333-342)
_VACUOUS_PLUGINS = {
    "VolumeRestrictions", "VolumeBinding", "VolumeZone", "NodeVolumeLimits",
    "EBSLimits", "GCEPDLimits", "AzureDiskLimits", "CinderLimits",
    "DefaultBinder", "PrioritySort", "DefaultPreemption",
}
_KNOWN_PLUGINS = set(SCORE_PLUGINS) | set(FILTER_PLUGINS) | _VACUOUS_PLUGINS

_EXTENSION_POINTS = {
    "queueSort", "preFilter", "filter", "postFilter", "preScore", "score",
    "reserve", "permit", "preBind", "bind", "postBind",
}

from ..models.objects import DEFAULT_SCHEDULER_NAME  # noqa: E402 (single source)


class SchedulerConfig(NamedTuple):
    """Score weights (0 disables a score plugin), filter disables, and the
    NodeResourcesFit ignored columns. Defaults mirror
    algorithmprovider/registry.go:119-132 plus the three simulator plugins
    at weight 1. Hashable — passed statically into the jitted scan."""

    w_balanced: float = 1.0
    w_least: float = 1.0
    w_node_affinity: float = 1.0
    w_taint_toleration: float = 1.0
    w_interpod: float = 1.0
    w_spread: float = 2.0
    w_prefer_avoid: float = 10000.0
    w_simon: float = 1.0
    w_gpu_share: float = 1.0
    w_local: float = 1.0
    f_taints: bool = True
    f_node_affinity: bool = True
    f_ports: bool = True
    f_fit: bool = True
    f_spread: bool = True
    f_interpod: bool = True
    f_gpu: bool = True
    f_local: bool = True
    f_unschedulable: bool = True
    # resource-axis columns the fit filter skips (NodeResourcesFitArgs
    # ignoredResources/ignoredResourceGroups, resolved against the vocab by
    # resolve_profiles)
    fit_ignored_cols: tuple = ()


DEFAULT_CONFIG = SchedulerConfig()


class Profile(NamedTuple):
    scheduler_name: str
    config: SchedulerConfig
    fit_ignored_names: Tuple[str, ...] = ()
    fit_ignored_groups: Tuple[str, ...] = ()


class SchedulerProfiles(NamedTuple):
    """All profiles of one KubeSchedulerConfiguration, in file order."""

    profiles: Tuple[Profile, ...]

    def lookup(self, scheduler_name: str) -> Optional[Profile]:
        for p in self.profiles:
            if p.scheduler_name == scheduler_name:
                return p
        return None


def _err(path: str, msg: str):
    raise ValueError(f"{path}: {msg}")


def _parse_plugin_args(path: str, profile_name: str, entries) -> tuple:
    """pluginConfig → (fit_ignored_names, fit_ignored_groups); everything
    that would change outcomes and does not map fails loudly."""
    names: list = []
    groups: list = []
    for pc in entries or []:
        pname = str(pc.get("name", ""))
        args = pc.get("args") or {}
        if pname == "NodeResourcesFit":
            for field, val in args.items():
                if field == "ignoredResources":
                    names.extend(str(v) for v in val or [])
                elif field == "ignoredResourceGroups":
                    groups.extend(str(v) for v in val or [])
                elif field in ("apiVersion", "kind"):
                    continue
                else:
                    _err(path, f"profile {profile_name!r}: NodeResourcesFitArgs."
                               f"{field} is not supported (only ignoredResources/"
                               "ignoredResourceGroups map onto the fit kernel)")
        elif pname == "InterPodAffinity":
            w = args.get("hardPodAffinityWeight", 1)
            if int(w) != 1:
                _err(path, f"profile {profile_name!r}: InterPodAffinityArgs."
                           f"hardPodAffinityWeight={w} is not supported (the "
                           "symmetric hard-affinity weight is fixed at the "
                           "default 1, encoded at template build)")
            for field in args:
                if field not in ("hardPodAffinityWeight", "apiVersion", "kind"):
                    _err(path, f"profile {profile_name!r}: InterPodAffinityArgs."
                               f"{field} is not supported")
        elif pname in _VACUOUS_PLUGINS:
            # cannot change a simulation's outcome in either implementation
            continue
        elif pname in _KNOWN_PLUGINS:
            if args:
                fields = ", ".join(k for k in args if k not in ("apiVersion", "kind"))
                _err(path, f"profile {profile_name!r}: pluginConfig args for "
                           f"{pname} ({fields}) are not supported — they would "
                           "change scoring/filtering semantics silently")
        else:
            _err(path, f"profile {profile_name!r}: pluginConfig names unknown "
                       f"plugin {pname!r}")
    return tuple(names), tuple(groups)


def _parse_profile(path: str, profile: dict, index: int) -> Profile:
    name = str(profile.get("schedulerName") or DEFAULT_SCHEDULER_NAME)
    plugins = profile.get("plugins") or {}
    cfg = DEFAULT_CONFIG._asdict()

    for point in plugins:
        if point not in _EXTENSION_POINTS:
            _err(path, f"profile {name!r}: unknown plugins extension point "
                       f"{point!r}")

    def check_known(entries, where):
        for entry in entries or []:
            ename = str(entry.get("name", ""))
            if ename != "*" and ename not in _KNOWN_PLUGINS:
                _err(path, f"profile {name!r}: {where} names unknown plugin "
                           f"{ename!r}")

    # kube merge semantics (vendored mergePluginSets): disabled entries
    # filter the defaults FIRST, then user-enabled entries are appended —
    # so `disabled: "*"` + `enabled: [X]` leaves only X.
    score = plugins.get("score") or {}
    check_known(score.get("disabled"), "plugins.score.disabled")
    check_known(score.get("enabled"), "plugins.score.enabled")
    for entry in score.get("disabled") or []:
        ename = str(entry.get("name", ""))
        if ename == "*":
            for k in list(cfg):
                if k.startswith("w_"):
                    cfg[k] = 0.0
            continue
        slot = SCORE_PLUGINS.get(ename)
        if slot:
            cfg[f"w_{slot}"] = 0.0
    for entry in score.get("enabled") or []:
        slot = SCORE_PLUGINS.get(str(entry.get("name", "")))
        if slot:
            cfg[f"w_{slot}"] = float(entry.get("weight", 1) or 1)

    filt = plugins.get("filter") or {}
    check_known(filt.get("disabled"), "plugins.filter.disabled")
    check_known(filt.get("enabled"), "plugins.filter.enabled")
    for entry in filt.get("disabled") or []:
        ename = str(entry.get("name", ""))
        if ename == "*":
            for k in list(cfg):
                if k.startswith("f_"):
                    cfg[k] = False
            continue
        slot = FILTER_PLUGINS.get(ename)
        if slot and slot != "node_name":
            cfg[f"f_{slot}"] = False

    # other extension points: validate names only — their semantics are
    # fused into the scan (reserve/bind) or structural (queueSort)
    for point in ("preFilter", "preScore", "reserve", "permit", "preBind",
                  "bind", "postBind", "postFilter", "queueSort"):
        ps = plugins.get(point) or {}
        check_known(ps.get("disabled"), f"plugins.{point}.disabled")
        check_known(ps.get("enabled"), f"plugins.{point}.enabled")

    names, groups = _parse_plugin_args(path, name, profile.get("pluginConfig"))
    return Profile(
        scheduler_name=name,
        config=SchedulerConfig(**cfg),
        fit_ignored_names=names,
        fit_ignored_groups=groups,
    )


def load_scheduler_config(path: str):
    """Parse a KubeSchedulerConfiguration yaml. Returns a SchedulerConfig
    for the common single-default-profile case (back-compat: hashable,
    directly usable as the jit-static config) or a SchedulerProfiles when
    the file defines named/multiple profiles or per-plugin args that must
    resolve against the cluster's resource vocabulary."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if doc.get("kind") not in ("KubeSchedulerConfiguration", None):
        raise ValueError(f"{path}: not a KubeSchedulerConfiguration")
    pct = doc.get("percentageOfNodesToScore")
    if pct not in (None, 0, 100):
        _err(path, f"percentageOfNodesToScore={pct} is not supported: the "
                   "reference forces 100 (pkg/simulator/utils.go:370) and "
                   "every kernel scores the full node axis")
    profiles_doc = doc.get("profiles") or []
    if not profiles_doc:
        return DEFAULT_CONFIG
    profiles = tuple(
        _parse_profile(path, p or {}, i) for i, p in enumerate(profiles_doc)
    )
    seen = set()
    for p in profiles:
        if p.scheduler_name in seen:
            _err(path, f"duplicate profile schedulerName {p.scheduler_name!r}")
        seen.add(p.scheduler_name)
    if (
        len(profiles) == 1
        and profiles[0].scheduler_name == DEFAULT_SCHEDULER_NAME
        and not profiles[0].fit_ignored_names
        and not profiles[0].fit_ignored_groups
    ):
        return profiles[0].config
    return SchedulerProfiles(profiles=profiles)


# pathological profile alternation would mean one scan per pod; above this
# many contiguous segments the stream is treated as non-segmentable
MAX_PROFILE_SEGMENTS = 64


def _route_stream(sched_config, ordered, resource_names, forced=None):
    """Shared profile routing: returns (segments, invalid, used) where
    ``segments`` is ``[(config_or_None, lo, hi)]`` contiguous same-profile
    runs covering the stream in order, ``invalid`` maps pod index →
    unknown-profile reason, and ``used`` maps profile name → resolved
    config (None for unknown names). Both public resolvers wrap this so
    the per-profile column resolution and reason wording cannot drift."""
    def resolve_cols(profile: Profile) -> SchedulerConfig:
        cols = []
        for i, rname in enumerate(resource_names):
            if rname in profile.fit_ignored_names or any(
                rname.startswith(g + "/") for g in profile.fit_ignored_groups
            ):
                cols.append(i)
        return profile.config._replace(fit_ignored_cols=tuple(cols))

    invalid = {}
    used = {}
    segments = []
    cur_cfg = None
    have_cur = False
    lo = 0
    for i, pod in enumerate(ordered):
        if forced is not None and forced[i]:
            continue  # bypasses every scheduler (simulator.go:329-331)
        name = pod.spec.scheduler_name or DEFAULT_SCHEDULER_NAME
        if name not in used:
            profile = sched_config.lookup(name)
            used[name] = None if profile is None else resolve_cols(profile)
        cfg = used[name]
        if cfg is None:
            from .reasons import unknown_profile

            invalid[i] = unknown_profile(name)
            continue  # never scheduled; extends the active segment
        if not have_cur:
            cur_cfg, have_cur = cfg, True
        elif cfg != cur_cfg:
            segments.append((cur_cfg, lo, i))
            cur_cfg, lo = cfg, i
    segments.append((cur_cfg if have_cur else None, lo, len(ordered)))
    return segments, invalid, used


def resolve_profiles(sched_config, ordered, resource_names, forced=None):
    """Route the pod stream onto ONE effective SchedulerConfig.

    Returns (config_or_None, invalid) where `invalid` maps pod index →
    unschedulable reason for pods whose spec.schedulerName matches no
    profile (kube's event handlers never admit them to the queue, so they
    stay Pending forever; the simulation reports that explicitly).

    Unforced pods referencing two or more profiles whose resolved configs
    DIFFER raise ValueError — the callers of this resolver (batched
    scenario sweeps) run one compiled pipeline for the whole stream.
    ``simulate`` routes through :func:`resolve_profile_segments` instead,
    which supports differing profiles as consecutive scans."""
    if sched_config is None or isinstance(sched_config, SchedulerConfig):
        return sched_config, {}
    if not isinstance(sched_config, SchedulerProfiles):
        raise ValueError(f"unsupported scheduler config object: {sched_config!r}")
    segments, invalid, used = _route_stream(sched_config, ordered, resource_names, forced)
    distinct = {cfg for cfg, _, _ in segments if cfg is not None}
    if len(distinct) > 1:
        names = sorted(n for n, c in used.items() if c is not None)
        raise ValueError(
            "pods reference scheduler profiles with differing plugin "
            f"configurations ({', '.join(names)}); per-pod profile routing "
            "inside one simulation is not supported"
        )
    return (distinct.pop() if distinct else None), invalid


def resolve_profile_segments(sched_config, ordered, resource_names, forced=None):
    """Split the pod stream into contiguous same-profile segments.

    Returns (segments, invalid): ``segments`` is a list of
    ``(config_or_None, lo, hi)`` half-open index ranges covering the whole
    stream in order; ``invalid`` maps pod index → unschedulable reason
    (unknown profile — kube's event handlers never admit such pods).

    Where :func:`resolve_profiles` raises on DIFFERING referenced profiles,
    this resolver supports them (``utils.go:304-381`` accepts the full
    multi-profile surface): consecutive scans share the scheduling carry,
    so placements equal the reference's serial driver routing each pod to
    its profile's framework. Forced pods bypass every scheduler and simply
    extend the current segment (binds stay in stream order). Only a
    pathological interleaving (> MAX_PROFILE_SEGMENTS contiguous runs)
    raises."""
    if sched_config is None or isinstance(sched_config, SchedulerConfig):
        return [(sched_config, 0, len(ordered))], {}
    if not isinstance(sched_config, SchedulerProfiles):
        raise ValueError(f"unsupported scheduler config object: {sched_config!r}")
    segments, invalid, _used = _route_stream(sched_config, ordered, resource_names, forced)
    if len(segments) > MAX_PROFILE_SEGMENTS:
        raise ValueError(
            f"pod stream alternates scheduler profiles into {len(segments)} "
            f"segments (> {MAX_PROFILE_SEGMENTS}): non-segmentable "
            "interleaving; order pods by profile"
        )
    return segments, invalid
