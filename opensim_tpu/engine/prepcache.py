"""Incremental prepare: content-keyed encode cache + delta re-encoding.

Repeated simulations against one cluster used to pay the full host-side
``prepare()`` cost — workload expansion plus cluster encoding, the dominant
host cost at 50k-pod scale (NOTES.md round-5 #5) — on every call: every REST
request re-encoded the snapshot, every planner sweep re-prepared its
candidate cluster. This module makes the host path pay O(changes) instead of
O(cluster):

- ``PrepareCache``: an LRU of ``prepare()`` outputs keyed by a cluster/app
  content fingerprint, with per-entry locks and pristine bind-state
  snapshots (``simulate``'s decode mutates the prepared pods; entries are
  restored after every use so a cache hit is indistinguishable from a fresh
  prepare).
- Delta re-encoders over a cached base ``Prepared``:
    * ``derive_with_apps``  — append an app's expanded pods to the stream
      (new templates re-assemble against the cached O(N) node arenas);
    * ``extend_with_nodes`` — add nodes cloned from a template (the planner
      case), splicing per-node DaemonSet pods in at exactly the positions a
      fresh expansion would produce them;
    * ``drop_mask_for_scaled`` — flip valid-mask bits for pods a scale
      request removed, instead of re-encoding the shrunk cluster.

Correctness bar (tests/test_prepcache.py): placements byte-identical to a
full re-encode on every path. The delta stream preserves the exact pod
order a fresh ``prepare()`` would produce; template/domain/vocab ids may be
numbered differently (they are opaque to the engines).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..encoding.state import ClusterEncoder, EncodedCluster, ScanState
from ..models import expand
from ..models.objects import (
    ANNO_WORKLOAD_KIND,
    LABEL_APP_NAME,
    Pod,
    ResourceTypes,
    touch_epoch,
)
from ..utils.trace import PREP_STATS
from . import queues
from .simulator import (
    AppResource,
    Prepared,
    SimulateResult,
    _owner_selector,
    _tmpl_hint,
    pinned_node_name,
    prepare,
    restore_bind_state,
    simulate,
    snapshot_bind_state,
)
from ..ops import kernels

# ---------------------------------------------------------------------------
# content fingerprints
# ---------------------------------------------------------------------------


def _meta_rv(obj: object) -> str:
    raw = getattr(obj, "raw", None) or {}
    return str((raw.get("metadata") or {}).get("resourceVersion", ""))


def fingerprint_cluster(cluster: ResourceTypes) -> str:
    """Content key for a cluster snapshot. Hashes object identity + version
    (name/uid/resourceVersion) plus the node fields that feed the encoder
    directly, so hand-built clusters (no uid/rv) still key on node content.
    In-place mutation of an already-fingerprinted object is NOT detected —
    callers that edit objects must invalidate explicitly (the REST server
    re-fingerprints on every snapshot refresh)."""
    h = hashlib.blake2b(digest_size=16)
    for n in cluster.nodes:
        h.update(
            "|".join(
                (
                    "n",
                    n.metadata.name,
                    n.metadata.uid or "",
                    _meta_rv(n),
                    "1" if n.unschedulable else "0",
                    json.dumps(sorted(n.metadata.labels.items())),
                    json.dumps(sorted((t.key, t.value, t.effect) for t in n.taints)),
                    json.dumps(sorted(n.allocatable.items())),
                    n.metadata.annotations.get("simon/node-local-storage", ""),
                )
            ).encode()
        )
    for p in cluster.pods:
        m = p.metadata
        h.update(
            f"p|{m.namespace}|{m.name}|{m.uid}|{_meta_rv(p)}|{p.spec.node_name}|{p.phase}".encode()
        )
    for kind, objs in (
        ("dep", cluster.deployments),
        ("rs", cluster.replica_sets),
        ("sts", cluster.stateful_sets),
        ("ds", cluster.daemon_sets),
        ("job", cluster.jobs),
        ("cj", cluster.cron_jobs),
    ):
        for w in objs:
            h.update(
                f"{kind}|{w.metadata.namespace}|{w.metadata.name}|{w.metadata.uid}|{_meta_rv(w)}|{w.replicas}".encode()
            )
    return h.hexdigest()


def fingerprint_apps(apps: List[AppResource]) -> str:
    """Content key for an app list: hashes each object's raw dict when
    present (request payloads round-trip exactly), identity otherwise."""
    h = hashlib.blake2b(digest_size=16)
    for app in apps:
        h.update(f"a|{app.name}".encode())
        rt = app.resources
        for objs in (
            rt.pods, rt.deployments, rt.replica_sets, rt.stateful_sets,
            rt.daemon_sets, rt.jobs, rt.cron_jobs,
        ):
            for o in objs:
                raw = getattr(o, "raw", None)
                if raw:
                    h.update(json.dumps(raw, sort_keys=True, default=str).encode())
                else:
                    h.update(
                        f"{type(o).__name__}|{o.metadata.namespace}|{o.metadata.name}|{o.metadata.uid}".encode()
                    )
    return h.hexdigest()


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------


class StaleFingerprintError(RuntimeError):
    """A cache hit landed on an entry whose watched object was ``touch()``ed
    after the entry was fingerprinted — the cached encoding no longer
    matches the object's content. Fix: ``cache.invalidate(obj)`` after the
    mutation (see models.objects.VersionedObject and
    docs/static-analysis.md#cache-mutation). ``obj`` carries the offending
    object so the cache can evict everything it taints."""

    def __init__(self, message: str, obj: Optional[object] = None) -> None:
        super().__init__(message)
        self.obj = obj


def _watched_objects(cluster: ResourceTypes, apps: List[AppResource]) -> List[object]:
    """Every model object a (cluster, apps) fingerprint covers — the set
    the stale-entry guard watches for version bumps."""
    out: List[object] = []
    rts = [cluster] + [a.resources for a in apps]
    for rt in rts:
        out.extend(rt.nodes)
        out.extend(rt.pods)
        out.extend(rt.deployments)
        out.extend(rt.replica_sets)
        out.extend(rt.stateful_sets)
        out.extend(rt.daemon_sets)
        out.extend(rt.jobs)
        out.extend(rt.cron_jobs)
        # RawObject kinds are versioned too: they don't enter the content
        # fingerprint, but the touch()/invalidate(obj) protocol must hold
        # uniformly for every model object a cluster carries
        out.extend(rt.services)
        out.extend(rt.pdbs)
        out.extend(rt.storage_classes)
        out.extend(rt.pvcs)
        out.extend(rt.config_maps)
    return out


#: (watched (object, version) pairs, touch epoch) — both captured at
#: FINGERPRINT time, i.e. before the (possibly seconds-long) prepare runs,
#: so a touch()+invalidate() landing during the build is not lost: the
#: entry records pre-build versions and an epoch older than the touch,
#: forcing the next check_fresh to scan and catch it.
WatchSnapshot = Tuple[List[Tuple[object, int]], int]


def watch_snapshot(cluster: ResourceTypes, apps: List[AppResource]) -> WatchSnapshot:
    """Capture the stale-guard baseline for a (cluster, apps) pair. The
    epoch is read BEFORE the versions: a touch interleaving between the
    two reads then leaves the entry's epoch behind the global one, which
    forces a full version scan on the next check_fresh."""
    epoch = touch_epoch()
    pairs = [(o, getattr(o, "_local_version", 0)) for o in _watched_objects(cluster, apps)]
    return pairs, epoch


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    invalidations: int = 0
    evictions: int = 0

    def as_dict(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "invalidations": self.invalidations,
            "evictions": self.evictions,
        }


# drop-mask compaction telemetry (ISSUE 12, obs/footprint.py): how many
# times twin_pod_delta REFUSED a delta because the accumulated masked-row
# density crossed the threshold, forcing the caller's full rebuild — the
# event that re-compacts the stream. A process-global counter because the
# refusal site has no cache handle (the caller owns the rebuild).
_compaction_lock = threading.Lock()
_compactions = 0  # guarded-by: _compaction_lock


def note_compaction() -> None:
    global _compactions
    with _compaction_lock:
        _compactions += 1


def compactions_total() -> int:
    with _compaction_lock:
        return _compactions


class CacheEntry:
    """One cached ``Prepared`` plus everything reuse needs: a pristine
    bind-state snapshot, a lock serializing uses of the (shared) pod
    objects, and a numpy→device map so delta builds re-upload only changed
    tensors. Entries derived from a base share the base's lock — their pod
    streams alias the same objects."""

    def __init__(
        self,
        key: str,
        prep: Optional[Prepared],
        base: Optional["CacheEntry"] = None,
        watch: Optional[WatchSnapshot] = None,
    ) -> None:
        self.key = key
        self.prep = prep
        self.base = base
        self.lock = base.lock if base is not None else threading.RLock()  # lockwatch: hold-exempt — per-entry lock spans derive/encode by design
        self.bind_snap = snapshot_bind_state(prep) if prep is not None else []
        self._dev_map: Optional[dict] = None  # guarded-by: lock
        # live-twin delta state (server/watch.py): pods DELETED by watch
        # events stay in the cached stream with their valid-mask bit flipped
        # here instead of forcing a full re-encode; the REST layer unions
        # this into every simulate() drop mask derived from the entry
        self.base_drop: Optional[np.ndarray] = None  # guarded-by: lock
        # (object, local_version at fingerprint time) — the stale-entry
        # guard; see VersionedObject (models/objects.py) and
        # watch_snapshot(). Derived entries share the base's list: their
        # stream aliases the same objects, and the base was proven fresh
        # before the delta was built.
        if watch is None and base is not None:
            self.watched: List[Tuple[object, int]] = base.watched
            self._touch_epoch = base._touch_epoch
        elif watch is not None:
            self.watched, self._touch_epoch = watch
        else:
            self.watched, self._touch_epoch = [], touch_epoch()

    def restore(self) -> None:
        if self.prep is not None:
            restore_bind_state(self.prep, self.bind_snap)

    def watches(self, obj: object) -> bool:
        return any(o is obj for o, _ in self.watched)

    def check_fresh(self) -> None:
        """Raise StaleFingerprintError if any watched object was touched
        since this entry was fingerprinted.

        Fast path: ``touch()`` bumps a process-global epoch, so when no
        object anywhere was touched since this entry (the steady state)
        this is one integer compare, not an O(watched) scan. A clean scan
        re-arms the fast path at the current epoch."""
        epoch = touch_epoch()
        if epoch == self._touch_epoch:
            return
        for obj, v0 in self.watched:
            v1 = getattr(obj, "_local_version", 0)
            if v1 != v0:
                kind = getattr(obj, "kind", type(obj).__name__)
                meta = getattr(obj, "metadata", None)
                name = getattr(meta, "name", "?") if meta is not None else "?"
                raise StaleFingerprintError(
                    f"cached prepare is stale: {kind} {name!r} was touch()ed "
                    f"(version {v1} vs {v0} at fingerprint time) without cache "
                    "invalidation; call cache.invalidate(obj) after mutating "
                    "a fingerprinted object (docs/static-analysis.md#cache-mutation)",
                    obj=obj,
                )
        self._touch_epoch = epoch

    def dev_map(self) -> dict:
        """{id(numpy leaf): device leaf} over the entry's EncodedCluster —
        delta assemblies reuse the already-uploaded tensors for every leaf
        the delta did not touch."""
        # a locked accessor: delta builders call this while already inside
        # the entry lock (RLock — free re-entry), but the planner's
        # lock-free extend_with_nodes path reaches here too
        with self.lock:
            if self._dev_map is None:
                self._dev_map = {
                    id(np_leaf): dev_leaf
                    for np_leaf, dev_leaf in zip(self.prep.ec_np, self.prep.ec)
                }
            return self._dev_map


class PrepareCache:
    """Thread-safe LRU of CacheEntry keyed by content fingerprint."""

    def __init__(self, capacity: int = 8) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()  # guarded-by: _lock
        self.stats = CacheStats()

    def get(self, key: str) -> Optional[CacheEntry]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.stats.misses += 1
                return None
            self._entries.move_to_end(key)
            self.stats.hits += 1
            return entry

    def put(self, key: str, entry: CacheEntry) -> CacheEntry:
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                return existing  # racing builders: first one wins
            self._entries[key] = entry
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.stats.evictions += 1
            return entry

    def invalidate(self, target: Union[str, object] = "") -> int:
        """Drop cache entries; returns the number dropped.

        - ``invalidate()`` — everything;
        - ``invalidate(prefix)`` — entries whose key starts with ``prefix``
          (the REST server's path when the live snapshot's fingerprint
          changes);
        - ``invalidate(obj)`` — entries whose fingerprint covered the model
          object ``obj`` (by identity): THE call to make after mutating an
          already-fingerprinted Pod/Node/Workload in place, closing the
          NOTES.md in-place-mutation envelope. Pair with ``obj.touch()`` so
          a forgotten invalidation fails loudly (StaleFingerprintError)
          instead of serving stale placements."""
        with self._lock:
            if isinstance(target, str):
                doomed = [k for k in self._entries if k.startswith(target)]
            else:
                doomed = [k for k, e in self._entries.items() if e.watches(target)]
            for k in doomed:
                del self._entries[k]
            self.stats.invalidations += len(doomed)
        if doomed:
            # trace event outside the cache lock (the span sink shares the
            # metrics recorder lock; never hold both)
            from ..obs import trace as obs

            obs.event("prepcache.invalidate", dropped=len(doomed))
        return len(doomed)

    def check_fresh(self, entry: CacheEntry) -> None:
        """Entry freshness check that also EVICTS on staleness: once an
        entry is proven stale it can never become fresh again, so leaving
        it cached would turn every later hit on its key into the same
        error (a REST client has no way to call invalidate(obj)). Eviction
        is by the offending OBJECT, dropping every entry it taints (e.g. a
        REST base entry and its derived full-key entries share one watch
        list) — recovery costs one failed request, not one per entry."""
        from ..resilience import faults

        try:
            # chaos injection point: a fault here (exc name ``stale``) lands
            # exactly like a mid-flight touch() on a watched object
            faults.fault_point("cache.stale")
            entry.check_fresh()
        except StaleFingerprintError as e:
            from ..obs import trace as obs

            obs.event("prepcache.stale", status="error", key=entry.key)
            if e.obj is not None:
                self.invalidate(e.obj)
            self.invalidate(entry.key)
            raise

    def entries_snapshot(self) -> List[CacheEntry]:
        """Point-in-time list of resident entries, LRU-oldest first — the
        memory observatory's walk (obs/footprint.py). The list is a copy;
        per-entry reads still take each entry's own lock."""
        with self._lock:
            return list(self._entries.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# ---------------------------------------------------------------------------
# delta assembly
# ---------------------------------------------------------------------------


def _to_device_reusing(
    ec_np: EncodedCluster, st0_np: ScanState, base_entry: Optional[CacheEntry]
) -> Tuple[EncodedCluster, ScanState]:
    """``scheduler.to_device`` with leaf reuse: tensors the delta shares
    with the cached base keep their device copies (no re-upload)."""
    dev_map = base_entry.dev_map() if base_entry is not None and base_entry.prep is not None else {}
    ec = EncodedCluster(
        *[dev_map[id(a)] if id(a) in dev_map else jnp.asarray(a) for a in ec_np]
    )
    st0 = ScanState(*[jnp.asarray(a) for a in st0_np])
    return ec, st0


def _assemble_delta(
    base_entry: Optional[CacheEntry],
    enc: "ClusterEncoder",
    ordered: List[Pod],
    tmpl_parts: List[object],
    forced_parts: List[object],
    n_cluster: int,
    n_bare: int,
    ds_group_sizes: List[int],
) -> Prepared:
    ec_np, st0_np, meta = enc.build()
    features = kernels.features_of(ec_np)
    ec, st0 = _to_device_reusing(ec_np, st0_np, base_entry)
    tmpl_ids = np.concatenate(
        [np.asarray(p, dtype=np.int32) for p in tmpl_parts]
    ) if tmpl_parts else np.zeros((0,), np.int32)
    forced = np.concatenate(
        [np.asarray(p, dtype=bool) for p in forced_parts]
    ) if forced_parts else np.zeros((0,), bool)
    node_idx = {name: i for i, name in enumerate(meta.node_names)}
    ds_target = [
        node_idx.get(pinned_node_name(p), -1)
        if p.metadata.annotations.get(ANNO_WORKLOAD_KIND) == "DaemonSet"
        else -1
        for p in ordered
    ]
    return Prepared(
        ec=ec,
        st0=st0,
        meta=meta,
        ordered=ordered,
        tmpl_ids=tmpl_ids,
        forced=forced,
        ds_target=ds_target,
        features=features,
        ec_np=ec_np,
        encoder=enc,
        n_cluster=n_cluster,
        n_bare=n_bare,
        ds_group_sizes=ds_group_sizes,
    )


def _expand_app(cluster: ResourceTypes, app: AppResource, use_greed: bool) -> List[Pod]:
    """The exact app expansion pipeline of ``simulator._prepare_inner``."""
    app_pods = expand.generate_pods_from_resources(app.resources, cluster.nodes)
    for p in app_pods:
        p.metadata.labels.setdefault(LABEL_APP_NAME, app.name)
    app_pods = queues.toleration_sort(queues.affinity_sort(app_pods))
    if use_greed:
        app_pods = queues.greed_sort(cluster.nodes, app_pods)
    return app_pods


def derive_with_apps(
    base: Prepared,
    cluster: ResourceTypes,
    apps: List[AppResource],
    use_greed: bool = False,
    base_entry: Optional[CacheEntry] = None,
) -> Optional[Prepared]:
    """Delta re-encode: the cached base's stream plus `apps` appended —
    exactly the stream ``prepare(cluster, apps)`` would produce when the
    base was prepared from the same cluster with no apps. `base_entry`
    (when `base` is its prep) enables device-tensor reuse for unchanged
    leaves. Returns None when the result would be empty."""
    got = derive_with_app_slices(
        base, cluster, apps, use_greed=use_greed, base_entry=base_entry
    )
    return None if got is None else got[0]


def derive_with_app_slices(
    base: Prepared,
    cluster: ResourceTypes,
    apps: List[AppResource],
    use_greed: bool = False,
    base_entry: Optional[CacheEntry] = None,
) -> Optional[Tuple[Prepared, List[Tuple[int, int]]]]:
    """:func:`derive_with_apps` that also reports per-app stream slices.

    Returns ``(prep, slices)`` where ``slices[k] = (lo, hi)`` is the
    half-open index range app ``k``'s expanded pods occupy in
    ``prep.ordered``. This is the share-safe handoff the request-axis
    batcher (``engine/reqbatch.py``) builds on: N requests' apps are
    appended onto ONE fork of the cached base arenas, and each request's
    scenario mask enables exactly the base region plus its own slice —
    masked foreign pods never touch engine state, so per-request
    placements are bit-identical to a solo ``derive_with_apps`` of that
    app alone (gated by tests/test_admission.py)."""
    if isinstance(base, CacheEntry):  # convenience: entry accepted directly
        base_entry, base = base, base.prep
    t0 = time.monotonic()
    enc = base.encoder.fork()
    new_pods: List = []
    forced_new: List[bool] = []
    slices: List[Tuple[int, int]] = []
    n_base = len(base.ordered)
    for app in apps:
        lo = n_base + len(new_pods)
        for p in _expand_app(cluster, app, use_greed):
            new_pods.append(p)
            forced_new.append(bool(p.spec.node_name))
        slices.append((lo, n_base + len(new_pods)))
    if not new_pods and not base.ordered:
        return None
    tmpl_new = [
        enc.add_pod(p, (lambda p=p: _owner_selector(p)), hint=_tmpl_hint(p))
        for p in new_pods
    ]
    prep = _assemble_delta(
        base_entry,
        enc,
        ordered=list(base.ordered) + new_pods,
        tmpl_parts=[base.tmpl_ids, tmpl_new] if len(base.tmpl_ids) else [tmpl_new],
        forced_parts=[base.forced, forced_new] if len(base.forced) else [forced_new],
        n_cluster=base.n_cluster,
        n_bare=base.n_bare,
        ds_group_sizes=list(base.ds_group_sizes or []),
    )
    PREP_STATS.record("delta_apps", time.monotonic() - t0)
    return prep, slices


def extend_with_nodes(
    base_prep: Prepared,
    new_nodes: List,
    cluster: ResourceTypes,
    apps: List[AppResource],
    use_greed: bool = False,
    base_entry: Optional[CacheEntry] = None,
) -> Optional[Prepared]:
    """Delta re-encode for node addition (the planner's candidate sweep):
    encode the new nodes into the cached arenas and splice their DaemonSet
    pods in at the exact stream positions a fresh full expansion would
    produce. Returns None when the delta cannot reproduce a fresh prepare:

    - greedy sort orders app pods by node TOTALS, which the added nodes
      change — the whole stream may reorder;
    - app DaemonSets expand one pod per node inside the app's sorted
      region — splicing there is not order-preserving in general.
    """
    if use_greed:
        return None
    if any(a.resources.daemon_sets for a in apps):
        return None
    if base_prep is None or base_prep.encoder is None or base_prep.ds_group_sizes is None:
        return None
    t0 = time.monotonic()
    enc = base_prep.encoder.fork()
    enc.extend_nodes(new_nodes)

    # per-DaemonSet pods for the new nodes, in cluster.daemon_sets order —
    # the same expansion order _cluster_pods uses
    groups_new = [expand.pods_from_daemon_set(ds, new_nodes) for ds in cluster.daemon_sets]
    if len(groups_new) != len(base_prep.ds_group_sizes):
        return None  # cluster's DS set changed vs the base prep: not a pure node delta

    b = base_prep.n_cluster - sum(base_prep.ds_group_sizes)
    ordered: List = list(base_prep.ordered[:b])
    tmpl_parts: List = [base_prep.tmpl_ids[:b]]
    forced_parts: List = [base_prep.forced[:b]]
    ds_group_sizes: List[int] = []
    off = b
    for size, pods_k in zip(base_prep.ds_group_sizes, groups_new):
        ordered.extend(base_prep.ordered[off : off + size])
        tmpl_parts.append(base_prep.tmpl_ids[off : off + size])
        forced_parts.append(base_prep.forced[off : off + size])
        off += size
        ids = [
            enc.add_pod(p, (lambda p=p: _owner_selector(p)), hint=_tmpl_hint(p))
            for p in pods_k
        ]
        ordered.extend(pods_k)
        tmpl_parts.append(ids)
        forced_parts.append([bool(p.spec.node_name) for p in pods_k])
        ds_group_sizes.append(size + len(pods_k))
    # the app region rides along unchanged (apps have no DaemonSets here)
    ordered.extend(base_prep.ordered[base_prep.n_cluster :])
    tmpl_parts.append(base_prep.tmpl_ids[base_prep.n_cluster :])
    forced_parts.append(base_prep.forced[base_prep.n_cluster :])

    prep = _assemble_delta(
        base_entry,
        enc,
        ordered=ordered,
        tmpl_parts=[p for p in tmpl_parts if len(p)],
        forced_parts=[p for p in forced_parts if len(p)],
        n_cluster=base_prep.n_cluster + sum(len(g) for g in groups_new),
        n_bare=base_prep.n_bare,
        ds_group_sizes=ds_group_sizes,
    )
    PREP_STATS.record("delta_nodes", time.monotonic() - t0)
    return prep


def drop_mask_for_scaled(
    prep: Prepared, owned_by: Callable[[Pod, set], bool], scaled: set
) -> np.ndarray:
    """Valid-mask flip for a scale request: mark the BARE cluster pods owned
    by the scaled workloads (the pods ``scale-apps`` removes from the
    snapshot before re-simulating). Only the bare prefix is eligible — the
    fresh path filters ``cluster.pods``, never workload expansions."""
    mask = np.zeros((len(prep.ordered),), dtype=bool)
    for i in range(prep.n_bare):
        if owned_by(prep.ordered[i], scaled):
            mask[i] = True
    return mask


def pad_drop_mask(mask: Optional[np.ndarray], n: int) -> Optional[np.ndarray]:
    """Extend a base-entry drop mask to a longer derived stream. Safe for
    every derive path in use: ``derive_with_apps`` appends at the end and
    ``extend_with_nodes`` splices only above the bare-pod prefix, while twin
    drop masks only ever flag bare pods — set bits never move."""
    if mask is None:
        return None
    if len(mask) >= n:
        return mask[:n]
    out = np.zeros((n,), dtype=bool)
    out[: len(mask)] = mask
    return out


def union_drop_masks(
    a: Optional[np.ndarray], b: Optional[np.ndarray], n: int
) -> Optional[np.ndarray]:
    """Union of two (optional) drop masks, padded to stream length ``n``."""
    a = pad_drop_mask(a, n)
    b = pad_drop_mask(b, n)
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def twin_pod_delta(
    base_entry: CacheEntry,
    key: str,
    added: List[Pod],
    removed_keys: set,
    watch: Optional[WatchSnapshot] = None,
) -> Optional[CacheEntry]:
    """O(changes) base-entry maintenance for the live twin (server/watch.py):
    derive a new base CacheEntry from the current one after a batch of pod
    ADDED/DELETED watch events, without re-expanding or re-encoding the
    cluster.

    - ``added`` pods are encoded into a fork of the cached arenas and
      inserted at the END OF THE BARE REGION — exactly where a fresh
      ``prepare()`` of the re-listed cluster puts them (the twin appends new
      pods to its pod list, mirroring event order).
    - ``removed_keys`` — ``(namespace, name)`` pairs — become valid-mask
      flips recorded in ``CacheEntry.base_drop``; the pods stay in the
      stream but every engine skips them (the scale-apps drop-mask path,
      proven placement-identical to re-encoding the shrunk cluster).

    Returns None when the entry cannot express the delta (no encoder
    provenance, a removed pod outside the bare region, or the accumulated
    masked-row density past the compaction threshold below) — the caller
    falls back to a full rebuild. MUST be called with ``base_entry.lock``
    held and bind state restored."""
    prep = base_entry.prep
    if prep is None or prep.encoder is None or prep.ds_group_sizes is None:
        return None
    t0 = time.monotonic()
    nb = prep.n_bare
    drop = (
        np.array(base_entry.base_drop, dtype=bool, copy=True)
        if base_entry.base_drop is not None
        else np.zeros((len(prep.ordered),), dtype=bool)
    )
    if removed_keys:
        found = set()
        for i in range(nb):
            p = prep.ordered[i]
            k = (p.metadata.namespace, p.metadata.name)
            if k in removed_keys:
                drop[i] = True
                found.add(k)
        missing = removed_keys - found
        if missing:
            # a deletion we cannot locate in the bare prefix (e.g. the pod
            # was never admissible, or it lives in a workload expansion) —
            # only the full rebuild knows how to express it
            return None
    if added:
        enc = prep.encoder.fork()
        ids_new = [
            enc.add_pod(p, (lambda p=p: _owner_selector(p)), hint=_tmpl_hint(p))
            for p in added
        ]
        new_prep = _assemble_delta(
            base_entry,
            enc,
            ordered=list(prep.ordered[:nb]) + list(added) + list(prep.ordered[nb:]),
            tmpl_parts=[
                prep.tmpl_ids[:nb],
                np.asarray(ids_new, dtype=np.int32),
                prep.tmpl_ids[nb:],
            ],
            forced_parts=[
                prep.forced[:nb],
                np.asarray([bool(p.spec.node_name) for p in added], dtype=bool),
                prep.forced[nb:],
            ],
            n_cluster=prep.n_cluster + len(added),
            n_bare=nb + len(added),
            ds_group_sizes=list(prep.ds_group_sizes),
        )
        drop = np.concatenate([drop[:nb], np.zeros((len(added),), bool), drop[nb:]])
    else:
        new_prep = prep  # drops alone never re-encode: the mask is the delta
    # compaction threshold: deleted pods stay in the stream as masked rows,
    # so pure add/delete churn would otherwise grow the stream (and every
    # engine pass over it) without bound. Past the threshold the delta is
    # refused and the caller's full rebuild re-prepares the compacted
    # cluster — amortized O(cluster / threshold) per churned pod.
    n_dropped = int(drop.sum())
    if n_dropped > max(64, len(drop) // 4):
        note_compaction()
        return None
    entry = CacheEntry(key, new_prep, base=base_entry, watch=watch)
    entry.base_drop = drop if n_dropped else None
    PREP_STATS.record("twin_delta", time.monotonic() - t0)
    return entry


# ---------------------------------------------------------------------------
# attach-from-shm (multi-process serving fleet, server/fleet.py)
# ---------------------------------------------------------------------------


def publication_parts(entry: CacheEntry) -> Optional[dict]:
    """The host-side pieces of a warm base entry a twin owner publishes
    over shared memory (server/fleet.py): everything a worker process
    needs to rebuild an equivalent :class:`CacheEntry` EXCEPT the device
    tensors (each attaching process re-uploads once per generation) and
    the per-entry lock (locks are process-local by definition). MUST be
    called with ``entry.lock`` held and bind state restored, like every
    other reader of the shared pod objects. Returns None for a no-prep
    entry (a cluster with no schedulable pods — nothing to publish)."""
    prep = entry.prep
    if prep is None:
        return None
    st0_np = ScanState(*[np.asarray(a) for a in prep.st0])
    return {
        "ec_np": prep.ec_np,
        "st0_np": st0_np,
        "meta": prep.meta,
        "ordered": prep.ordered,
        "tmpl_ids": prep.tmpl_ids,
        "forced": prep.forced,
        "ds_target": prep.ds_target,
        "features": prep.features,
        "encoder": prep.encoder,
        "n_cluster": prep.n_cluster,
        "n_bare": prep.n_bare,
        "ds_group_sizes": prep.ds_group_sizes,
        "base_drop": entry.base_drop,
    }


def entry_from_publication(key: str, parts: dict) -> CacheEntry:
    """Rebuild a warm base :class:`CacheEntry` from published parts — the
    worker-process half of the fleet's attach-from-shm path. The numpy
    leaves in ``parts`` may be zero-copy read-only views over shared
    memory; nothing here (or on any serving path over the entry) writes
    through them — deltas fork the encoder and drop masks are copied
    before mutation. The one per-attach cost is the device upload of the
    encoded cluster (each process owns its device buffers; later derives
    reuse them leaf-by-leaf through ``CacheEntry.dev_map``)."""
    ec_np: EncodedCluster = parts["ec_np"]
    st0_np: ScanState = parts["st0_np"]
    ec = EncodedCluster(*[jnp.asarray(a) for a in ec_np])
    st0 = ScanState(*[jnp.asarray(a) for a in st0_np])
    prep = Prepared(
        ec=ec,
        st0=st0,
        meta=parts["meta"],
        ordered=parts["ordered"],
        tmpl_ids=parts["tmpl_ids"],
        forced=parts["forced"],
        ds_target=parts["ds_target"],
        features=parts["features"],
        ec_np=ec_np,
        encoder=parts["encoder"],
        n_cluster=parts["n_cluster"],
        n_bare=parts["n_bare"],
        ds_group_sizes=parts["ds_group_sizes"],
    )
    entry = CacheEntry(key, prep)
    with entry.lock:  # fresh and unpublished, but base_drop is guarded-by it
        entry.base_drop = parts.get("base_drop")
    return entry


# ---------------------------------------------------------------------------
# steady-state entry point
# ---------------------------------------------------------------------------


def simulate_cached(
    cluster: ResourceTypes,
    apps: List[AppResource],
    cache: PrepareCache,
    *,
    use_greed: bool = False,
    node_pad: int = 128,
    sched_config: Optional[object] = None,
    extra_plugins: tuple = (),
    tie_seed: Optional[int] = None,
    key: Optional[str] = None,
) -> "SimulateResult":
    """One full simulation through the encode cache: the first call for a
    (cluster, apps) content key pays the full prepare; every later call
    reuses the cached Prepared (fingerprint + bind-state restore — O(pods)
    pointer work, no expansion, no encode). The steady-state path bench.py
    --config steady measures."""
    full_key = key or (
        fingerprint_cluster(cluster)
        + "|" + fingerprint_apps(apps)
        + f"|g{int(use_greed)}|p{node_pad}"
    )
    entry = cache.get(full_key)
    if entry is None:
        # baseline captured BEFORE the build: a touch()+invalidate() racing
        # the prepare leaves this entry provably stale, not silently fresh
        watch = watch_snapshot(cluster, apps)
        prep = prepare(cluster, apps, use_greed=use_greed, node_pad=node_pad)
        entry = cache.put(full_key, CacheEntry(full_key, prep, watch=watch))
    else:
        t0 = time.monotonic()
        cache.check_fresh(entry)
        with entry.lock:
            entry.restore()
        PREP_STATS.record("hit", time.monotonic() - t0)
    if entry.prep is None:
        return simulate(
            cluster, apps, use_greed=use_greed, node_pad=node_pad,
            sched_config=sched_config, extra_plugins=extra_plugins, tie_seed=tie_seed,
        )
    with entry.lock:
        try:
            return simulate(
                cluster, apps, sched_config=sched_config,
                extra_plugins=extra_plugins, tie_seed=tie_seed, prep=entry.prep,
            )
        finally:
            entry.restore()
