"""Decision-audit evaluators (ISSUE 7): normalize engine outputs into
:class:`~opensim_tpu.engine.reasons.PlacementExplanation` records and, on
demand, reconstruct one pod's full scoring decision.

Two tiers, priced differently:

- **Bulk** (``simulate(..., explain=True)``): every pod gets a record built
  from data the engines already produced — status, winning node, and for
  unschedulable pods the per-filter rejection counts the failure
  attribution computed. O(pods) host work, no per-node evaluation.
- **Deep** (:func:`explain_pod`, behind ``simon explain <pod>``): replay
  the scheduling state to the instant *before* the pod's step from the
  recorded placements, then re-evaluate the score pipeline through the
  SAME kernel functions the XLA scan runs (``kernels.score_parts`` is the
  scan's own accumulation order), yielding the per-plugin breakdown on the
  winning node and the margin over the runner-up. O(nodes) for one pod.

The engine-computed ``chosen`` stays authoritative throughout: the replayed
state is exact up to float summation order (``np.add.at`` accumulates in
index order where the scan accumulated in bind order), so the breakdown is
reported *about* the engine's winner, never used to re-decide it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..ops import kernels
from . import reasons
from .reasons import PlacementExplanation, Reason


def rebuild_counts(prep, chosen: np.ndarray, upto: Optional[int] = None):
    """Host-side reconstruction of the ScanState count tensors (port_used,
    dom_sel, dom_anti, dom_prefw) from placements — the numpy mirror of
    ``kernels.bind_update``'s count updates. ``upto`` restricts to binds
    strictly before that stream index (deep-explain replay); None folds in
    every bind (the megakernel failure path)."""
    ec = prep.ec_np
    st0 = prep.st0
    chosen = np.asarray(chosen)
    bound = chosen >= 0
    if upto is not None:
        bound = bound.copy()
        bound[upto:] = False
    us = prep.tmpl_ids[: len(bound)][bound]
    cs = chosen[bound].astype(np.int64)

    port_used = np.array(np.asarray(st0.port_used), dtype=np.float32, copy=True)
    ports = np.asarray(ec.ports)[us]  # [B, Hp]
    pv = ports >= 0
    if pv.any():
        rows = np.repeat(cs, ports.shape[1])[pv.ravel()]
        np.add.at(port_used, (rows, ports.ravel()[pv.ravel()]), 1.0)

    dom_sel = np.array(np.asarray(st0.dom_sel), dtype=np.float32, copy=True)
    matches = np.asarray(ec.matches_sel)[us].astype(np.float32)  # [B, A]
    node_domain = np.asarray(ec.node_domain)
    for tk in range(node_domain.shape[1]):
        np.add.at(dom_sel, node_domain[cs, tk], matches)

    dom_anti = np.array(np.asarray(st0.dom_anti), dtype=np.float32, copy=True)
    anti_g_topo = np.asarray(ec.anti_g_topo)
    anti_g = np.asarray(ec.anti_g)[us].astype(np.float32)
    for g in range(anti_g_topo.shape[0]):
        np.add.at(dom_anti[:, g], node_domain[cs, anti_g_topo[g]], anti_g[:, g])

    dom_prefw = np.array(np.asarray(st0.dom_prefw), dtype=np.float32, copy=True)
    prefg_topo = np.asarray(ec.prefg_topo)
    prefg_w = np.asarray(ec.prefg_w)[us]
    for g in range(prefg_topo.shape[0]):
        np.add.at(dom_prefw[:, g], node_domain[cs, prefg_topo[g]], prefg_w[:, g])

    return port_used, dom_sel, dom_anti, dom_prefw


def replay_state(prep, chosen: np.ndarray, gpu_take: np.ndarray, upto: int):
    """The ScanState the scheduler saw right before stream index ``upto``,
    rebuilt from the recorded placements (chosen node + GPU slot packing per
    pod). used/ports/domain counts are pure sums; vg/dev state replays the
    deterministic tightest-fit packing sequentially over the (rare)
    local-storage binds."""
    from ..encoding.state import ScanState

    ec = prep.ec_np
    st0 = prep.st0
    chosen = np.asarray(chosen)
    bound = chosen >= 0
    bound = bound.copy()
    bound[upto:] = False
    us = prep.tmpl_ids[: len(bound)][bound]
    cs = chosen[bound].astype(np.int64)

    used = np.array(np.asarray(st0.used), dtype=np.float32, copy=True)
    np.add.at(used, cs, np.asarray(ec.req)[us])

    port_used, dom_sel, dom_anti, dom_prefw = rebuild_counts(prep, chosen, upto=upto)

    gpu_free = np.array(np.asarray(st0.gpu_free), dtype=np.float32, copy=True)
    if prep.features.gpu and len(cs):
        take = np.asarray(gpu_take)[: len(bound)][bound].astype(np.float32)  # [B, Gd]
        mem = np.asarray(ec.gpu_mem)[us].astype(np.float32)  # [B]
        np.add.at(gpu_free, cs, -(take * mem[:, None]))

    vg_free = np.array(np.asarray(st0.vg_free), dtype=np.float32, copy=True)
    dev_free = np.array(np.asarray(st0.dev_free), dtype=np.float32, copy=True)
    if prep.features.local:
        big = np.float32(1e30)
        lvm_req = np.asarray(ec.lvm_req)
        dev_req_sizes = np.asarray(ec.dev_req_sizes)
        node_dev_media = np.asarray(ec.node_dev_media)
        node_dev_cap = np.asarray(ec.node_dev_cap)
        Mv = dev_req_sizes.shape[2]
        for j in np.nonzero(bound)[0]:
            u = int(prep.tmpl_ids[j])
            node = int(chosen[j])
            lvm = float(lvm_req[u])
            vf = vg_free[node]
            if vf.shape[0]:
                fits = vf >= lvm
                if fits.any():
                    vf[np.argmin(np.where(fits, vf, big))] -= max(lvm, 0.0)
            df = dev_free[node]
            taken = np.zeros_like(df, dtype=bool)
            for media in (0, 1):
                for k in reversed(range(Mv)):  # ascending sizes; 0-pads skipped
                    size = float(dev_req_sizes[u, media, k])
                    if size <= 0.0:
                        continue
                    cand = (
                        (node_dev_media[node] == media) & (df >= size) & (df > 0) & ~taken
                    )
                    if cand.any():
                        taken[np.argmin(np.where(cand, node_dev_cap[node], big))] = True
            df[taken] = 0.0

    return ScanState(
        used=used, port_used=port_used, dom_sel=dom_sel, dom_anti=dom_anti,
        dom_prefw=dom_prefw, gpu_free=gpu_free, vg_free=vg_free, dev_free=dev_free,
    )


@dataclass
class ExplainContext:
    """Everything an on-demand deep explanation needs, captured by
    ``simulate(..., explain=True)`` and attached to ``EngineDecision``.
    Holds a reference to the (large) Prepared — meant for library/CLI
    callers; the REST layer serializes explanations and drops this."""

    prep: object
    chosen: np.ndarray
    gpu_take: np.ndarray
    static_fail: np.ndarray  # [U,4] or per-pod [P,4] (segments)
    sf_rows: np.ndarray      # pod index -> static_fail row
    fail_counts: np.ndarray  # [P, NUM_FILTERS-4]
    insufficient: np.ndarray  # [P, R]
    n_nodes: int
    node_names: Sequence[str]
    resource_names: Sequence[str]
    config: object = None
    segments: Optional[list] = None  # [(config_or_None, lo, hi)]
    extra_plugins: tuple = ()
    engine: str = ""
    # node mask of a masked re-simulation (planner prep reuse): the deep
    # audit must score exactly the node set the engine considered
    node_valid: Optional[np.ndarray] = None

    def config_for(self, i: int):
        if self.segments:
            for cfg, lo, hi in self.segments:
                if lo <= i < hi:
                    return cfg
        return self.config

    def index_of(self, pod_name: str) -> Optional[int]:
        """Stream index of ``ns/name`` or bare ``name``. Expanded pods carry
        generated uid suffixes (``web-00a3…-00a4…``), so a query that exactly
        matches no pod falls back to a workload-prefix match: the first pod
        whose name starts with ``<query>-`` wins when every such pod shares
        that prefix (one workload); distinct workloads raise ambiguity."""
        hit = None
        for i, p in enumerate(self.prep.ordered):
            full = f"{p.metadata.namespace}/{p.metadata.name}"
            if full == pod_name or p.metadata.name == pod_name:
                if hit is not None and p.metadata.name == pod_name:
                    raise ValueError(
                        f"pod name {pod_name!r} is ambiguous; use namespace/name"
                    )
                hit = i
                if full == pod_name:
                    return i
        if hit is not None:
            return hit
        import re

        bare = pod_name.rsplit("/", 1)[-1]
        ns = pod_name.rsplit("/", 1)[0] if "/" in pod_name else None
        # exactly <bare> plus generated uid segments: "web" matches
        # "web-00a3…-00a4…" but NOT another workload "web-frontend-…"
        gen = re.compile(re.escape(bare) + r"(-[0-9a-f]{10})+$")
        matches = [
            i
            for i, p in enumerate(self.prep.ordered)
            if gen.fullmatch(p.metadata.name)
            and (ns is None or p.metadata.namespace == ns)
        ]
        # first match in stream order — pods of one workload share a
        # template, so any member's explanation stands in for the workload
        return matches[0] if matches else None

    def reason_counts(self, i: int) -> List[reasons.ReasonCount]:
        return reasons.counts_from_rows(
            np.asarray(self.static_fail)[int(self.sf_rows[i])],
            self.fail_counts[i],
            self.insufficient[i],
            self.resource_names,
        )


def audit_rejects(static_fail, sf_rows, fail_counts, mask) -> np.ndarray:
    """Aggregate 11-slot per-filter reject totals (kernel filter-index
    order) from per-pod attribution rows — the XLA-path counterpart of the
    C++ engine's in-engine ``filter_rejects`` accumulator. ``mask`` selects
    the audited pods (valid, unforced)."""
    rej = np.zeros(kernels.NUM_FILTERS, np.int64)
    mask = np.asarray(mask, dtype=bool)
    if mask.any():
        static_rows = np.asarray(static_fail)[np.asarray(sf_rows)[mask]]
        rej[: kernels.F_PORTS] = static_rows.sum(axis=0, dtype=np.int64)
        rej[kernels.F_PORTS:] = np.asarray(fail_counts)[mask].sum(axis=0, dtype=np.int64)
    return rej


def primary_reason_histogram(
    static_fail, sf_rows, fail_counts, failed_idx
) -> Dict[str, int]:
    """``{reason_name: pod count}`` over the unschedulable pods, each pod
    attributed to its dominant filter (max rejected nodes, ties by filter
    precedence — the argmax over the merged row takes the lowest index)."""
    out: Dict[str, int] = {}
    failed_idx = np.asarray(failed_idx)
    if not len(failed_idx):
        return out
    merged = np.concatenate(
        [
            np.asarray(static_fail)[np.asarray(sf_rows)[failed_idx]],
            np.asarray(fail_counts)[failed_idx],
        ],
        axis=1,
    )
    primary = np.argmax(merged, axis=1)
    # a pod with all-zero rows (e.g. no attribution ran) falls to slot 0;
    # report those as unattributed rather than inventing a hostname mismatch
    has_any = merged.max(axis=1) > 0
    for k in primary[has_any]:
        name = Reason(int(k)).name.lower()
        out[name] = out.get(name, 0) + 1
    n_unattr = int((~has_any).sum())
    if n_unattr:
        out["unattributed"] = n_unattr
    return out


def explain_pod(ctx: ExplainContext, i: int) -> PlacementExplanation:
    """Deep decision audit for one stream index: the bulk record plus — for
    scheduled pods — the per-plugin score breakdown on the winning node and
    the margin over the runner-up, evaluated against the replayed pre-bind
    state through the scan's own kernels."""
    import jax.numpy as jnp

    from ..encoding.state import ScanState

    prep = ctx.prep
    pod = prep.ordered[i]
    name = f"{pod.metadata.namespace}/{pod.metadata.name}"
    c = int(ctx.chosen[i])
    forced = bool(prep.forced[i])

    if forced:
        if c < 0:
            return PlacementExplanation(
                pod=name, status="unschedulable", nodes_total=ctx.n_nodes,
                forced=True, message=reasons.node_not_found(pod.spec.node_name),
            )
        return PlacementExplanation(
            pod=name, status="scheduled", nodes_total=ctx.n_nodes,
            node=str(ctx.node_names[c]), forced=True,
            message="pre-bound (spec.nodeName set); bypassed the scheduler",
        )

    if c < 0:
        counts = ctx.reason_counts(i)
        return PlacementExplanation(
            pod=name, status="unschedulable", nodes_total=ctx.n_nodes,
            reasons=counts,
            message=reasons.render_unschedulable(ctx.n_nodes, counts),
        )

    # scheduled: replay the pre-bind state and re-run the score pipeline
    u = int(prep.tmpl_ids[i])
    cfg = ctx.config_for(i)
    st = replay_state(prep, ctx.chosen, ctx.gpu_take, upto=i)
    st_dev = ScanState(*[jnp.asarray(a) for a in st])
    from . import nativepath

    ec = prep.ec_np
    nv = None
    if ctx.node_valid is not None:
        nv = np.ascontiguousarray(ctx.node_valid, dtype=bool)
        ec = ec._replace(node_valid=nv)
    stat = nativepath._stat_np(prep, cfg, node_valid=nv)
    res = kernels.pod_step(
        ec, stat, st_dev, u, feat=prep.features, cfg=cfg,
        extra=ctx.extra_plugins,
    )
    parts = kernels.score_parts(
        ec, stat, st_dev, u, res.feasible, prep.features, cfg,
        ctx.extra_plugins,
    )
    score = np.asarray(res.score)
    feasible = np.asarray(res.feasible)
    scores = {k: round(float(np.asarray(v)[c]), 4) for k, v in parts.items()}
    total = round(float(score[c]), 4)
    runner_up = margin = None
    others = feasible.copy()
    others[c] = False
    if others.any():
        masked = np.where(others, score, -np.inf)
        ru = int(np.argmax(masked))
        runner_up = str(ctx.node_names[ru])
        margin = round(float(score[c] - score[ru]), 4)
    return PlacementExplanation(
        pod=name, status="scheduled", nodes_total=ctx.n_nodes,
        node=str(ctx.node_names[c]), scores=scores, score=total,
        runner_up=runner_up, margin=margin,
        message=f"scheduled on {ctx.node_names[c]} "
        f"(score {total}"
        + (f", margin {margin} over {runner_up}" if runner_up is not None else "")
        + ")",
    )


def build_explanations(
    ctx: ExplainContext,
    custom_reasons: Dict[int, str],
    victims_of: Dict[int, int],
    drops=(),
) -> List[PlacementExplanation]:
    """Bulk tier: one record per pod in the stream (dropped pods excluded),
    from data the engines already produced — no per-node work."""
    out: List[PlacementExplanation] = []
    ordered = ctx.prep.ordered
    forced = ctx.prep.forced
    for i, pod in enumerate(ordered):
        if i in drops:
            continue
        name = f"{pod.metadata.namespace}/{pod.metadata.name}"
        c = int(ctx.chosen[i])
        if forced[i] and c < 0:
            out.append(
                PlacementExplanation(
                    pod=name, status="unschedulable", nodes_total=ctx.n_nodes,
                    forced=True,
                    message=reasons.node_not_found(pod.spec.node_name),
                )
            )
        elif c >= 0:
            out.append(
                PlacementExplanation(
                    pod=name, status="scheduled", nodes_total=ctx.n_nodes,
                    node=str(ctx.node_names[c]), forced=bool(forced[i]),
                )
            )
        elif i in custom_reasons:
            out.append(
                PlacementExplanation(
                    pod=name, status="unschedulable", nodes_total=ctx.n_nodes,
                    message=custom_reasons[i],
                )
            )
        elif i in victims_of:
            p = ordered[victims_of[i]]
            out.append(
                PlacementExplanation(
                    pod=name, status="preempted", nodes_total=ctx.n_nodes,
                    message=reasons.preempted(p.metadata.namespace, p.metadata.name),
                )
            )
        else:
            counts = ctx.reason_counts(i)
            out.append(
                PlacementExplanation(
                    pod=name, status="unschedulable", nodes_total=ctx.n_nodes,
                    reasons=counts,
                    message=reasons.render_unschedulable(ctx.n_nodes, counts),
                )
            )
    return out
