"""Fast-path selection and input marshalling for the Pallas megakernel.

`applicable()` decides whether a prepared simulation can run on
`ops/pallas_scan.run_fast_scan` (feature subset + layout constraints);
`schedule()` marshals the encoded cluster into the kernel's VMEM/SMEM
layouts and runs it. Placements are identical to the XLA scan — the tests
in tests/test_fastpath.py assert equality — so callers can switch freely.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import numpy as np

from ..encoding import vocab as V
from ..ops import kernels
from ..ops.pallas_scan import CHUNK, FastInputs, run_fast_scan
from ..utils import envknobs
from .schedconfig import DEFAULT_CONFIG

HOSTNAME = "kubernetes.io/hostname"

_VMEM_BUDGET = 10 * 1024 * 1024


def _pad8_static(n: int) -> int:
    return max(8, 8 * math.ceil(n / 8))


def applicable(prep, config=None) -> bool:
    return why_not(prep, config) is None


def why_not(prep, config=None) -> Optional[str]:
    """Envelope check for the megakernel: returns None when the prepared
    simulation can run on it, else a one-line reason (surfaced as engine
    attribution — VERDICT r4 #3). The kernel covers: static filters + fit +
    least/balanced/share + topology spread + inter-pod terms, hostname plus
    at most four other topology keys (stacked per-key count blocks)."""
    if config is not None and config != DEFAULT_CONFIG:
        return "non-default scheduler config (weight/disable merges run on the XLA or C++ engine)"
    f = prep.features
    ec = prep.ec_np if prep.ec_np is not None else prep.ec
    if f.ports and int(ec.ports.max() if ec.ports.size else -1) >= 64:
        return "port-vocab ids >=64 exceed the 64 padded port rows"
    if f.gpu and int(ec.node_gpu_mem.shape[1]) > 8:
        return f"{int(ec.node_gpu_mem.shape[1])} GPUs/node > 8 supported"
    if f.local and (
        int(ec.node_vg_cap.shape[1]) > 8
        or int(ec.node_dev_cap.shape[1]) > 8
        or int(ec.dev_req_sizes.shape[2]) > 8
    ):
        return "open-local VG/device axes > 8 supported"
    # inter-pod terms are supported with bounded table sizes
    if f.interpod or f.prefg:
        if int(ec.anti_g_sel.shape[0]) > 16 or int(ec.prefg_sel.shape[0]) > 16:
            return "inter-pod global term tables > 16 rows"
        if (
            int(ec.at_sel.shape[1]) > 4
            or int(ec.an_sel.shape[1]) > 4
            or int(ec.pt_sel.shape[1]) > 4
        ):
            return "inter-pod per-template terms > 4 per pod"
    # N is padded to a 128-lane multiple at marshalling time
    # (build_inputs), so any encoder node_pad is acceptable
    N = 128 * math.ceil(int(ec.node_valid.shape[0]) / 128)
    U = int(ec.req.shape[0])
    A = int(ec.matches_sel.shape[1])
    R = int(ec.alloc.shape[1])
    # beyond 512 templates the kernel switches to big-U mode (template
    # tables in HBM, one DMA per step — see use_big_u/run_fast_scan);
    # 2048 bounds the SMEM scalar tables
    if R > 8 or U > 2048 or A > 64:
        over = [
            f"{label}={val} > {cap} supported"
            for label, val, cap in (("R", R, 8), ("U", U, 2048), ("A", A, 64))
            if val > cap
        ]
        return "table sizes outside envelope: " + ", ".join(over)
    vocab = prep.meta.vocab
    topo_keys = vocab.topo_keys.items()
    non_host = [k for k in topo_keys if k != HOSTNAME]
    if len(non_host) > 4:
        # hostname + up to four zone-like keys (compile-time unrolled
        # per-key loops; beyond that the XLA scan wins anyway)
        return f"{len(non_host)} non-hostname topology keys > 4 supported"
    # hostname domains must be node-identity (each valid node carries its
    # own hostname label) for the per-node count layout to be exact
    if HOSTNAME in topo_keys:
        tk = topo_keys.index(HOSTNAME)
        nd = np.asarray(ec.node_domain)[:, tk]
        nv = np.asarray(ec.node_valid)
        trash = np.asarray(ec.domain_topo).shape[0] - 1
        if (nd[nv] == trash).any():
            return "some valid nodes carry no hostname label"
        if len(np.unique(nd[nv])) != int(nv.sum()):
            return "hostname domains are not node-identity (duplicate hostname labels)"
    # pallas compiled path only on TPU; elsewhere the interpreter would be
    # slower than the XLA scan (tests force it via OPENSIM_FASTPATH=interpret)
    if envknobs.raw("OPENSIM_DISABLE_FASTPATH"):
        return "disabled by --backend xla (OPENSIM_DISABLE_FASTPATH)"
    if envknobs.raw("OPENSIM_NATIVE") == "1":
        return "disabled by --backend native (OPENSIM_NATIVE=1)"
    if jax.default_backend() != "tpu" and envknobs.raw("OPENSIM_FASTPATH") != "interpret":
        return f"no TPU backend (jax.default_backend()={jax.default_backend()!r})"
    # VMEM budget. The pallas_call signature is generated per feature-flag
    # combination (_input_layout): a feature that is off contributes ZERO
    # rows — its buffers don't exist in the program. Resident rows ([x, N]):
    #   always: alloc/used0/used/used_out (4R), template tables (3U unless
    #   big-U), node_cnt (A), has_zone (K), node_valid (1)
    #   +interpod: anti_node + prefw_node (2G)
    #   +gpu: gpu0/gpu_free/gpu_out (3Gd)
    #   +local: vg cap/init/free/out (4Vg) + dev cap/init/free/out + media
    #   one-hots (6Dv)
    #   +ports: port_used (Hp)
    #   +na/tt: one [U, N] table each
    # plus the zone blocks: zone_NZ + zone_ZN (2·K·N·Z) and the [*, Z]
    # scratch counts.
    if non_host:
        counts = []
        for key in non_host:
            nd = np.asarray(ec.node_domain)[:, topo_keys.index(key)]
            counts.append(len(np.unique(nd)))
        Z = max(128, 128 * math.ceil(max(counts) / 128))
    else:
        Z = 128
    K = max(len(non_host), 1)
    G = 16  # padded global-term row cap (≤16 enforced above)
    Gd_pad = _pad8_static(int(ec.node_gpu_mem.shape[1]))
    Vg_pad = _pad8_static(int(ec.node_vg_cap.shape[1]))
    Dv_pad = _pad8_static(int(ec.node_dev_cap.shape[1]))
    ports_np = np.asarray(ec.ports)
    Hp_pad = _pad8_static(
        int(ports_np.max()) + 1 if ports_np.size and ports_np.max() >= 0 else 1
    )
    U_resident = 0 if use_big_u(U, N) else U
    rows = 4 * R + 3 * U_resident + A + K + 1
    zone_z_rows = K * A
    # [X, U] tables resident in non-big-U mode ([X, U_pad128] in big-U they
    # move to HBM): matches + ports + interpod term tables
    u_cols = 0 if use_big_u(U, N) else max(U, 128)
    u_rows = A  # matches_AU
    if f.interpod or f.prefg:
        rows += 2 * G
        zone_z_rows += 2 * G
        u_rows += 4 * G  # antig/gmatch/prefg/pmatch
    if f.gpu:
        rows += 3 * Gd_pad
    if f.local:
        rows += 4 * Vg_pad + 6 * Dv_pad
    if f.ports:
        rows += Hp_pad
        u_rows += 2 * Hp_pad  # port_HU + port_conf_HU
    if f.pref_node_affinity:
        rows += U_resident
    if f.prefer_taints:
        rows += U_resident
    if f.prefer_avoid:
        rows += U_resident
    vmem = (rows * N + (2 * K * N + zone_z_rows) * Z + u_rows * u_cols) * 4
    if vmem > _VMEM_BUDGET:
        return f"VMEM estimate {vmem / 1e6:.1f} MB exceeds the {_VMEM_BUDGET / 1e6:.0f} MB budget"
    return None


def _gc_row(prep) -> int:
    """Resource-axis row of alibabacloud.com/gpu-count when the dynamic
    allocatable path (Features.gc_dyn) is active, else -1."""
    if not prep.features.gc_dyn:
        return -1
    return kernels.gc_row_of(prep.ec_np if prep.ec_np is not None else prep.ec)


def use_big_u(U: int, N: int) -> bool:
    """Template tables move to HBM (per-step DMA) once the three resident
    [U, N] tables would crowd VMEM; below that the fully-resident kernel is
    faster. VMEM-aware: a 1000-template workload on a small cluster stays
    resident (536×256 is 1.6 MB), while 513 templates × 5120 nodes (31 MB)
    goes to HBM — matching the historical U>512 envelope at headline N."""
    return 3 * U * N * 4 > 4 * 1024 * 1024


_precompute_jit = jax.jit(kernels.precompute_static)


def build_inputs(prep) -> Tuple[FastInputs, dict]:
    cached = getattr(prep, "_fast_inputs", None)
    if cached is not None:
        return cached
    # host-side numpy views: per-array np.asarray on device arrays costs a
    # tunnel RPC each, so use the retained numpy EncodedCluster and fetch the
    # static tables with one batched device_get
    ec = prep.ec_np if prep.ec_np is not None else jax.device_get(prep.ec)
    # static tables computed with ALL nodes valid: validity is applied as a
    # runtime row inside the kernel so scenario sweeps can mask nodes without
    # re-marshalling (static filters are per-node, so this is equivalent)
    import jax.numpy as jnp

    ec_all_valid = prep.ec._replace(node_valid=jnp.ones_like(prep.ec.node_valid))
    stat = jax.device_get(_precompute_jit(ec_all_valid))
    # static_fail diagnostics must count over the REAL valid set (the
    # all-valid tables would count padding nodes); one extra cached
    # precompute fetches just that small array
    static_fail_real = np.asarray(jax.device_get(_precompute_jit(prep.ec).static_fail))
    # the kernel needs a 128-lane node axis; pad every [*, N] table here
    # (padding nodes are invalid, domain-less, zero-capacity) and trim the
    # outputs back in schedule()/sweep()
    N_orig = int(ec.node_valid.shape[0])
    N = 128 * math.ceil(N_orig / 128)
    pad_n = N - N_orig

    def _padN(a, axis=-1, fill=0):
        a = np.asarray(a)
        if pad_n == 0:
            return a
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, pad_n)
        return np.pad(a, widths, constant_values=fill)

    U = int(ec.req.shape[0])
    A = int(ec.matches_sel.shape[1])
    R = int(ec.alloc.shape[1])
    vocab = prep.meta.vocab
    topo_keys = vocab.topo_keys.items()
    host_tk = topo_keys.index(HOSTNAME) if HOSTNAME in topo_keys else -1
    zone_tks = [i for i, k in enumerate(topo_keys) if k != HOSTNAME]

    trash = np.asarray(ec.domain_topo).shape[0] - 1
    node_domain = _padN(np.asarray(ec.node_domain), axis=0, fill=trash)

    # per-key zone one-hot blocks (dense, shared Z padded to 128 lanes);
    # topo-idx → key-index map: 0 = hostname, 1..K = zone keys in vocab order
    K = max(len(zone_tks), 1)
    if zone_tks:
        Z = max(
            128,
            128 * math.ceil(
                max(len(np.unique(node_domain[:, tk])) for tk in zone_tks) / 128
            ),
        )
    else:
        Z = 128
    # zone_NZ is [K, N, Z] (not [N, K*Z]): per-key blocks must start at lane
    # offset 0 — Mosaic cannot broadcast a vector sliced out of the flat
    # layout at lane offset k·Z
    zone_NZ = np.zeros((K, N, Z), np.float32)
    has_zone = np.zeros((K, N), np.float32)
    for ki, tk in enumerate(zone_tks):
        zd = node_domain[:, tk]
        _ids, zone_inv = np.unique(zd, return_inverse=True)
        present = zd != trash
        zone_NZ[ki, np.arange(N)[present], zone_inv[present]] = 1.0
        has_zone[ki] = present.astype(np.float32)
    zone_ZN = np.ascontiguousarray(
        zone_NZ.transpose(0, 2, 1).reshape(K * Z, N)
    )
    key_of_tk = {host_tk: 0}
    for ki, tk in enumerate(zone_tks):
        key_of_tk[tk] = ki + 1

    A_pad = max(8, 8 * math.ceil(A / 8))
    matches_AU = np.zeros((A_pad, U), np.float32)
    matches_AU[:A, :] = np.asarray(ec.matches_sel).T.astype(np.float32)

    spr_topo = np.asarray(ec.spr_topo)
    Cs = spr_topo.shape[1]
    spr_active = (spr_topo >= 0).astype(np.int32)
    _key_lut = np.zeros((max(len(topo_keys), 1) + 1,), np.int32)
    for tk, ki in key_of_tk.items():
        if tk >= 0:
            _key_lut[tk] = ki
    spr_key = _key_lut[np.maximum(spr_topo, 0)].astype(np.int32)
    spr_sel = np.maximum(np.asarray(ec.spr_sel), 0).astype(np.int32)
    spr_skew = np.asarray(ec.spr_skew).astype(np.float32)
    spr_hard = np.asarray(ec.spr_hard).astype(np.int32)
    matches_sel = np.asarray(ec.matches_sel)
    spr_self = np.zeros((U, Cs), np.float32)
    spread_weight = np.asarray(stat.spread_weight)
    spr_weight = np.zeros((U, Cs), np.float32)
    for u in range(U):
        for c in range(Cs):
            if spr_topo[u, c] >= 0:
                spr_self[u, c] = float(matches_sel[u, spr_sel[u, c]])
                spr_weight[u, c] = float(spread_weight[spr_topo[u, c]])

    # extension state, fetched in ONE batched device_get (per-array fetches
    # cost a tunnel RPC each), then transposed with sublane padding
    gpu_free0, vg_free0, dev_free0 = jax.device_get(
        (prep.st0.gpu_free, prep.st0.vg_free, prep.st0.dev_free)
    )

    def _padT(mat):  # [N_orig, K] -> [K_pad, N]
        mat = _padN(np.asarray(mat), axis=0)
        Kp = _pad8_static(mat.shape[1])
        out_m = np.zeros((Kp, mat.shape[0]), np.float32)
        out_m[: mat.shape[1]] = mat.T.astype(np.float32)
        return out_m

    gpu0_DN = _padT(gpu_free0)
    Gd_pad = gpu0_DN.shape[0]
    vg_cap_VN = _padT(prep.meta.node_vg_cap)
    vg0_VN = _padT(vg_free0)
    dev_cap_DN = _padT(prep.meta.node_dev_cap)
    dev0_DN = _padT(dev_free0)
    media = _padN(np.asarray(prep.meta.node_dev_media), axis=0, fill=-1)  # [N, Dv]
    Dv_pad = dev_cap_DN.shape[0]
    dev_media_DN = np.zeros((2 * Dv_pad, N), np.float32)
    for m in range(2):
        dev_media_DN[m * Dv_pad : m * Dv_pad + media.shape[1]] = (media.T == m).astype(np.float32)

    req_np = np.asarray(ec.req).astype(np.float32)
    cpu_nz = np.where(req_np[:, V.RES_CPU] > 0, req_np[:, V.RES_CPU], 100.0).astype(np.float32)
    mem_nz = np.where(req_np[:, V.RES_MEMORY] > 0, req_np[:, V.RES_MEMORY], 200.0 * 1024 * 1024).astype(
        np.float32
    )

    # inter-pod term tables: per-template incoming terms + padded global
    # existing-term rows (host flag, carried weights, selector matches)
    def terms(sel_arr, topo_arr):
        sel = np.asarray(sel_arr)
        topo = np.asarray(topo_arr)
        active = (sel >= 0).astype(np.int32)
        key = _key_lut[np.maximum(np.asarray(topo), 0)].astype(np.int32)
        return active, key, np.maximum(sel, 0).astype(np.int32)

    # host-port rows: [Hp_pad, U] template multi-hot
    ports_u = np.asarray(ec.ports)  # [U, Hp_tmpl] port vocab ids, -1 pad
    n_port_vocab = int(ports_u.max()) + 1 if ports_u.size and ports_u.max() >= 0 else 0
    Hp_pad = _pad8_static(max(n_port_vocab, 1))
    port_HU = np.zeros((Hp_pad, U), np.float32)
    for u_i in range(ports_u.shape[0]):
        for h in ports_u[u_i]:
            if h >= 0:
                port_HU[int(h), u_i] += 1.0
    # filter-side rows expand each template's ports to every CONFLICTING
    # vocab id (wildcard hostIP overlaps specific ones — nodeports.go);
    # the bind update keeps port_HU so only the pod's own triples are marked
    conf = np.asarray(ec.port_conflict).astype(np.float32)  # [Hv, Hv]
    port_conf_HU = np.zeros_like(port_HU)
    if n_port_vocab:
        port_conf_HU[:n_port_vocab] = (
            conf[:n_port_vocab, :n_port_vocab] @ port_HU[:n_port_vocab] > 0
        ).astype(np.float32)

    at_active, at_key, at_sel = terms(ec.at_sel, ec.at_topo)
    an_active, an_key, an_sel = terms(ec.an_sel, ec.an_topo)
    pt_active, pt_key, pt_sel = terms(ec.pt_sel, ec.pt_topo)
    at_self = np.where(at_active == 1, np.take_along_axis(matches_sel, at_sel, axis=1), 0.0).astype(
        np.float32
    )
    pt_w = np.asarray(ec.pt_w).astype(np.float32)

    g_sel = np.asarray(ec.anti_g_sel)
    g_topo = np.asarray(ec.anti_g_topo)
    G = g_sel.shape[0]
    G_pad = _pad8_static(G)
    anti_g_key = np.zeros((G_pad,), np.int32)
    antig_GU = np.zeros((G_pad, U), np.float32)
    gmatch_GU = np.zeros((G_pad, U), np.float32)
    anti_carry = np.asarray(ec.anti_g).astype(np.float32)  # [U, G]
    for g in range(G):
        anti_g_key[g] = int(_key_lut[max(int(g_topo[g]), 0)])
        antig_GU[g] = anti_carry[:, g]
        gmatch_GU[g] = matches_sel[:, g_sel[g]].astype(np.float32)
    p_sel = np.asarray(ec.prefg_sel)
    p_topo = np.asarray(ec.prefg_topo)
    Gp = p_sel.shape[0]
    Gp_pad = _pad8_static(Gp)
    prefg_key = np.zeros((Gp_pad,), np.int32)
    prefg_GU = np.zeros((Gp_pad, U), np.float32)
    pmatch_GU = np.zeros((Gp_pad, U), np.float32)
    pref_carry = np.asarray(ec.prefg_w).astype(np.float32)  # [U, Gp]
    for g in range(Gp):
        prefg_key[g] = int(_key_lut[max(int(p_topo[g]), 0)])
        prefg_GU[g] = pref_carry[:, g]
        pmatch_GU[g] = matches_sel[:, p_sel[g]].astype(np.float32)

    fi = FastInputs(
        alloc_T=np.ascontiguousarray(_padN(ec.alloc, axis=0).T.astype(np.float32)),
        used0_T=np.ascontiguousarray(_padN(jax.device_get(prep.st0.used), axis=0).T.astype(np.float32)),
        static_pass=_padN(stat.static_pass).astype(np.float32),
        aff_mask=_padN(stat.aff_mask).astype(np.float32),
        share_raw=_padN(stat.share_raw).astype(np.float32),
        zone_NZ=zone_NZ,
        zone_ZN=zone_ZN,
        has_zone=has_zone,
        matches_AU=matches_AU,
        node_valid=_padN(ec.node_valid, axis=0).astype(np.float32)[None, :],
        req=req_np,
        cpu_nz=cpu_nz,
        mem_nz=mem_nz,
        pin=np.asarray(ec.pin).astype(np.int32),
        spr_active=spr_active,
        spr_key=spr_key,
        spr_sel=spr_sel,
        spr_skew=spr_skew,
        spr_hard=spr_hard,
        spr_self=spr_self,
        spr_weight=spr_weight,
        at_active=at_active,
        at_key=at_key,
        at_sel=at_sel,
        at_self=at_self,
        an_active=an_active,
        an_key=an_key,
        an_sel=an_sel,
        pt_active=pt_active,
        pt_key=pt_key,
        pt_sel=pt_sel,
        pt_w=pt_w,
        anti_g_key=anti_g_key,
        prefg_key=prefg_key,
        antig_GU=antig_GU,
        gmatch_GU=gmatch_GU,
        prefg_GU=prefg_GU,
        pmatch_GU=pmatch_GU,
        gpu_mem=np.asarray(ec.gpu_mem).astype(np.float32),
        gpu_cnt=np.asarray(ec.gpu_count).astype(np.float32),
        gpu0_DN=gpu0_DN,
        lvm_req=np.asarray(ec.lvm_req).astype(np.float32),
        dev_req=np.asarray(ec.dev_req).astype(np.float32),
        dev_need=np.asarray(ec.dev_req_count).astype(np.float32),
        dev_sizes=np.asarray(ec.dev_req_sizes).reshape(ec.dev_req_sizes.shape[0], -1).astype(np.float32),
        vg_cap_VN=vg_cap_VN,
        vg0_VN=vg0_VN,
        dev_cap_DN=dev_cap_DN,
        dev0_DN=dev0_DN,
        dev_media_DN=dev_media_DN,
        port_HU=port_HU,
        port_conf_HU=port_conf_HU,
        na_raw=_padN(stat.na_raw).astype(np.float32),
        tt_raw=_padN(stat.tt_raw).astype(np.float32),
        avoid_raw=_padN(ec.avoid_score).astype(np.float32),
    )
    meta = {"static_fail": static_fail_real, "n_orig": N_orig}
    # device-resident copies so repeated runs (capacity loops, sweeps) skip
    # the host→device transfer of ~25 arrays
    fi = FastInputs(*[jax.numpy.asarray(a) for a in fi])
    try:
        prep._fast_inputs = (fi, meta)
    except AttributeError:
        pass
    return fi, meta


class _SweepContext:
    """Host-side tables hoisted out of the per-scenario loop."""

    def __init__(self, prep) -> None:
        ec = prep.ec_np if prep.ec_np is not None else jax.device_get(prep.ec)
        self.node_domain = np.asarray(ec.node_domain)
        self.trash = np.asarray(ec.domain_topo).shape[0] - 1
        self.spr_topo = np.asarray(ec.spr_topo)
        self.log_sizes = np.asarray(ec.log_sizes)

    def spread_weights(self, node_valid: np.ndarray) -> np.ndarray:
        """[U, Cs] log(size+2) table for a scenario's valid-node subset
        (domain counts are valid-set dependent). Weights come from the
        shared ec.log_sizes lookup so they are bitwise-identical to every
        other engine's."""
        Tk = self.node_domain.shape[1]
        sizes = np.zeros((Tk,), np.int64)
        for tk in range(Tk):
            doms = self.node_domain[node_valid, tk]
            sizes[tk] = len(np.unique(doms[doms != self.trash]))
        weights = self.log_sizes[np.clip(sizes, 0, self.log_sizes.shape[0] - 1)]
        return np.where(
            self.spr_topo >= 0, weights[np.maximum(self.spr_topo, 0)], 0.0
        ).astype(np.float32)


def sweep(
    prep, node_valid_masks, pod_valid_masks, forced_masks,
    interpret: Optional[bool] = None, big_u: Optional[bool] = None,
):
    """Scenario sweep on the megakernel: ALL scenarios in ONE batched
    dispatch — ``jax.vmap`` over the per-scenario inputs (node validity,
    spread weights, pod masks) prepends a scenario axis to the kernel grid,
    so S scans run back-to-back in a single Pallas program with no
    per-scenario dispatch overhead (the shared template/state tables are
    not duplicated: unbatched operands keep their block mappings). Returns
    (unscheduled [S], used [S, N, R], chosen [S, P], vg_used [S]) matching
    parallel.scenarios.SweepResult. `big_u=None` defers to the use_big_u
    heuristic (tests override it to exercise the HBM-DMA path on small
    shapes)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fi, meta = build_inputs(prep)
    if big_u is None:
        big_u = use_big_u(*fi.static_pass.shape)
    S = node_valid_masks.shape[0]
    P = pod_valid_masks.shape[1]
    pad = (-P) % CHUNK
    tmpl = np.asarray(prep.tmpl_ids)
    if pad:
        tmpl = np.concatenate([tmpl, np.zeros(pad, tmpl.dtype)])
    ctx = _SweepContext(prep)
    vg0 = np.asarray(fi.vg0_VN)
    N_orig = meta["n_orig"]
    N_pad = int(fi.node_valid.shape[1])

    nv_all = np.zeros((S, N_pad), bool)
    nv_all[:, :N_orig] = np.asarray(node_valid_masks, dtype=bool)
    pv_all = np.zeros((S, P + pad), bool)
    pv_all[:, :P] = np.asarray(pod_valid_masks, dtype=bool)
    fm_all = np.zeros((S, P + pad), bool)
    fm_all[:, :P] = np.asarray(forced_masks, dtype=bool)
    sw_all = np.stack(
        [ctx.spread_weights(nv_all[s, :N_orig]) for s in range(S)]
    )

    def one(nv_row, sw, pv, fm):
        return run_fast_scan(
            fi._replace(node_valid=nv_row, spr_weight=sw), tmpl, pv, fm,
            has_interpod=bool(prep.features.interpod or prep.features.prefg),
            has_gpu=bool(prep.features.gpu),
            has_local=bool(prep.features.local),
            has_ports=bool(prep.features.ports),
            has_na=bool(prep.features.pref_node_affinity),
            has_tt=bool(prep.features.prefer_taints),
            has_avoid=bool(prep.features.prefer_avoid),
            interpret=interpret,
            big_u=big_u,
            gc_row=_gc_row(prep),
        )

    import jax.numpy as jnp

    chosen_b, used_b, _gt, _gf, vg_b, _dev = jax.vmap(one)(
        jnp.asarray(nv_all.astype(np.float32)[:, None, :]),
        jnp.asarray(sw_all),
        jnp.asarray(pv_all),
        jnp.asarray(fm_all),
    )

    chosen_all = np.asarray(chosen_b)[:, :P]
    unscheduled = ((chosen_all < 0) & pv_all[:, :P]).sum(axis=1).astype(np.int32)
    used = np.asarray(used_b).transpose(0, 2, 1)[:, :N_orig]
    # per the XLA sweep, VG usage counts only scenario-valid nodes
    vg_used = ((vg0[None] - np.asarray(vg_b)) * nv_all[:, None, :]).sum(
        axis=(1, 2)
    ).astype(np.float32)
    return unscheduled, used, chosen_all, vg_used


def schedule(
    prep, tmpl_ids, pod_valid, forced,
    interpret: Optional[bool] = None, big_u: Optional[bool] = None,
):
    """Run the megakernel on a padded pod stream (P % CHUNK == 0).
    Returns (chosen [P] i32, used_final [N, R], static_fail [U, 4],
    gpu_take [P, Gd], gpu_free [N, Gd], vg_free [N, Vg], dev_free [N, Dv]).
    `big_u=None` defers to the use_big_u heuristic."""
    from ..resilience import faults

    # stands in for a Mosaic compile failure (a construct passing interpret
    # mode but not the real compiler) — simulate()'s ladder demotes, or
    # fails hard under OPENSIM_REQUIRE_TPU=1 (chaos suite)
    faults.fault_point("engine.compile")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fi, meta = build_inputs(prep)
    if big_u is None:
        big_u = use_big_u(*fi.static_pass.shape)
    tmpl_ids = np.asarray(tmpl_ids)
    pod_valid = np.asarray(pod_valid)
    forced = np.asarray(forced)
    P = len(tmpl_ids)
    pad = (-P) % CHUNK
    if pad:
        tmpl_ids = np.concatenate([tmpl_ids, np.zeros(pad, tmpl_ids.dtype)])
        pod_valid = np.concatenate([pod_valid, np.zeros(pad, bool)])
        forced = np.concatenate([forced, np.zeros(pad, bool)])
    has_interpod = bool(prep.features.interpod or prep.features.prefg)
    has_gpu = bool(prep.features.gpu)
    has_local = bool(prep.features.local)
    chosen, used_T, gpu_take, gpu_T, vg_T, dev_T = run_fast_scan(
        fi, tmpl_ids, pod_valid, forced,
        has_interpod=has_interpod, has_gpu=has_gpu, has_local=has_local,
        has_ports=bool(prep.features.ports),
        has_na=bool(prep.features.pref_node_affinity),
        has_tt=bool(prep.features.prefer_taints),
        has_avoid=bool(prep.features.prefer_avoid),
        interpret=interpret,
        big_u=big_u,
        gc_row=_gc_row(prep),
    )
    Gd = int(prep.st0.gpu_free.shape[1])
    Vg = int(prep.st0.vg_free.shape[1])
    Dv = int(prep.st0.dev_free.shape[1])
    No = meta["n_orig"]  # lane padding added in build_inputs is trimmed here
    return (
        np.asarray(chosen)[:P],
        np.asarray(used_T).T[:No],
        meta["static_fail"],
        np.asarray(gpu_take)[:P, :Gd],
        np.asarray(gpu_T)[:Gd].T[:No],
        np.asarray(vg_T)[:Vg].T[:No],
        np.asarray(dev_T)[:Dv].T[:No],
    )
