"""The scan-bind engine.

``schedule_pods`` drives the whole pod queue through one fused, jitted
``lax.scan``: each step runs every filter/score kernel across the full node
axis, picks the best node, and folds the bind back into the carry. This
replaces the reference's serial driver↔scheduler rendezvous
(``pkg/simulator/simulator.go:309-348``: create pod → block on
``simulatorStop`` channel → informer update) with a pure state transition —
no channels, no goroutines, no fake apiserver.

Determinism note: the reference tie-breaks equal-score nodes by reservoir
sampling (``generic_scheduler.go:188-210``, nondeterministic); we take the
lowest node index. Structural results (counts, feasibility) are identical.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..encoding.state import EncodedCluster, ScanState
from ..ops import kernels


class ScheduleOutput(NamedTuple):
    chosen: jnp.ndarray  # [P] i32 node index, -1 unscheduled
    fail_counts: jnp.ndarray  # [P, NUM_FILTERS] i32
    insufficient: jnp.ndarray  # [P, R] i32 nodes short per resource
    final_state: ScanState


def _step(ec: EncodedCluster, st: ScanState, x):
    u, pod_valid, forced = x
    res = kernels.pod_step(ec, st, u)
    # Pre-bound pods (spec.nodeName set) bypass the scheduler in the
    # reference (simulator.go:329-331 only waits for unbound pods): they
    # always land on their node and still consume its resources.
    pin = ec.pin[u]
    chosen = jnp.where(forced, jnp.where(pin >= 0, pin, -1), res.chosen)
    do_bind = pod_valid & (chosen >= 0)
    node = jnp.maximum(chosen, 0)
    st_bound = kernels.bind_update(ec, st, u, node)
    st_next = jax.tree_util.tree_map(
        lambda a, b: jnp.where(do_bind, b, a), st, st_bound
    )
    chosen = jnp.where(do_bind, chosen, -1)
    return st_next, (chosen, res.fail_counts, res.insufficient)


@functools.partial(jax.jit, static_argnames=("unroll",))
def schedule_pods(ec: EncodedCluster, st0: ScanState, tmpl_ids, pod_valid, forced, unroll: int = 1):
    """Run the bind scan. tmpl_ids [P] i32, pod_valid/forced [P] bool."""
    step = functools.partial(_step, ec)
    final_state, (chosen, fail_counts, insufficient) = jax.lax.scan(
        step, st0, (tmpl_ids, pod_valid, forced), unroll=unroll
    )
    return ScheduleOutput(
        chosen=chosen,
        fail_counts=fail_counts,
        insufficient=insufficient,
        final_state=final_state,
    )


def to_device(ec: EncodedCluster, st: ScanState):
    """Move numpy-built tensors to the accelerator once per simulation."""
    dev = lambda a: jnp.asarray(a)
    return (
        EncodedCluster(*[dev(a) for a in ec]),
        ScanState(*[dev(a) for a in st]),
    )
