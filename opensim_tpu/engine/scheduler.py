"""The scan-bind engine.

``schedule_pods`` drives the whole pod queue through one fused, jitted
``lax.scan``: each step runs every filter/score kernel across the full node
axis, picks the best node, and folds the bind back into the carry. This
replaces the reference's serial driver↔scheduler rendezvous
(``pkg/simulator/simulator.go:309-348``: create pod → block on
``simulatorStop`` channel → informer update) with a pure state transition —
no channels, no goroutines, no fake apiserver.

Determinism note: the reference tie-breaks equal-score nodes by reservoir
sampling (``generic_scheduler.go:188-210``, nondeterministic); we take the
lowest node index by default. Structural results (counts, feasibility) are
identical. The opt-in ``tie_seed`` (CLI ``--tie-break=sample[:seed]``)
reproduces the reference's sampled distribution — seeded and reproducible —
for distribution-level comparison runs; it forces the XLA scan (the
megakernel and C++ engines stay lowest-index).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..encoding.state import EncodedCluster, ScanState
from ..utils import envknobs
from ..ops import kernels


def scan_unroll() -> int:
    """The OPENSIM_SCAN_UNROLL tuning knob (accelerator runs: amortizes
    per-iteration dispatch; neutral-to-negative on CPU). Positive integer,
    default 1. Resolved OUTSIDE jit by every scan entry point so the value
    participates in the jit cache key."""
    raw = envknobs.raw("OPENSIM_SCAN_UNROLL", "1")
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"OPENSIM_SCAN_UNROLL must be a positive integer, got {raw!r}"
        ) from None
    if val < 1:
        raise ValueError(f"OPENSIM_SCAN_UNROLL must be >= 1, got {raw!r}")
    return val


class ScheduleOutput(NamedTuple):
    chosen: jnp.ndarray  # [P] i32 node index, -1 unscheduled
    fail_counts: jnp.ndarray  # [P, NUM_FILTERS-4] i32 — dynamic filters (ports..extra)
    insufficient: jnp.ndarray  # [P, R] i32 nodes short per resource
    gpu_take: jnp.ndarray  # [P, Gd] f32 GPU slots packed per device
    static_fail: jnp.ndarray  # [U, 4] i32 — static filters (pin/unsched/taint/affinity)
    final_state: ScanState
    # C++ engine only: which evaluation path ran ({"path", "steps",
    # "profile"?}) — attribution so a silent incremental-cache disengage
    # can never masquerade as a tuned number (None on the XLA/fast paths)
    native_stats: Optional[dict] = None
    # decision audit (explain mode, ISSUE 7): 11-slot per-filter reject
    # totals accumulated across every scheduled step. The C++ engine fills
    # this in-engine (ScanArgs.filter_rejects); the XLA path derives it
    # host-side from the count_all per-pod rows (simulator._audit_rejects)
    filter_rejects: Optional[object] = None


def _step(ec: EncodedCluster, stat, feat, cfg, extra, st: ScanState, x, select_key=None,  # opensim-lint: jit-region
          count_all=False):
    u, pod_valid, forced = x
    # Pre-bound pods (spec.nodeName set) bypass the scheduler in the
    # reference (simulator.go:329-331 only waits for unbound pods): they
    # always land on their node and still consume its resources — so the
    # whole filter/score pipeline is skipped via lax.cond (live-cluster
    # snapshots replay thousands of forced binds per request).
    n_dyn = kernels.NUM_FILTERS - kernels.F_PORTS
    R = ec.alloc.shape[1]

    def run_pipeline(_):
        res = kernels.pod_step(ec, stat, st, u, feat, cfg, extra, count_all=count_all)
        if select_key is None:
            return res.chosen, res.fail_counts, res.insufficient
        # --tie-break=sample: uniform choice among the score maxima — the
        # distribution of selectHost's reservoir sampling
        # (generic_scheduler.go:188-210) instead of the deterministic
        # lowest-index default
        neg = jnp.float32(-1e30)
        masked = jnp.where(res.feasible, res.score, neg)
        eq = res.feasible & (masked == jnp.max(masked))
        r = jax.random.uniform(select_key, masked.shape)
        pick = jnp.argmax(jnp.where(eq, r, -1.0)).astype(jnp.int32)
        chosen = jnp.where(jnp.any(res.feasible), pick, jnp.int32(-1))
        return chosen, res.fail_counts, res.insufficient

    def skip_pipeline(_):
        return (
            jnp.int32(-1),
            jnp.zeros((n_dyn,), jnp.int32),
            jnp.zeros((R,), jnp.int32),
        )

    picked, fail_counts, insufficient = jax.lax.cond(forced, skip_pipeline, run_pipeline, None)
    pin = ec.pin[u]
    chosen = jnp.where(forced, jnp.where(pin >= 0, pin, -1), picked)
    do_bind = pod_valid & (chosen >= 0)
    node = jnp.maximum(chosen, 0)
    st_next, gpu_take = kernels.bind_update(ec, st, u, node, do_bind, feat)
    chosen = jnp.where(do_bind, chosen, -1)
    return st_next, (chosen, fail_counts, insufficient, gpu_take)


@functools.partial(
    jax.jit,
    static_argnames=("features", "config", "extra_plugins", "unroll", "tie_seed", "explain"),
)
def _schedule_pods_jit(
    ec: EncodedCluster,
    st0: ScanState,
    tmpl_ids,
    pod_valid,
    forced,
    features: kernels.Features = kernels.ALL_FEATURES,
    config=None,
    extra_plugins: tuple = (),
    unroll: int = 1,
    tie_seed=None,
    explain: bool = False,
):
    """Run the bind scan. tmpl_ids [P] i32, pod_valid/forced [P] bool.

    Static per-(template, node) filter/score tables are computed once up
    front; the scan body only evaluates usage-dependent kernels the
    workload's `features` actually exercise. `tie_seed` (an int) switches
    selectHost to the reference's sampled tie-break: a PRNG key rides the
    scan carry and every step draws uniformly over its score maxima.
    `explain` (decision audit, ISSUE 7) makes every step emit its per-filter
    reject counts instead of only failed steps — a separate trace, so the
    default compile is unchanged."""
    from .schedconfig import DEFAULT_CONFIG

    config = config or DEFAULT_CONFIG
    stat = kernels.precompute_static(ec, config)
    if tie_seed is None:
        step = functools.partial(
            _step, ec, stat, features, config, extra_plugins, count_all=explain
        )
        final_state, (chosen, fail_counts, insufficient, gpu_take) = jax.lax.scan(
            step, st0, (tmpl_ids, pod_valid, forced), unroll=unroll
        )
    else:
        def step(carry, x):
            st, key = carry
            key, sub = jax.random.split(key)
            st_next, out = _step(
                ec, stat, features, config, extra_plugins, st, x, select_key=sub,
                count_all=explain,
            )
            return (st_next, key), out

        (final_state, _), (chosen, fail_counts, insufficient, gpu_take) = jax.lax.scan(
            step, (st0, jax.random.PRNGKey(int(tie_seed))),
            (tmpl_ids, pod_valid, forced), unroll=unroll,
        )
    return ScheduleOutput(
        chosen=chosen,
        fail_counts=fail_counts,
        insufficient=insufficient,
        gpu_take=gpu_take,
        static_fail=stat.static_fail,
        final_state=final_state,
    )


def schedule_pods(
    ec: EncodedCluster,
    st0: ScanState,
    tmpl_ids,
    pod_valid,
    forced,
    features: kernels.Features = kernels.ALL_FEATURES,
    config=None,
    extra_plugins: tuple = (),
    unroll: int = 1,
    tie_seed=None,
    explain: bool = False,
):
    """:func:`_schedule_pods_jit` through the compile watch (ISSUE 12,
    obs/profile.py): every host-side call records its abstract signature,
    and a jit-cache miss records compile seconds with recompile-cause
    attribution (shape vs dtype vs static-flag change). Calls arriving
    UNDER tracing (the vmapped sweeps invoke this inside their own jit)
    pass straight through — the outer sweep boundary is instrumented
    instead."""
    from ..obs.profile import observed_jit_call

    return observed_jit_call(
        "schedule_pods",
        _schedule_pods_jit,
        args=(ec, st0, tmpl_ids, pod_valid, forced),
        static={
            "features": features,
            "config": config,
            "extra_plugins": extra_plugins,
            "unroll": unroll,
            "tie_seed": tie_seed,
            "explain": explain,
        },
    )


def pad_pod_stream(tmpl_ids, pod_valid, forced, bucket: int = 256):
    """Pad the pod stream to a bucket multiple so scan lengths (and thus jit
    signatures) repeat across runs — SURVEY.md §7 'pad P and N to buckets to
    avoid per-run jit recompiles'. Padded steps have pod_valid=False and
    never bind."""
    import numpy as np

    P = len(tmpl_ids)
    target = max(bucket, bucket * ((P + bucket - 1) // bucket))
    pad = target - P
    if pad == 0:
        return tmpl_ids, pod_valid, forced
    return (
        np.concatenate([tmpl_ids, np.zeros(pad, dtype=tmpl_ids.dtype)]),
        np.concatenate([pod_valid, np.zeros(pad, dtype=bool)]),
        np.concatenate([forced, np.zeros(pad, dtype=bool)]),
    )


def to_device(ec: EncodedCluster, st: ScanState):
    """Move numpy-built tensors to the accelerator once per simulation."""
    from ..obs import trace as obs
    from ..resilience import faults

    with obs.span("engine.device_put"):
        # chaos injection point for device loss / transfer failure: upstream
        # a failed upload fails the request closed (typed 500) — there is no
        # stale-tensor fallback that would be correct
        faults.fault_point("engine.device_put")
        dev = lambda a: jnp.asarray(a)
        return (
            EncodedCluster(*[dev(a) for a in ec]),
            ScanState(*[dev(a) for a in st]),
        )
