"""Opt-in preemption — the PostFilter the reference registers but never
exercises.

The reference's scheduler profile includes ``DefaultPreemption``
(vendored ``algorithmprovider/registry.go:104``), but its driver deletes every
unschedulable pod before a retry could run the nominated placement
(``pkg/simulator/simulator.go:333-342``), so the PostFilter is vacuous there
(PARITY.md, divergence 6). This module implements the intent as a
what-if-capable pass: after the bind scan, each unschedulable pod with a
positive ``spec.priority`` searches nodes where evicting strictly
lower-priority pods frees enough resources, mirroring the shape of
``dryRunPreemption`` → ``SelectVictimsOnNode`` → ``pickOneNodeForPreemption``
(vendored ``defaultpreemption/default_preemption.go``).

Scope (documented simplifications):
- victims are selected ascending by priority until the preemptor's resource
  request fits (no PDB accounting — the simulator has no eviction API);
- candidate nodes are ranked by (fewest victims, lowest summed victim
  priority, lowest node index) — a deterministic stand-in for
  ``pickOneNodeForPreemption``'s tie-break ladder;
- eligibility uses the static filters (unschedulable/taints/affinity/
  nodeName) plus resource fit; feature filters that depend on *other* pods
  (anti-affinity, spread) are re-checked conservatively by requiring the
  preemptor to have none of those constraints when they are active;
- victims are restricted to plain resource consumers: pods holding GPU
  devices, host ports, or local storage are skipped (their release is not
  re-packed), as are pods matched by any inter-pod/spread selector (another
  placement may depend on them as an affinity anchor or domain count);
- force-bound (pre-existing) pods are never victims.

Off by default: ``simulate(..., enable_preemption=True)`` or
``simon apply --enable-preemption``.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..models import selectors
from ..models.objects import Node, Pod


def _static_ok(pod: Pod, node: Node) -> bool:
    if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
        return False
    if node.unschedulable:
        return False
    if not selectors.pod_matches_node_selector_and_affinity(pod, node):
        return False
    taints = [t for t in node.taints if t.effect in ("NoSchedule", "NoExecute")]
    return selectors.find_untolerated_taint(taints, pod.spec.tolerations) is None


def preempt_pass(
    prep,
    chosen: np.ndarray,
    nodes: List[Node],
    used: np.ndarray,
    alloc: np.ndarray,
) -> Tuple[np.ndarray, Dict[int, int]]:
    """Attempt preemption for every unscheduled, positive-priority pod in
    stream order. Returns the updated ``chosen`` and a map of
    victim-stream-index → preemptor-stream-index. ``used``/``alloc`` are the
    encoded ``[N, R]`` resource tensors (mutated in place on success)."""
    ec = prep.ec_np
    tmpl = prep.tmpl_ids
    forced = prep.forced
    ordered = prep.ordered
    req = np.asarray(ec.req)  # [U, R]
    prio = np.array([p.spec.priority for p in ordered], dtype=np.int64)
    n_real = len(nodes)
    victims_of: Dict[int, int] = {}

    # pods with inter-pod/spread constraints interact with evictions in ways
    # this pass does not model — skip preemption for those preemptors
    at_sel = np.asarray(ec.at_sel)
    an_sel = np.asarray(ec.an_sel)
    spr_topo = np.asarray(ec.spr_topo)
    spr_hard = np.asarray(ec.spr_hard)
    gpu_mem = np.asarray(ec.gpu_mem)
    lvm_req = np.asarray(ec.lvm_req)
    dev_req = np.asarray(ec.dev_req)
    ports = np.asarray(ec.ports)

    def constrained(u: int) -> bool:
        # constraints whose post-eviction state this pass does not model:
        # inter-pod terms, hard spread, host ports, GPU devices, local storage
        return bool(
            (at_sel[u] >= 0).any()
            or (an_sel[u] >= 0).any()
            or ((spr_topo[u] >= 0) & spr_hard[u]).any()
            or (ports[u] >= 0).any()
            or gpu_mem[u] > 0
            or lvm_req[u] > 0
            or (dev_req[u] > 0).any()
        )

    matches_sel = np.asarray(ec.matches_sel)
    sel_features = bool(prep.features.sel_counts)

    def victim_ok(u: int) -> bool:
        # only plain resource consumers release cleanly: no device/port/
        # storage holdings, and — when inter-pod/spread constraints exist
        # anywhere in the workload — no selector matches this pod (another
        # placement may depend on it as an anchor or domain count)
        if gpu_mem[u] > 0 or lvm_req[u] > 0 or (dev_req[u] > 0).any() or (ports[u] >= 0).any():
            return False
        return not (sel_features and matches_sel[u].any())

    chosen = chosen.copy()
    # node → evictable bound-pod indices, built once and maintained
    # incrementally (a full per-node rescan would be O(pods × nodes) per
    # unschedulable pod)
    by_node: Dict[int, List[int]] = {}
    for j in range(len(ordered)):
        if chosen[j] >= 0 and not forced[j] and victim_ok(int(tmpl[j])):
            by_node.setdefault(int(chosen[j]), []).append(j)
    for i in range(len(ordered)):
        if chosen[i] >= 0 or forced[i] or prio[i] <= 0:
            continue
        u = int(tmpl[i])
        if constrained(u):
            continue
        best = None  # (n_victims, sum_prio, node, victim_indices)
        for n in range(n_real):
            if not _static_ok(ordered[i], nodes[n]):
                continue
            cand = [j for j in by_node.get(n, []) if prio[j] < prio[i]]
            cand.sort(key=lambda j: (prio[j], j))
            free = alloc[n] - used[n]
            taken: List[int] = []
            freed = np.zeros_like(free)
            for j in cand:
                if np.all(req[u] <= free + freed):
                    break
                freed = freed + req[int(tmpl[j])]
                taken.append(j)
            if not np.all(req[u] <= free + freed):
                continue  # even evicting every candidate is not enough
            key = (len(taken), int(sum(prio[j] for j in taken)), n)
            if best is None or key < best[:3]:
                best = (*key, taken)
        if best is None:
            continue
        _, _, n, taken = best
        for j in taken:
            victims_of[j] = i
            used[n] -= req[int(tmpl[j])]
            chosen[j] = -1
        taken_set = set(taken)
        by_node[n] = [j for j in by_node.get(n, []) if j not in taken_set]
        used[n] += req[u]
        chosen[i] = n
        if victim_ok(u):
            by_node[n].append(i)  # the preemptor may itself be preempted later
    return chosen, victims_of
