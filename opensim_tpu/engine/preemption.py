"""Opt-in preemption — the PostFilter the reference registers but never
exercises.

The reference's scheduler profile includes ``DefaultPreemption``
(vendored ``algorithmprovider/registry.go:104``), but its driver deletes every
unschedulable pod before a retry could run the nominated placement
(``pkg/simulator/simulator.go:333-342``), so the PostFilter is vacuous there
(PARITY.md, divergence 6). This module implements the intent as a
what-if-capable pass: after the bind scan, each unschedulable pod with a
positive ``spec.priority`` searches nodes where evicting strictly
lower-priority pods frees enough resources, mirroring the shape of
``dryRunPreemption`` → ``SelectVictimsOnNode`` → ``pickOneNodeForPreemption``
(vendored ``defaultpreemption/default_preemption.go``).

Modeled dimensions:
- CPU/memory/extended resources (victims free their requests);
- host ports (victims free their ports; the preemptor's ports are checked
  through the wildcard-aware conflict matrix);
- fractional GPU devices (victims free the exact per-device slots recorded
  at bind time in ``gpu_take``; the preemptor is re-packed with the same
  tightest-fit / greedy rules as ``kernels.bind_update``);
- open-local storage for the PREEMPTOR (tightest-fit VG + smallest-fitting
  exclusive devices) — storage-holding pods are never victims (their VG
  allocation is not tracked per pod, so it cannot be released exactly);
- cascading re-placement: evicted victims are re-queued in stream order and
  re-placed on the lowest-index feasible node when capacity exists
  elsewhere, mirroring a nominated pod re-entering the scheduling queue.

Remaining documented simplifications:
- victims are selected ascending by priority until everything fits (no PDB
  accounting — the simulator has no eviction API);
- candidate nodes are ranked by (fewest victims, lowest summed victim
  priority, lowest node index) — a deterministic stand-in for
  ``pickOneNodeForPreemption``'s tie-break ladder;
- preemptors carrying required inter-pod terms or hard spread constraints
  are skipped, as are preemptors matched by an existing pod's global
  anti-affinity term (placing one would retroactively violate the
  symmetric check);
- when inter-pod/spread selectors exist anywhere in the workload, pods
  matched by any selector are never victims (another placement may depend
  on them as an affinity anchor or domain count);
- force-bound (pre-existing) pods are never victims.

Off by default: ``simulate(..., enable_preemption=True)`` or
``simon apply --enable-preemption``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import selectors
from ..models.objects import Node, Pod


def _static_ok(pod: Pod, node: Node) -> bool:
    if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
        return False
    if node.unschedulable:
        return False
    if not selectors.pod_matches_node_selector_and_affinity(pod, node):
        return False
    taints = [t for t in node.taints if t.effect in ("NoSchedule", "NoExecute")]
    return selectors.find_untolerated_taint(taints, pod.spec.tolerations) is None


class _State:
    """Mutable per-node resource view shared by eviction, placement, and
    cascade — numpy rows of the final ScanState (mutated in place)."""

    def __init__(self, ec, used, alloc, port_used, gpu_free, vg_free, dev_free, gpu_take):
        self.ec = ec
        self.used = used
        self.alloc = alloc
        self.port_used = port_used
        self.gpu_free = gpu_free
        self.vg_free = vg_free
        self.dev_free = dev_free
        self.gpu_take = gpu_take
        self.req = np.asarray(ec.req)
        self.ports = np.asarray(ec.ports)
        self.conflict = np.asarray(ec.port_conflict)
        self.gpu_mem = np.asarray(ec.gpu_mem)
        self.gpu_count = np.asarray(ec.gpu_count)
        self.lvm_req = np.asarray(ec.lvm_req)
        self.dev_req_sizes = np.asarray(ec.dev_req_sizes)
        self.node_dev_media = np.asarray(ec.node_dev_media)
        self.node_dev_cap = np.asarray(ec.node_dev_cap)
        self.Hports = port_used.shape[1] if port_used.ndim == 2 else 0

    def port_hot(self, u: int) -> np.ndarray:
        ids = self.ports[u]
        ids = ids[ids >= 0]
        if self.Hports == 0 or ids.size == 0:
            return np.zeros((self.Hports,), np.float32)
        return np.bincount(ids, minlength=self.Hports).astype(np.float32)

    def ports_ok(self, u: int, n: int, freed: np.ndarray) -> bool:
        """NodePorts with the wildcard-aware conflict matrix
        (kernels.ports_filter) against the node's counts minus `freed`."""
        ids = self.ports[u]
        ids = ids[ids >= 0]
        if ids.size == 0:
            return True
        remaining = self.port_used[n] - freed
        return not bool((self.conflict[ids] @ remaining > 0).any())

    def gpu_fit(self, u: int, n: int, freed: np.ndarray) -> Optional[np.ndarray]:
        """GPU packing per kernels.bind_update / AllocateGpuId
        (gpunodeinfo.go:232-290). Returns per-device take or None."""
        mem = float(self.gpu_mem[u])
        if mem <= 0:
            return np.zeros_like(self.gpu_free[n]) if self.gpu_free.size else None
        cnt = float(self.gpu_count[u])
        free = self.gpu_free[n] + freed
        chunks = np.floor_divide(free, max(mem, 1.0))
        if not (chunks.sum() >= cnt and cnt > 0):
            return None
        if cnt == 1:
            fits = free >= mem
            tight = int(np.argmin(np.where(fits, free, np.float32(1e30))))
            take = np.zeros_like(free)
            take[tight] = 1.0
            return take
        cum = np.cumsum(chunks)
        return np.clip(cnt - (cum - chunks), 0.0, chunks).astype(free.dtype)

    def storage_fit(self, u: int, n: int) -> Optional[Tuple[int, List[int]]]:
        """Open-local feasibility for the preemptor (victims free nothing
        here). Returns (vg_choice or -1, device indices) or None."""
        lvm = float(self.lvm_req[u])
        vg_choice = -1
        if lvm > 0:
            fits = self.vg_free[n] >= lvm
            if not fits.any():
                return None
            vg_choice = int(np.argmin(np.where(fits, self.vg_free[n], np.float32(1e30))))
        devs: List[int] = []
        taken = np.zeros_like(self.dev_free[n], dtype=bool)
        for media in (0, 1):
            sizes = self.dev_req_sizes[u, media]
            for size in sorted(s for s in sizes if s > 0):  # smallest volume first
                cand = (
                    (self.node_dev_media[n] == media)
                    & (self.dev_free[n] >= size)
                    & (self.dev_free[n] > 0)
                    & ~taken
                )
                if not cand.any():
                    return None
                pick = int(np.argmin(np.where(cand, self.node_dev_cap[n], np.float32(1e30))))
                taken[pick] = True
                devs.append(pick)
        return vg_choice, devs

    def place(self, u: int, i: int, n: int, gpu_alloc: Optional[np.ndarray]) -> None:
        """Commit a placement: resources, ports, gpu slots, storage."""
        self.used[n] += self.req[u]
        if self.Hports:
            self.port_used[n] += self.port_hot(u)
        if gpu_alloc is not None and float(self.gpu_mem[u]) > 0:
            self.gpu_free[n] -= gpu_alloc * float(self.gpu_mem[u])
            self.gpu_take[i] = gpu_alloc
        st = self.storage_fit(u, n)
        if st is not None:
            vg_choice, devs = st
            if vg_choice >= 0:
                self.vg_free[n, vg_choice] -= float(self.lvm_req[u])
            for d in devs:
                self.dev_free[n, d] = 0.0

    def evict(self, u: int, j: int, n: int) -> None:
        self.used[n] -= self.req[u]
        if self.Hports:
            self.port_used[n] -= self.port_hot(u)
        mem = float(self.gpu_mem[u])
        if mem > 0 and self.gpu_take is not None:
            self.gpu_free[n] += self.gpu_take[j] * mem
            self.gpu_take[j] = 0.0


def preempt_pass(
    prep,
    chosen: np.ndarray,
    nodes: List[Node],
    used: np.ndarray,
    alloc: np.ndarray,
    port_used: Optional[np.ndarray] = None,
    gpu_free: Optional[np.ndarray] = None,
    vg_free: Optional[np.ndarray] = None,
    dev_free: Optional[np.ndarray] = None,
    gpu_take: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, Dict[int, int]]:
    """Attempt preemption for every unscheduled, positive-priority pod in
    stream order, then re-place evicted victims where capacity exists.
    Returns the updated ``chosen`` and a map of victim-stream-index →
    preemptor-stream-index (victims successfully re-placed are removed).
    All state arrays are mutated in place."""
    ec = prep.ec_np
    tmpl = prep.tmpl_ids
    forced = prep.forced
    ordered = prep.ordered
    prio = np.array([p.spec.priority for p in ordered], dtype=np.int64)
    n_real = len(nodes)
    victims_of: Dict[int, int] = {}

    opt_state = (port_used, gpu_free, vg_free, dev_free, gpu_take)
    if any(a is None for a in opt_state) and any(a is not None for a in opt_state):
        # `used` is the caller's FINAL state; defaulting only some of the
        # companion arrays to the initial st0 would silently mix epochs
        # (e.g. final resource usage with initial port occupancy)
        raise ValueError(
            "preempt_pass: pass port_used/gpu_free/vg_free/dev_free/gpu_take "
            "together (all or none) — partial state mixes initial and final "
            "occupancy"
        )
    if port_used is None:
        port_used = np.array(np.asarray(prep.st0.port_used), copy=True)
    if gpu_free is None:
        gpu_free = np.array(np.asarray(prep.st0.gpu_free), copy=True)
    if vg_free is None:
        vg_free = np.array(np.asarray(prep.st0.vg_free), copy=True)
    if dev_free is None:
        dev_free = np.array(np.asarray(prep.st0.dev_free), copy=True)
    if gpu_take is None:
        gpu_take = np.zeros((len(ordered), gpu_free.shape[1]), np.float32)
    st = _State(ec, used, alloc, port_used, gpu_free, vg_free, dev_free, gpu_take)

    at_sel = np.asarray(ec.at_sel)
    an_sel = np.asarray(ec.an_sel)
    spr_topo = np.asarray(ec.spr_topo)
    spr_hard = np.asarray(ec.spr_hard)
    gpu_mem = np.asarray(ec.gpu_mem)
    lvm_req = np.asarray(ec.lvm_req)
    dev_req = np.asarray(ec.dev_req)
    matches_sel = np.asarray(ec.matches_sel)
    # only anti-affinity terms some template actually carries can be
    # violated (the encoder keeps a dummy row at G=0 when none exist)
    carried_g = np.asarray(ec.anti_g).any(axis=0)
    anti_g_sel = np.asarray(ec.anti_g_sel)[carried_g]
    sel_features = bool(prep.features.sel_counts)

    def constrained(u: int) -> bool:
        # constraints whose post-eviction state this pass does not model:
        # the preemptor's own required inter-pod terms and hard spread, and
        # being the target of an existing pod's global anti-affinity term
        if (at_sel[u] >= 0).any() or (an_sel[u] >= 0).any():
            return True
        if ((spr_topo[u] >= 0) & spr_hard[u]).any():
            return True
        if anti_g_sel.size and matches_sel[u, anti_g_sel].any():
            return True
        return False

    def victim_ok(u: int) -> bool:
        # storage holders never release exactly (per-pod VG allocation is
        # not tracked); selector-matched pods may anchor other placements
        if lvm_req[u] > 0 or (dev_req[u] > 0).any():
            return False
        return not (sel_features and matches_sel[u].any())

    def fits(u: int, n: int, free_res, freed_res, freed_ports, freed_gpu) -> bool:
        # match fit_filter: only resources the preemptor actually requests
        # gate the fit (a node overcommitted by force-bound pods in some
        # resource must still admit a pod requesting none of it)
        if not np.all((st.req[u] <= free_res + freed_res) | (st.req[u] <= 0)):
            return False
        if not st.ports_ok(u, n, freed_ports):
            return False
        if float(gpu_mem[u]) > 0 and st.gpu_fit(u, n, freed_gpu) is None:
            return False
        return True

    chosen = chosen.copy()
    # node → evictable bound-pod indices, built once and maintained
    # incrementally (a full per-node rescan would be O(pods × nodes) per
    # unschedulable pod)
    by_node: Dict[int, List[int]] = {}
    for j in range(len(ordered)):
        if chosen[j] >= 0 and not forced[j] and victim_ok(int(tmpl[j])):
            by_node.setdefault(int(chosen[j]), []).append(j)

    for i in range(len(ordered)):
        if chosen[i] >= 0 or forced[i] or prio[i] <= 0:
            continue
        u = int(tmpl[i])
        if constrained(u):
            continue
        best = None  # (n_victims, sum_prio, node, victim_indices)
        for n in range(n_real):
            if not _static_ok(ordered[i], nodes[n]):
                continue
            if st.storage_fit(u, n) is None:
                continue  # victims free no storage — the node must fit as-is
            cand = [j for j in by_node.get(n, []) if prio[j] < prio[i]]
            cand.sort(key=lambda j: (prio[j], j))
            free = alloc[n] - used[n]
            taken: List[int] = []
            freed_res = np.zeros_like(free)
            freed_ports = np.zeros((st.Hports,), np.float32)
            freed_gpu = np.zeros_like(gpu_free[n])
            for j in cand:
                if fits(u, n, free, freed_res, freed_ports, freed_gpu):
                    break
                ju = int(tmpl[j])
                freed_res = freed_res + st.req[ju]
                if st.Hports:
                    freed_ports = freed_ports + st.port_hot(ju)
                if float(gpu_mem[ju]) > 0:
                    freed_gpu = freed_gpu + gpu_take[j] * float(gpu_mem[ju])
                taken.append(j)
            if not fits(u, n, free, freed_res, freed_ports, freed_gpu):
                continue  # even evicting every candidate is not enough
            key = (len(taken), int(sum(prio[j] for j in taken)), n)
            if best is None or key < best[:3]:
                best = (*key, taken)
        if best is None:
            continue
        _, _, n, taken = best
        for j in taken:
            victims_of[j] = i
            st.evict(int(tmpl[j]), j, n)
            chosen[j] = -1
        taken_set = set(taken)
        by_node[n] = [j for j in by_node.get(n, []) if j not in taken_set]
        gpu_alloc = st.gpu_fit(u, n, np.zeros_like(gpu_free[n]))
        st.place(u, i, n, gpu_alloc)
        chosen[i] = n
        if victim_ok(u):
            by_node[n].append(i)  # the preemptor may itself be preempted later

    # cascade: evicted victims re-enter in stream order and land on the
    # lowest-index node with spare capacity (a nominated pod going back
    # through the queue); no further eviction is triggered
    for j in sorted(victims_of):
        ju = int(tmpl[j])
        if constrained(ju):
            continue  # its inter-pod/spread feasibility cannot be re-checked here
        for n in range(n_real):
            if not _static_ok(ordered[j], nodes[n]):
                continue
            free = alloc[n] - used[n]
            if not fits(ju, n, free, 0.0, np.zeros((st.Hports,), np.float32),
                        np.zeros_like(gpu_free[n])):
                continue
            if st.storage_fit(ju, n) is None:
                continue
            gpu_alloc = st.gpu_fit(ju, n, np.zeros_like(gpu_free[n]))
            st.place(ju, j, n, gpu_alloc)
            chosen[j] = n
            del victims_of[j]
            if victim_ok(ju):
                by_node.setdefault(n, []).append(j)
            break
    return chosen, victims_of
