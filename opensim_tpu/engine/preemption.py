"""Opt-in preemption — the PostFilter the reference registers but never
exercises.

The reference's scheduler profile includes ``DefaultPreemption``
(vendored ``algorithmprovider/registry.go:104``), but its driver deletes every
unschedulable pod before a retry could run the nominated placement
(``pkg/simulator/simulator.go:333-342``), so the PostFilter is vacuous there
(PARITY.md, divergence 6). This module implements the intent as a
what-if-capable pass: after the bind scan, each unschedulable pod with a
positive ``spec.priority`` searches nodes where evicting strictly
lower-priority pods frees enough resources, mirroring the shape of
``dryRunPreemption`` → ``SelectVictimsOnNode`` → ``pickOneNodeForPreemption``
(vendored ``defaultpreemption/default_preemption.go``).

Modeled dimensions:
- CPU/memory/extended resources (victims free their requests);
- host ports (victims free their ports; the preemptor's ports are checked
  through the wildcard-aware conflict matrix);
- fractional GPU devices (victims free the exact per-device slots recorded
  at bind time in ``gpu_take``; the preemptor is re-packed with the same
  tightest-fit / greedy rules as ``kernels.bind_update``);
- open-local storage for BOTH sides: the preemptor is placed with
  tightest-fit VG + smallest-fitting exclusive devices, and storage-holding
  victims release their exact allocation — recovered by a deterministic
  host-side replay of the bind stream through the same allocation rules
  (the engines don't record per-pod VG/device choices; the replay is
  verified against the final state and storage victims are disabled if it
  diverges);
- PodDisruptionBudgets (``default_preemption.go:642,731-775``): victim
  selection mirrors ``selectVictimsOnNode`` — remove every lower-priority
  pod, then reprieve PDB-violating victims first (highest priority first),
  then non-violating ones; candidate nodes are ranked by
  ``pickOneNodeForPreemption``'s ladder (fewest PDB violations, lowest
  highest-victim priority, lowest summed priority, fewest victims, lowest
  node index — the pod-start-time criterion collapses onto stream order).
  DisruptionsAllowed is derived from spec + currently-bound matching pods
  (the simulator has no PDB status controller); committed evictions
  consume allowance, successful cascade re-placements restore it;
- cascading re-placement: evicted victims are re-queued in stream order and
  re-placed on the lowest-index feasible node when capacity exists
  elsewhere, mirroring a nominated pod re-entering the scheduling queue.

Required inter-pod affinity/anti-affinity and hard topology spread are
re-evaluated against the post-eviction placement (``_TermChecker`` — the
object-level analogue of ``selectVictimsOnNode`` re-running the filter
plugins after ``RemovePod``), for the preemptor, during the reprieve loop,
and for cascade re-placements; selector-matched pods are eligible victims
(kube's ``IgnoredDuringExecution``: evicting an affinity anchor never
re-validates other already-bound pods — exactly the reference's behavior).

Remaining documented simplifications:
- force-bound (pre-existing) pods are never victims;
- preferred (soft) terms do not influence which candidate node wins beyond
  ``pickOneNodeForPreemption``'s ladder (kube likewise does not re-score).

Off by default: ``simulate(..., enable_preemption=True)`` or
``simon apply --enable-preemption``. DECISION (r3): this stays opt-in —
the reference's default profile registers DefaultPreemption
(``registry.go:104``) but its driver deletes every unschedulable pod
before a retry could use the nominated node (``simulator.go:333-342``), so
the reference's OBSERVED default behavior is no preemption. Matching
observed behavior by default and offering the working pass behind a flag
is strictly more capable without diverging on any reference workload.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import selectors
from ..models.objects import Node, Pod


def _static_ok(pod: Pod, node: Node) -> bool:
    if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
        return False
    if node.unschedulable:
        return False
    if not selectors.pod_matches_node_selector_and_affinity(pod, node):
        return False
    taints = [t for t in node.taints if t.effect in ("NoSchedule", "NoExecute")]
    return selectors.find_untolerated_taint(taints, pod.spec.tolerations) is None


class _State:
    """Mutable per-node resource view shared by eviction, placement, and
    cascade — numpy rows of the final ScanState (mutated in place)."""

    def __init__(self, ec, used, alloc, port_used, gpu_free, vg_free, dev_free, gpu_take):
        self.ec = ec
        self.used = used
        self.alloc = alloc
        self.port_used = port_used
        self.gpu_free = gpu_free
        self.vg_free = vg_free
        self.dev_free = dev_free
        self.gpu_take = gpu_take
        self.storage_of: Dict[int, Tuple[int, int, List[int]]] = {}
        self.req = np.asarray(ec.req)
        self.ports = np.asarray(ec.ports)
        self.conflict = np.asarray(ec.port_conflict)
        self.gpu_mem = np.asarray(ec.gpu_mem)
        self.gpu_count = np.asarray(ec.gpu_count)
        self.lvm_req = np.asarray(ec.lvm_req)
        self.dev_req_sizes = np.asarray(ec.dev_req_sizes)
        self.node_dev_media = np.asarray(ec.node_dev_media)
        self.node_dev_cap = np.asarray(ec.node_dev_cap)
        self.Hports = port_used.shape[1] if port_used.ndim == 2 else 0

    def port_hot(self, u: int) -> np.ndarray:
        ids = self.ports[u]
        ids = ids[ids >= 0]
        if self.Hports == 0 or ids.size == 0:
            return np.zeros((self.Hports,), np.float32)
        return np.bincount(ids, minlength=self.Hports).astype(np.float32)

    def ports_ok(self, u: int, n: int, freed: np.ndarray) -> bool:
        """NodePorts with the wildcard-aware conflict matrix
        (kernels.ports_filter) against the node's counts minus `freed`."""
        ids = self.ports[u]
        ids = ids[ids >= 0]
        if ids.size == 0:
            return True
        remaining = self.port_used[n] - freed
        return not bool((self.conflict[ids] @ remaining > 0).any())

    def gpu_fit(self, u: int, n: int, freed: np.ndarray) -> Optional[np.ndarray]:
        """GPU packing per kernels.bind_update / AllocateGpuId
        (gpunodeinfo.go:232-290). Returns per-device take or None."""
        mem = float(self.gpu_mem[u])
        if mem <= 0:
            return np.zeros_like(self.gpu_free[n]) if self.gpu_free.size else None
        cnt = float(self.gpu_count[u])
        free = self.gpu_free[n] + freed
        chunks = np.floor_divide(free, max(mem, 1.0))
        if not (chunks.sum() >= cnt and cnt > 0):
            return None
        if cnt == 1:
            fits = free >= mem
            tight = int(np.argmin(np.where(fits, free, np.float32(1e30))))
            take = np.zeros_like(free)
            take[tight] = 1.0
            return take
        cum = np.cumsum(chunks)
        return np.clip(cnt - (cum - chunks), 0.0, chunks).astype(free.dtype)

    def has_storage(self, u: int) -> bool:
        return float(self.lvm_req[u]) > 0 or (self.dev_req_sizes[u] > 0).any()

    def storage_fit(
        self, u: int, n: int, vg_row=None, dev_row=None
    ) -> Optional[Tuple[int, List[int]]]:
        """Open-local feasibility. Returns (vg_choice or -1, device indices)
        or None. `vg_row`/`dev_row` override the node's live state (used for
        the remove-all / reprieve hypotheticals and the bind replay)."""
        vg_free = self.vg_free[n] if vg_row is None else vg_row
        dev_free = self.dev_free[n] if dev_row is None else dev_row
        lvm = float(self.lvm_req[u])
        vg_choice = -1
        if lvm > 0:
            fits = vg_free >= lvm
            if not fits.any():
                return None
            vg_choice = int(np.argmin(np.where(fits, vg_free, np.float32(1e30))))
        devs: List[int] = []
        taken = np.zeros_like(dev_free, dtype=bool)
        for media in (0, 1):
            sizes = self.dev_req_sizes[u, media]
            for size in sorted(s for s in sizes if s > 0):  # smallest volume first
                cand = (
                    (self.node_dev_media[n] == media)
                    & (dev_free >= size)
                    & (dev_free > 0)
                    & ~taken
                )
                if not cand.any():
                    return None
                pick = int(np.argmin(np.where(cand, self.node_dev_cap[n], np.float32(1e30))))
                taken[pick] = True
                devs.append(pick)
        return vg_choice, devs

    def place(self, u: int, i: int, n: int, gpu_alloc: Optional[np.ndarray]) -> None:
        """Commit a placement: resources, ports, gpu slots, storage (the
        storage choice is recorded so a later eviction can release it)."""
        self.used[n] += self.req[u]
        if self.Hports:
            self.port_used[n] += self.port_hot(u)
        if gpu_alloc is not None and float(self.gpu_mem[u]) > 0:
            self.gpu_free[n] -= gpu_alloc * float(self.gpu_mem[u])
            self.gpu_take[i] = gpu_alloc
        st = self.storage_fit(u, n)
        if st is not None:
            vg_choice, devs = st
            if vg_choice >= 0:
                self.vg_free[n, vg_choice] -= float(self.lvm_req[u])
            for d in devs:
                self.dev_free[n, d] = 0.0
            if vg_choice >= 0 or devs:
                self.storage_of[i] = (n, vg_choice, devs)

    def evict(self, u: int, j: int, n: int) -> None:
        self.used[n] -= self.req[u]
        if self.Hports:
            self.port_used[n] -= self.port_hot(u)
        mem = float(self.gpu_mem[u])
        if mem > 0 and self.gpu_take is not None:
            self.gpu_free[n] += self.gpu_take[j] * mem
            self.gpu_take[j] = 0.0
        rec = self.storage_of.pop(j, None)
        if rec is not None:
            rn, vg_choice, devs = rec
            if vg_choice >= 0:
                self.vg_free[rn, vg_choice] += float(self.lvm_req[u])
            for d in devs:
                self.dev_free[rn, d] = self.node_dev_cap[rn, d]


def _replay_storage(st: "_State", prep, chosen, tmpl) -> bool:
    """Recover each bound pod's VG/device allocation by replaying the bind
    stream through the same tightest-fit rules from the initial state.
    Populates ``st.storage_of``; returns False (and leaves it empty) when
    the replayed final state disagrees with the engine's — storage-holding
    victims are then disabled rather than released inexactly."""
    vg0 = np.array(np.asarray(prep.st0.vg_free), copy=True)
    dev0 = np.array(np.asarray(prep.st0.dev_free), copy=True)
    rec: Dict[int, Tuple[int, int, List[int]]] = {}
    for j in range(len(chosen)):
        n = int(chosen[j])
        if n < 0:
            continue
        u = int(tmpl[j])
        if not st.has_storage(u):
            continue
        fitres = st.storage_fit(u, n, vg_row=vg0[n], dev_row=dev0[n])
        if fitres is None:
            return False
        vg_choice, devs = fitres
        if vg_choice >= 0:
            vg0[n, vg_choice] -= float(st.lvm_req[u])
        for d in devs:
            dev0[n, d] = 0.0
        rec[j] = (n, vg_choice, devs)
    if not (np.allclose(vg0, st.vg_free, rtol=1e-5) and np.allclose(dev0, st.dev_free, rtol=1e-5)):
        return False
    st.storage_of.update(rec)
    return True


def _pdb_budgets(pdbs, ordered, chosen) -> List[dict]:
    """Derive each PDB's DisruptionsAllowed from its spec, the bound
    matching pods (healthy — the simulator has no disruption-status
    controller, every bound pod counts healthy) and the EXPECTED count —
    the owning workloads' declared replicas, kube's ``GetExpectedPodCount``
    (disruption controller): the expansion creates exactly
    ``spec.replicas`` stream pods per workload, so the expected count is
    the number of stream pods (bound or not) sharing the matching pods'
    controllers, plus matching bare pods. minAvailable 50% with 4 desired
    but only 2 bound therefore allows 0 disruptions, not 1. Nil/empty
    selectors match nothing (``filterPodsWithPDBViolation``,
    default_preemption.go:736-775)."""
    import math

    out = []
    for obj in pdbs:
        raw = getattr(obj, "raw", None) or (obj if isinstance(obj, dict) else {})
        meta = raw.get("metadata") or {}
        spec = raw.get("spec") or {}
        ns = meta.get("namespace") or "default"
        sel = spec.get("selector") or {}
        if not sel.get("matchLabels") and not sel.get("matchExpressions"):
            continue
        matching = [
            (j, p)
            for j, p in enumerate(ordered)
            if p.metadata.namespace == ns
            and p.metadata.labels
            and selectors.match_label_selector(sel, p.metadata.labels)
        ]
        healthy = sum(1 for j, _p in matching if int(chosen[j]) >= 0)
        # expected: every stream pod owned by a controller that owns at
        # least one matching pod (the stream holds exactly the declared
        # replica set), plus matching controller-less pods
        owners = set()
        expected = 0
        for _j, p in matching:
            ctrl = next(
                (r.uid for r in p.metadata.owner_references if r.controller), None
            )
            if ctrl is None:
                expected += 1
            else:
                owners.add((p.metadata.namespace, ctrl))
        for p in ordered:
            ctrl = next(
                (r.uid for r in p.metadata.owner_references if r.controller), None
            )
            if ctrl is not None and (p.metadata.namespace, ctrl) in owners:
                expected += 1

        def _val(v, basis):
            if isinstance(v, str) and v.strip().endswith("%"):
                return int(math.ceil(float(v.strip()[:-1]) / 100.0 * basis))
            return int(v)

        if spec.get("minAvailable") is not None:
            # desiredHealthy = minAvailable (int) or ceil(pct·expected)
            allowed = healthy - _val(spec["minAvailable"], expected)
        elif spec.get("maxUnavailable") is not None:
            # desiredHealthy = expected − maxUnavailable (int or pct·expected)
            allowed = healthy - (expected - _val(spec["maxUnavailable"], expected))
        else:
            continue
        out.append({"ns": ns, "sel": sel, "allowed": max(int(allowed), 0)})
    return out


def _pdb_matches(pdb: dict, pod: Pod) -> bool:
    return (
        pod.metadata.namespace == pdb["ns"]
        and bool(pod.metadata.labels)
        and selectors.match_label_selector(pdb["sel"], pod.metadata.labels)
    )


def _aff_terms(pod: Pod, kind: str, mode: str):
    aff = (pod.spec.affinity or {}).get(kind) or {}
    return aff.get(f"{mode}DuringSchedulingIgnoredDuringExecution") or []


class _TermChecker:
    """Post-eviction required inter-pod-affinity / hard-spread feasibility
    for one preemptor on one node — the object-level equivalent of
    ``selectVictimsOnNode`` re-running the filter plugins after ``RemovePod``
    (vendored ``default_preemption.go`` → ``RunFilterPluginsWithNominatedPods``).
    Counts are recomputed from the live placement (``ordered`` + ``chosen``)
    at query time, with the hypothetical victim set excluded, so eviction
    effects — an anti-affinity blocker leaving, an affinity anchor leaving,
    a spread domain emptying — are all modeled. kube's
    ``IgnoredDuringExecution`` semantics apply throughout: evicting an
    anchor never re-validates other already-bound pods."""

    def __init__(self, ordered: List[Pod], nodes: List[Node]):
        self.ordered = ordered
        self.nodes = nodes
        self._eligible: Dict[tuple, frozenset] = {}

    def _bound(self, chosen, evicted):
        for j, p in enumerate(self.ordered):
            n = int(chosen[j])
            if n >= 0 and j not in evicted:
                yield p, self.nodes[n]

    def _eligible_vals(self, pod: Pod, key: str) -> frozenset:
        import json as _json

        sig = (
            tuple(sorted(pod.spec.node_selector.items())),
            _json.dumps((pod.spec.affinity or {}).get("nodeAffinity"), sort_keys=True),
            key,
        )
        vals = self._eligible.get(sig)
        if vals is None:
            vals = frozenset(
                n.metadata.labels[key]
                for n in self.nodes
                if key in n.metadata.labels
                and selectors.pod_matches_node_selector_and_affinity(pod, n)
            )
            self._eligible[sig] = vals
        return vals

    def ok(self, i: int, n_idx: int, chosen, evicted) -> bool:
        pod = self.ordered[i]
        node = self.nodes[n_idx]
        ns = pod.metadata.namespace
        bound = list(self._bound(chosen, evicted))

        # (1) existing pods' required anti-affinity vs the preemptor
        for p, pn in bound:
            for term in _aff_terms(p, "podAntiAffinity", "required"):
                if not selectors.affinity_term_matches_pod(
                    term, p.metadata.namespace, pod
                ):
                    continue
                key = term.get("topologyKey", "")
                val = pn.metadata.labels.get(key)
                if val is not None and node.metadata.labels.get(key) == val:
                    return False
        # (2) the preemptor's required anti-affinity
        for term in _aff_terms(pod, "podAntiAffinity", "required"):
            key = term.get("topologyKey", "")
            my = node.metadata.labels.get(key)
            if my is None:
                continue
            for p, pn in bound:
                if pn.metadata.labels.get(key) == my and (
                    selectors.affinity_term_matches_pod(term, ns, p)
                ):
                    return False
        # (3) the preemptor's required affinity (+ first-pod bootstrap)
        terms = _aff_terms(pod, "podAffinity", "required")
        if terms:
            matching = [
                (p, pn)
                for p, pn in bound
                if all(selectors.affinity_term_matches_pod(t, ns, p) for t in terms)
            ]
            labels_ok = all(
                node.metadata.labels.get(t.get("topologyKey", "")) is not None
                for t in terms
            )
            per_term_ok = labels_ok and all(
                any(
                    pn.metadata.labels.get(t.get("topologyKey", ""))
                    == node.metadata.labels.get(t.get("topologyKey", ""))
                    for _p, pn in matching
                    if pn.metadata.labels.get(t.get("topologyKey", "")) is not None
                )
                for t in terms
            )
            if not per_term_ok:
                map_empty = not any(
                    pn.metadata.labels.get(t.get("topologyKey", "")) is not None
                    for _p, pn in matching
                    for t in terms
                )
                self_match = all(
                    selectors.affinity_term_matches_pod(t, ns, pod) for t in terms
                )
                if not (labels_ok and map_empty and self_match):
                    return False
        # (4) hard topology-spread constraints
        for c in pod.spec.topology_spread_constraints:
            if c.get("whenUnsatisfiable", "DoNotSchedule") != "DoNotSchedule":
                continue
            key = c.get("topologyKey", "")
            my = node.metadata.labels.get(key)
            if my is None:
                return False
            sel = c.get("labelSelector")
            counts: Dict[str, int] = {}
            for p, pn in bound:
                val = pn.metadata.labels.get(key)
                if (
                    val is not None
                    and p.metadata.namespace == ns
                    and sel is not None
                    and selectors.match_label_selector(sel, p.metadata.labels)
                ):
                    counts[val] = counts.get(val, 0) + 1
            elig = self._eligible_vals(pod, key)
            if not elig:
                return False
            min_cnt = min(counts.get(v, 0) for v in elig)
            self_match = (
                1
                if sel is not None
                and selectors.match_label_selector(sel, pod.metadata.labels)
                else 0
            )
            if counts.get(my, 0) + self_match - min_cnt > int(c.get("maxSkew", 1)):
                return False
        return True


# MaxInt32+1, added per victim INSIDE the summed-priority criterion — kube
# does exactly this (default_preemption.go:500-502), deliberately making the
# sum count-sensitive so "a node with a few pods with negative priority is
# not picked over a node with a smaller number of pods with the same
# negative priority". Not a bug to simplify away: removing the offset would
# diverge from pickOneNodeForPreemption on any mixed victim-count tie.
_PRIO_OFFSET = 2**31


def preempt_pass(
    prep,
    chosen: np.ndarray,
    nodes: List[Node],
    used: np.ndarray,
    alloc: np.ndarray,
    port_used: Optional[np.ndarray] = None,
    gpu_free: Optional[np.ndarray] = None,
    vg_free: Optional[np.ndarray] = None,
    dev_free: Optional[np.ndarray] = None,
    gpu_take: Optional[np.ndarray] = None,
    pdbs: tuple = (),
    eligible: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, Dict[int, int]]:
    """Attempt preemption for every unscheduled, positive-priority pod in
    stream order, then re-place evicted victims where capacity exists.
    Returns the updated ``chosen`` and a map of victim-stream-index →
    preemptor-stream-index (victims successfully re-placed are removed).
    All state arrays are mutated in place."""
    ec = prep.ec_np
    tmpl = prep.tmpl_ids
    forced = prep.forced
    ordered = prep.ordered
    prio = np.array([p.spec.priority for p in ordered], dtype=np.int64)
    n_real = len(nodes)
    victims_of: Dict[int, int] = {}

    opt_state = (port_used, gpu_free, vg_free, dev_free, gpu_take)
    if any(a is None for a in opt_state) and any(a is not None for a in opt_state):
        # `used` is the caller's FINAL state; defaulting only some of the
        # companion arrays to the initial st0 would silently mix epochs
        # (e.g. final resource usage with initial port occupancy)
        raise ValueError(
            "preempt_pass: pass port_used/gpu_free/vg_free/dev_free/gpu_take "
            "together (all or none) — partial state mixes initial and final "
            "occupancy"
        )
    if port_used is None:
        port_used = np.array(np.asarray(prep.st0.port_used), copy=True)
    if gpu_free is None:
        gpu_free = np.array(np.asarray(prep.st0.gpu_free), copy=True)
    if vg_free is None:
        vg_free = np.array(np.asarray(prep.st0.vg_free), copy=True)
    if dev_free is None:
        dev_free = np.array(np.asarray(prep.st0.dev_free), copy=True)
    if gpu_take is None:
        gpu_take = np.zeros((len(ordered), gpu_free.shape[1]), np.float32)
    st = _State(ec, used, alloc, port_used, gpu_free, vg_free, dev_free, gpu_take)

    gpu_mem = np.asarray(ec.gpu_mem)
    lvm_req = np.asarray(ec.lvm_req)
    dev_req = np.asarray(ec.dev_req)
    # object-level interpod/spread re-evaluation against the post-eviction
    # placement (selectVictimsOnNode's RemovePod → filter re-run)
    checker = _TermChecker(ordered, nodes)

    # recover per-pod storage allocations by replay; when the replay cannot
    # reproduce the engine's final state, storage holders stay non-victims
    storage_replay_ok = _replay_storage(st, prep, chosen, tmpl)
    pdb_list = _pdb_budgets(pdbs, ordered, chosen)
    pdb_of: Dict[int, List[int]] = {}  # stream index → matching pdb indices
    for j, p in enumerate(ordered):
        ks = [k for k, pdb in enumerate(pdb_list) if _pdb_matches(pdb, p)]
        if ks:
            pdb_of[j] = ks
    allowed = [pdb["allowed"] for pdb in pdb_list]

    def victim_ok(u: int) -> bool:
        # storage holders are only evictable when their allocation was
        # recovered exactly. Selector-matched pods ARE evictable (r4: the
        # checker recomputes domain counts from the live placement, and
        # kube's IgnoredDuringExecution never re-validates bound pods that
        # depended on an evicted anchor)
        if not storage_replay_ok and (lvm_req[u] > 0 or (dev_req[u] > 0).any()):
            return False
        return True

    # dynamic gpu-count allocatable (kernels.gc_dynamic_alloc — the gpushare
    # Reserve rewrite): on device-bearing nodes the gc column's effective
    # allocatable is the count of not-fully-used devices. dyn <= static, so
    # the static vector check below stays a valid necessary condition and
    # the column just gets this extra, stricter test.
    from ..ops.kernels import gc_row_of

    _gc_col = gc_row_of(ec)
    _dev_valid = np.asarray(ec.node_gpu_mem) > 0  # [N, Gd]
    _has_dev = _dev_valid.any(axis=1) if _dev_valid.size else np.zeros(0, bool)

    def fits(u: int, n: int, free_res, freed_res, freed_ports, freed_gpu,
             vg_row=None, dev_row=None) -> bool:
        # match fit_filter: only resources the preemptor actually requests
        # gate the fit (a node overcommitted by force-bound pods in some
        # resource must still admit a pod requesting none of it)
        if not np.all((st.req[u] <= free_res + freed_res) | (st.req[u] <= 0)):
            return False
        if _gc_col >= 0 and n < _has_dev.shape[0] and _has_dev[n] and st.req[u][_gc_col] > 0:
            gfree = st.gpu_free[n] + freed_gpu
            dyn = float((_dev_valid[n] & (gfree > 0)).sum())
            adj = dyn - alloc[n][_gc_col]
            if st.req[u][_gc_col] > np.asarray(free_res + freed_res)[_gc_col] + adj:
                return False
        if not st.ports_ok(u, n, freed_ports):
            return False
        if float(gpu_mem[u]) > 0 and st.gpu_fit(u, n, freed_gpu) is None:
            return False
        if st.has_storage(u) and st.storage_fit(u, n, vg_row=vg_row, dev_row=dev_row) is None:
            return False
        return True

    chosen = chosen.copy()
    # node → evictable bound-pod indices, built once and maintained
    # incrementally (a full per-node rescan would be O(pods × nodes) per
    # unschedulable pod)
    by_node: Dict[int, List[int]] = {}
    for j in range(len(ordered)):
        if chosen[j] >= 0 and not forced[j] and victim_ok(int(tmpl[j])):
            by_node.setdefault(int(chosen[j]), []).append(j)

    def free_of(j: int, n: int, freed_res, freed_ports, freed_gpu, vg_hyp, dev_hyp, sign):
        """Add (sign=+1) or retract (sign=-1) victim j's holdings from the
        hypothetical freed state."""
        ju = int(tmpl[j])
        freed_res += sign * st.req[ju]
        if st.Hports:
            freed_ports += sign * st.port_hot(ju)
        if float(gpu_mem[ju]) > 0:
            freed_gpu += sign * gpu_take[j] * float(gpu_mem[ju])
        rec = st.storage_of.get(j)
        if rec is not None and rec[0] == n:
            _, vg_choice, devs = rec
            if vg_choice >= 0:
                vg_hyp[vg_choice] += sign * float(lvm_req[ju])
            for d in devs:
                dev_hyp[d] = st.node_dev_cap[n, d] if sign > 0 else 0.0

    for i in range(len(ordered)):
        if chosen[i] >= 0 or forced[i] or prio[i] <= 0:
            continue
        if eligible is not None and not eligible[i]:
            # pods outside every scheduler profile never enter a queue —
            # they cannot preempt either (simulate passes pod_valid here)
            continue
        u = int(tmpl[i])
        # (numPDBViolations, highest victim prio, Σ(prio+2^31), n victims,
        # node index, victims) — pickOneNodeForPreemption's ladder; the
        # pod-start-time criterion collapses onto stream order
        best = None
        for n in range(n_real):
            if not _static_ok(ordered[i], nodes[n]):
                continue
            cand = [j for j in by_node.get(n, []) if prio[j] < prio[i]]
            if not cand:
                # selectVictimsOnNode returns early when there are no
                # potential victims (default_preemption.go:582-585): a
                # zero-victim node is NOT a preemption candidate
                continue
            free = alloc[n] - used[n]
            # selectVictimsOnNode: remove ALL lower-priority pods first
            freed_res = np.zeros_like(free)
            freed_ports = np.zeros((st.Hports,), np.float32)
            freed_gpu = np.zeros_like(gpu_free[n])
            vg_hyp = vg_free[n].copy()
            dev_hyp = dev_free[n].copy()
            for j in cand:
                free_of(j, n, freed_res, freed_ports, freed_gpu, vg_hyp, dev_hyp, +1)
            if not fits(u, n, free, freed_res, freed_ports, freed_gpu, vg_hyp, dev_hyp):
                continue  # even evicting every candidate is not enough
            if not checker.ok(i, n, chosen, set(cand)):
                continue  # interpod/spread still violated with all evicted
            # MoreImportantPod order: higher priority first, then stream
            # order (our stand-in for pod start time)
            cand_sorted = sorted(cand, key=lambda j: (-prio[j], j))
            # split by PDB violation against a local allowance snapshot
            local_allowed = list(allowed)
            violating, nonviolating = [], []
            for j in cand_sorted:
                viol = False
                for k in pdb_of.get(j, ()):
                    local_allowed[k] -= 1
                    if local_allowed[k] < 0:
                        viol = True
                (violating if viol else nonviolating).append(j)
            # reprieve as many as possible: PDB-violating victims first,
            # then non-violating, highest priority first in both groups
            victims = set(cand)
            for j in violating + nonviolating:
                free_of(j, n, freed_res, freed_ports, freed_gpu, vg_hyp, dev_hyp, -1)
                if fits(
                    u, n, free, freed_res, freed_ports, freed_gpu, vg_hyp, dev_hyp
                ) and checker.ok(i, n, chosen, victims - {j}):
                    victims.discard(j)  # reprieved: stays bound
                else:
                    free_of(j, n, freed_res, freed_ports, freed_gpu, vg_hyp, dev_hyp, +1)
            viol_set = set(violating)
            n_viol = sum(1 for j in victims if j in viol_set)
            key = (
                n_viol,
                max((int(prio[j]) for j in victims), default=-_PRIO_OFFSET),
                sum(int(prio[j]) + _PRIO_OFFSET for j in victims),
                len(victims),
                n,
            )
            if best is None or key < best[:5]:
                best = (*key, sorted(victims))
        if best is None:
            continue
        n, taken = best[4], best[5]
        for j in taken:
            victims_of[j] = i
            st.evict(int(tmpl[j]), j, n)
            chosen[j] = -1
            for k in pdb_of.get(j, ()):
                allowed[k] -= 1  # committed disruption consumes budget
        taken_set = set(taken)
        by_node[n] = [j for j in by_node.get(n, []) if j not in taken_set]
        gpu_alloc = st.gpu_fit(u, n, np.zeros_like(gpu_free[n]))
        st.place(u, i, n, gpu_alloc)
        chosen[i] = n
        if victim_ok(u):
            by_node[n].append(i)  # the preemptor may itself be preempted later

    # cascade: evicted victims re-enter in stream order and land on the
    # lowest-index node with spare capacity (a nominated pod going back
    # through the queue); no further eviction is triggered
    for j in sorted(victims_of):
        ju = int(tmpl[j])
        for n in range(n_real):
            if not _static_ok(ordered[j], nodes[n]):
                continue
            free = alloc[n] - used[n]
            if not fits(ju, n, free, 0.0, np.zeros((st.Hports,), np.float32),
                        np.zeros_like(gpu_free[n])):
                continue
            if not checker.ok(j, n, chosen, set()):
                continue  # re-placement must satisfy interpod/spread too
            gpu_alloc = st.gpu_fit(ju, n, np.zeros_like(gpu_free[n]))
            st.place(ju, j, n, gpu_alloc)
            chosen[j] = n
            del victims_of[j]
            for k in pdb_of.get(j, ()):
                allowed[k] += 1  # re-placed: the pod runs again, budget restored
            if victim_ok(ju):
                by_node.setdefault(n, []).append(j)
            break
    return chosen, victims_of
