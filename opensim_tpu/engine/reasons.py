"""The registered reason-code enum + placement explanations (ISSUE 7).

Every unschedulable-reason string in the repo comes from this module — the
kube-scheduler FitError phrasings for the 11 filter plugins, plus the
non-filter outcomes (missing pinned node, unknown scheduler profile,
preemption victim). ``opensim-lint`` rule OSL901 enforces the registration:
an inline reason literal at an ``UnscheduledPod(...)`` construction site is
a lint error, so the XLA scan, the C++ engine, and every report/endpoint
render byte-identical diagnostics from one table.

:class:`PlacementExplanation` is the typed per-pod decision-audit record the
engines normalize into (engine/explain.py): scheduled → winning node (and,
on demand, the per-plugin score breakdown + runner-up margin);
unschedulable → per-filter rejection counts over nodes rendered in kube's
``0/N nodes are available: …`` phrasing.

This module deliberately imports nothing from :mod:`..ops` — it is the leaf
the kernel layer's ``FILTER_REASONS`` table is built FROM (ops/kernels.py
imports it), so the registry stays a single definition with no cycle.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence


class Reason(enum.Enum):
    """Registered reason codes. Filter members carry their kernel filter
    index as the value (asserted against ``ops.kernels.F_*`` by the tests);
    non-filter outcomes live at 100+."""

    # --- filter plugins (value == ops.kernels filter index) ---------------
    NODE_PIN = 0          # NodeName
    UNSCHEDULABLE = 1     # NodeUnschedulable
    TAINT = 2             # TaintToleration
    AFFINITY = 3          # NodeAffinity + nodeSelector
    PORTS = 4             # NodePorts
    FIT = 5               # NodeResourcesFit
    SPREAD = 6            # PodTopologySpread
    INTERPOD = 7          # InterPodAffinity
    GPU = 8               # GpuShare
    LOCAL = 9             # OpenLocal
    EXTRA = 10            # out-of-tree extra_plugins
    # --- non-filter outcomes ----------------------------------------------
    NODE_NOT_FOUND = 100   # forced pod whose spec.nodeName matches no node
    UNKNOWN_PROFILE = 101  # spec.schedulerName matches no profile
    PREEMPTED = 102        # evicted by a higher-priority pod

    @property
    def message(self) -> str:
        return _MESSAGES[self]

    @property
    def is_filter(self) -> bool:
        return self.value < 100


# kube-scheduler FitError phrasings (vendor/.../framework/types.go +
# the sim plugins' Filter status messages) — the ONE copy in the repo.
_MESSAGES: Dict[Reason, str] = {
    Reason.NODE_PIN: "node(s) didn't match the requested hostname",
    Reason.UNSCHEDULABLE: "node(s) were unschedulable",
    Reason.TAINT: "node(s) had taints that the pod didn't tolerate",
    Reason.AFFINITY: "node(s) didn't match Pod's node affinity",
    Reason.PORTS: "node(s) didn't have free ports for the requested pod ports",
    Reason.FIT: "Insufficient resources",
    Reason.SPREAD: "node(s) didn't match pod topology spread constraints",
    Reason.INTERPOD: "node(s) didn't satisfy inter-pod affinity rules",
    Reason.GPU: "Insufficient GPU memory in 1 GPU device",
    Reason.LOCAL: "node(s) didn't have enough local storage",
    Reason.EXTRA: "node(s) were rejected by an out-of-tree plugin",
    Reason.NODE_NOT_FOUND: 'node "{node}" not found',
    Reason.UNKNOWN_PROFILE: (
        "no scheduler profile named {profile!r} "
        "(pod never enters any profile's scheduling queue)"
    ),
    Reason.PREEMPTED: "preempted by higher-priority pod {pod}",
}

# the 11 filter messages in kernel filter-index order — ops/kernels.py
# re-exports this as FILTER_REASONS (single registered table, no drift)
FILTER_MESSAGES: List[str] = [
    _MESSAGES[r] for r in sorted((r for r in Reason if r.is_filter), key=lambda r: r.value)
]

N_STATIC_FILTERS = 4  # NODE_PIN..AFFINITY — template-static, precomputed


def node_not_found(node_name: str) -> str:
    return Reason.NODE_NOT_FOUND.message.format(node=node_name)


def unknown_profile(profile_name: str) -> str:
    return Reason.UNKNOWN_PROFILE.message.format(profile=profile_name)


def preempted(namespace: str, name: str) -> str:
    return Reason.PREEMPTED.message.format(pod=f"{namespace}/{name}")


# the capacity observatory's cluster report (obs/capacity.py) lists pods
# OBSERVED pending — no simulation ran, so there is no FitError breakdown
# to render; the registered phrasing keeps OSL901's one-registry contract
PENDING_OBSERVED = "pod is pending in the observed cluster (no node assigned)"


def pending_observed() -> str:
    return PENDING_OBSERVED


@dataclass
class ReasonCount:
    """One line of a FitError breakdown: ``count`` nodes rejected for
    ``code``; ``resource`` names the short resource for FIT rejections
    (kube reports each resource class on its own line)."""

    code: Reason
    count: int
    resource: str = ""

    @property
    def label(self) -> str:
        if self.code is Reason.FIT and self.resource:
            return f"Insufficient {self.resource}"
        return self.code.message

    def to_dict(self) -> dict:
        out = {"code": self.code.name.lower(), "count": int(self.count)}
        if self.resource:
            out["resource"] = self.resource
        return out


def render_unschedulable(n_nodes: int, counts: Sequence[ReasonCount]) -> str:
    """The kube FitError headline: ``0/N nodes are available: 3 node(s) had
    taints that the pod didn't tolerate, 1 Insufficient cpu.`` — parts
    sorted by label like the reference's sorted reason map."""
    parts = [(c.count, c.label) for c in counts if c.count > 0]
    if not parts:
        return f"0/{n_nodes} nodes are available."
    body = ", ".join(f"{cnt} {msg}" for cnt, msg in sorted(parts, key=lambda x: x[1]))
    return f"0/{n_nodes} nodes are available: {body}."


def counts_from_rows(
    static_fail_row,
    fail_counts_row,
    insufficient_row,
    resource_names: Sequence[str],
) -> List[ReasonCount]:
    """Normalize one pod's engine failure-attribution rows into typed
    reason counts. ``static_fail_row`` covers the 4 template-static filters,
    ``fail_counts_row`` the dynamic ones (PORTS..EXTRA); FIT expands into
    per-resource lines from ``insufficient_row`` (kube reports Insufficient
    per resource, not per plugin)."""
    merged = list(static_fail_row) + list(fail_counts_row)
    out: List[ReasonCount] = []
    for code in sorted((r for r in Reason if r.is_filter), key=lambda r: r.value):
        cnt = int(merged[code.value])
        if cnt <= 0:
            continue
        if code is Reason.FIT:
            for r, rname in enumerate(resource_names):
                rcnt = int(insufficient_row[r])
                if rcnt > 0:
                    out.append(ReasonCount(code, rcnt, resource=str(rname)))
        else:
            out.append(ReasonCount(code, cnt))
    return out


@dataclass
class PlacementExplanation:
    """The per-pod decision-audit record (the tentpole's typed output).

    ``status``:
      - ``scheduled``     — landed on ``node`` (``forced`` marks pre-bound
        pods that bypassed the scheduler, simulator.go:329-331);
      - ``unschedulable`` — ``reasons`` carries the per-filter rejection
        counts and ``message`` their kube FitError rendering;
      - ``preempted``     — evicted post-bind by a preemption pass.

    The score fields (``scores`` per-plugin weighted contributions on the
    winner, ``runner_up``/``margin`` vs the second-best node) are filled by
    the on-demand deep evaluator (engine/explain.py:explain_pod) — never on
    the bulk path, where they would cost O(nodes) per pod."""

    pod: str
    status: str
    nodes_total: int = 0
    node: Optional[str] = None
    forced: bool = False
    reasons: List[ReasonCount] = field(default_factory=list)
    message: str = ""
    # deep (on-demand) fields
    scores: Optional[Dict[str, float]] = None
    score: Optional[float] = None
    runner_up: Optional[str] = None
    margin: Optional[float] = None

    def to_dict(self) -> dict:
        out: dict = {"pod": self.pod, "status": self.status}
        if self.node is not None:
            out["node"] = self.node
        if self.forced:
            out["forced"] = True
        if self.reasons:
            out["reasons"] = [c.to_dict() for c in self.reasons]
        if self.message:
            out["message"] = self.message
        for k in ("scores", "score", "runner_up", "margin"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


def format_rejects(rejects: Dict[str, int]) -> str:
    """One-line human rendering of a per-filter reject-total dict — shared
    by ``simon explain``, ``simon apply --explain``, and any future report
    surface so the wording cannot drift."""
    return ", ".join(f"{k}={v}" for k, v in sorted(rejects.items()))


def count_lines(counts: Sequence[ReasonCount]) -> List[str]:
    """The per-reason breakdown lines (`` <n> × <label>``) under a kube
    FitError headline, shared by every text surface."""
    return [f"{c.count:5d} × {c.label}" for c in counts]


def primary_code(counts: Sequence[ReasonCount]) -> Optional[Reason]:
    """The dominant rejection reason of one unschedulable pod: the filter
    rejecting the most nodes, ties broken by filter precedence (lowest
    index — the order the default profile runs them)."""
    best: Optional[ReasonCount] = None
    for c in counts:
        if best is None or c.count > best.count or (
            c.count == best.count and c.code.value < best.code.value
        ):
            best = c
    return best.code if best is not None else None


def rejects_dict(vec) -> Dict[str, int]:
    """An 11-slot per-filter reject vector (kernel filter-index order) as a
    ``{reason_name: count}`` dict, zero slots dropped."""
    out: Dict[str, int] = {}
    for code in sorted((r for r in Reason if r.is_filter), key=lambda r: r.value):
        n = int(vec[code.value])
        if n:
            out[code.name.lower()] = n
    return out
