"""Simulation facade — parity with ``pkg/simulator/core.go``.

``simulate(cluster, apps, ...)`` mirrors ``Simulate()``
(``pkg/simulator/core.go:67-117``): expand the cluster's workloads into
pods, schedule cluster pods first, then each app in configured order, and
return which pods landed where plus unschedulable reasons. The fake
apiserver + informers + scheduler goroutine of the reference collapse into
one encoded tensor state and one jitted scan.
"""

from __future__ import annotations

import copy
import functools
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..encoding.state import ClusterEncoder, ClusterMeta, ScanState
from ..models import expand
from ..models.objects import (
    ANNO_GPU_ASSUME_TIME,
    ANNO_GPU_INDEX,
    ANNO_NODE_GPU_SHARE,
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_WORKLOAD_KIND,
    LABEL_APP_NAME,
    LABEL_GPU_CARD_MODEL,
    Node,
    Pod,
    ResourceTypes,
)
from ..ops import kernels
from ..utils import envknobs
from ..resilience import breaker as breakers
from ..resilience import faults
from ..resilience.deadline import Deadline, check_deadline, deadline_scope
from . import queues, reasons
from .scheduler import pad_pod_stream, scan_unroll, schedule_pods, to_device


@dataclass
class AppResource:
    """Parity with core.go:54-57."""

    name: str
    resources: ResourceTypes


@dataclass
class UnscheduledPod:
    """Parity with core.go:25-28."""

    pod: Pod
    reason: str


@dataclass
class NodeStatus:
    """Parity with core.go:31-36."""

    node: Node
    pods: List[Pod] = field(default_factory=list)


@dataclass
class EngineDecision:
    """Which scheduling engine actually ran and why the others were skipped
    (VERDICT r4 #3: no silent engine fallbacks). ``name`` is one of
    ``megakernel`` (Pallas), ``native`` (C++), ``xla`` (lax.scan);
    ``skipped`` maps each engine that did NOT run to a one-line reason.

    For the C++ engine, ``native_path`` names the evaluation path that
    served the scheduled steps (``incremental`` / ``generic`` / ``mixed``)
    and ``native_steps`` carries the per-path step counts — a silent
    incremental-cache disengage is an attribution fact, not a guess from
    wall-clock (ISSUE 4)."""

    name: str
    skipped: Dict[str, str] = field(default_factory=dict)
    native_path: Optional[str] = None
    native_steps: Optional[Dict[str, int]] = None
    # observability (ISSUE 5): the serving request's propagated
    # X-Simon-Request-Id, stamped by the REST layer so a decision can be
    # joined back to its flight-recorder trace; None for library callers
    request_id: Optional[str] = None
    # decision audit (ISSUE 7, ``simulate(..., explain=True)``): one typed
    # PlacementExplanation per pod, the per-filter reject totals across all
    # audited steps ({reason_name: nodes rejected}), and the context object
    # the on-demand deep evaluator (explain.explain_pod) consumes — the ctx
    # references the full Prepared, so serializers must drop it
    explanations: Optional[list] = None
    filter_rejects: Optional[Dict[str, int]] = None
    explain_ctx: Optional[object] = None

    def describe(self) -> str:
        base = self.name if self.native_path is None else f"{self.name}/{self.native_path}"
        if not self.skipped:
            return base
        why = "; ".join(f"{k}: {v}" for k, v in sorted(self.skipped.items()))
        return f"{base} (skipped {why})"


@dataclass
class SimulateResult:
    """Parity with core.go:19-23."""

    unscheduled_pods: List[UnscheduledPod] = field(default_factory=list)
    node_status: List[NodeStatus] = field(default_factory=list)
    engine: Optional[EngineDecision] = None

    def pods_on(self, node_name: str) -> List[Pod]:
        for ns in self.node_status:
            if ns.node.metadata.name == node_name:
                return ns.pods
        return []


def _validate_extra_plugins(extra_plugins) -> None:
    if not isinstance(extra_plugins, tuple):
        raise ValueError("extra_plugins must be a tuple (jit requires a hashable static argument)")
    for entry in extra_plugins:
        if not isinstance(entry, tuple) or not entry or entry[0] not in ("filter", "score"):
            raise ValueError(f'extra plugin entries are ("filter", fn) or ("score", fn, weight); got {entry!r}')
        if entry[0] == "filter" and len(entry) != 2:
            raise ValueError(f'filter plugin entries are ("filter", fn); got {entry!r}')
        if entry[0] == "score" and len(entry) != 3:
            raise ValueError(f'score plugin entries are ("score", fn, weight); got {entry!r}')


def _rebuilt_counts(prep: "Prepared", chosen: np.ndarray):
    """Host-side reconstruction of the ScanState count tensors the
    megakernel tracks internally (port_used, dom_sel, dom_anti, dom_prefw)
    from the final placements — shared with the decision-audit replay
    (engine/explain.py owns the one implementation)."""
    from .explain import rebuild_counts

    return rebuild_counts(prep, chosen)


def _fast_output(
    chosen: np.ndarray,
    used_final: np.ndarray,
    static_fail: np.ndarray,
    gpu_take: np.ndarray,
    gpu_final: np.ndarray,
    vg_final: np.ndarray,
    dev_final: np.ndarray,
    prep: "Prepared",
):
    """Adapt the megakernel's outputs into the ScheduleOutput shape the
    decode path consumes. NOTE: final_state's count tensors (port_used,
    dom_sel, dom_anti, dom_prefw) keep their initial values here — no
    success-path consumer reads them; ``_fast_failure_details`` rebuilds
    them host-side (``_rebuilt_counts``) on the failure path, where the
    reason evaluation needs the complete carry."""
    from .scheduler import ScheduleOutput

    P = len(chosen)
    R = int(prep.ec.alloc.shape[1])
    n_dynamic = kernels.NUM_FILTERS - kernels.F_PORTS
    return ScheduleOutput(
        chosen=chosen,
        fail_counts=np.zeros((P, n_dynamic), np.int32),
        insufficient=np.zeros((P, R), np.int32),
        gpu_take=gpu_take.astype(np.float32),
        static_fail=static_fail,
        final_state=prep.st0._replace(
            used=used_final.astype(np.float32),
            gpu_free=gpu_final.astype(np.float32),
            vg_free=vg_final.astype(np.float32),
            dev_free=dev_final.astype(np.float32),
        ),
    )


@functools.partial(jax.jit, static_argnames=("feat",))
def _failure_eval(ec, stat, st, us, feat):
    """One compiled dispatch: pod_step over the batch of distinct failed
    templates against the (final) carry."""
    step = lambda u: kernels.pod_step(ec, stat, st, u, feat)
    res = jax.vmap(step)(us)
    return res.fail_counts, res.insufficient


def _fast_failure_details(out, prep: "Prepared", failed_idx: np.ndarray):
    """Per-pod failure attribution without re-scanning the whole stream:
    evaluate ``pod_step`` once per distinct failed template against the
    final carry. Exact when no bind landed after the first failure (the
    caller checks) — the state a failed pod saw is then the final state,
    since failed pods mutate nothing (simulator.go:333-342 deletes them)."""
    from . import fastpath

    port_used, dom_sel, dom_anti, dom_prefw = _rebuilt_counts(prep, np.asarray(out.chosen))
    st = out.final_state._replace(
        port_used=port_used, dom_sel=dom_sel, dom_anti=dom_anti, dom_prefw=dom_prefw
    )
    out = out._replace(final_state=st)
    st = ScanState(*[jnp.asarray(a) for a in st])
    stat = fastpath._precompute_jit(prep.ec)  # jit-cached for this ec
    us = np.unique(prep.tmpl_ids[failed_idx])
    fc_u, ins_u = _failure_eval(prep.ec, stat, st, jnp.asarray(us), prep.features)
    fc_u, ins_u = np.asarray(fc_u), np.asarray(ins_u)
    pos = {int(u): k for k, u in enumerate(us)}
    fail_counts = np.array(out.fail_counts, copy=True)
    insufficient = np.array(out.insufficient, copy=True)
    for i in failed_idx:
        k = pos[int(prep.tmpl_ids[i])]
        fail_counts[i] = fc_u[k]
        insufficient[i] = ins_u[k]
    return out._replace(fail_counts=fail_counts, insufficient=insufficient)


def _tmpl_hint(pod: Pod) -> Optional[tuple]:
    """Cheap template-identity key for workload-owned pods: all pods of one
    workload expansion share a scheduling spec. DaemonSet pods embed their
    pinned node (each targets a different one); bare pods get no hint and
    take the full canonical path."""
    kind = pod.metadata.annotations.get(ANNO_WORKLOAD_KIND)
    name = pod.metadata.annotations.get("simon/workload-name")
    if not kind or not name:
        return None
    # the owning object's uid disambiguates same-named workloads coming from
    # different sources (cluster snapshot vs apps, or two apps)
    owner_uid = pod.metadata.owner_references[0].uid if pod.metadata.owner_references else ""
    pin = pinned_node_name(pod) if kind == "DaemonSet" else ""
    return (pod.metadata.namespace, kind, name, owner_uid, pod.spec.node_name, pin)


def _owner_selector(pod: Pod) -> Optional[dict]:
    """Selector used for system-default topology spreading: the owning
    workload's pods share identical labels, so matching on the pod's own
    labels reproduces the RS/STS selector grouping that k8s
    buildDefaultConstraints derives from the owning objects."""
    if pod.metadata.annotations.get(ANNO_WORKLOAD_KIND) and pod.metadata.labels:
        return {"matchLabels": dict(pod.metadata.labels)}
    return None


def _cluster_pods(cluster: ResourceTypes) -> Tuple[List[Pod], int, List[int]]:
    """GetValidPodExcludeDaemonSet (pkg/simulator/utils.go:77-230): bare
    cluster pods minus DaemonSet-owned ones (those are re-expanded per
    node), plus expanded cluster workloads.

    Returns ``(pods, n_bare, ds_group_sizes)`` — the bare-pod prefix length
    and the per-DaemonSet expansion sizes (the DS pods form the stream
    tail, grouped in ``cluster.daemon_sets`` order). The delta re-encoder
    uses both to splice changes in at exactly the positions a fresh
    expansion would produce them."""
    ds_names = {(d.metadata.namespace, d.metadata.name) for d in cluster.daemon_sets}
    bare = [
        p
        for p in cluster.pods
        if not any(
            r.kind == "DaemonSet" and (p.metadata.namespace, r.name) in ds_names
            for r in p.metadata.owner_references
        )
    ]
    rt = ResourceTypes(
        pods=bare,
        deployments=cluster.deployments,
        replica_sets=cluster.replica_sets,
        stateful_sets=cluster.stateful_sets,
        jobs=cluster.jobs,
        cron_jobs=cluster.cron_jobs,
    )
    pods = expand.generate_pods_from_resources(rt, cluster.nodes, include_daemon_sets=False)
    ds_group_sizes: List[int] = []
    for ds in cluster.daemon_sets:
        group = expand.pods_from_daemon_set(ds, cluster.nodes)
        ds_group_sizes.append(len(group))
        pods.extend(group)
    return pods, len(bare), ds_group_sizes


def _reason_string(
    static_fail: np.ndarray,
    fail_counts: np.ndarray,
    insufficient: np.ndarray,
    meta: ClusterMeta,
    n_nodes: int,
) -> str:
    """The kube-scheduler FitError message the reference surfaces (e.g.
    '0/4 nodes are available: 3 node(s) had taints...'), rendered through
    the registered reason-code enum (engine/reasons.py, ISSUE 7).
    static_fail covers the 4 template-static filters, fail_counts the
    usage-dependent ones."""
    counts = reasons.counts_from_rows(
        static_fail, fail_counts, insufficient, meta.resource_names
    )
    return reasons.render_unschedulable(n_nodes, counts)


@dataclass
class Prepared:
    """Expanded + encoded simulation inputs, shared by the single-run path
    and the planner's scenario sweeps."""

    ec: object
    st0: object
    meta: ClusterMeta
    ordered: List[Pod]
    tmpl_ids: np.ndarray
    forced: np.ndarray
    ds_target: List[int]  # node index a DaemonSet pod is pinned to, -1 otherwise
    features: kernels.Features = kernels.ALL_FEATURES
    ec_np: object = None  # host-side numpy EncodedCluster (fast-path marshalling)
    # incremental-prepare provenance (engine/prepcache.py): the encoder that
    # built this (forked for delta re-encoding), the cluster-pod prefix
    # length of the stream, the bare-pod prefix within it, and the cluster
    # DaemonSet expansion group sizes (stream tail of the cluster region)
    encoder: object = None
    n_cluster: int = 0
    n_bare: int = 0
    ds_group_sizes: Optional[List[int]] = None
    # request-axis batching (engine/reqbatch.py): the half-open stream
    # slice each app's expanded pods occupy, in `apps` order — lets the
    # admission batcher mask per-request regions without re-deriving
    app_slices: Optional[List[Tuple[int, int]]] = None


def pinned_node_name(pod: Pod) -> str:
    """Target node of a DaemonSet pod pinned via matchFields metadata.name
    (SetDaemonSetPodNodeNameByNodeAffinity semantics)."""
    aff = (pod.spec.affinity or {}).get("nodeAffinity") or {}
    required = aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in required.get("nodeSelectorTerms") or []:
        for f in term.get("matchFields") or []:
            if f.get("key") == "metadata.name" and f.get("operator") == "In":
                vals = f.get("values") or []
                if len(vals) == 1:
                    return str(vals[0])
    return ""


def prepare(
    cluster: ResourceTypes,
    apps: List[AppResource],
    use_greed: bool = False,
    node_pad: int = 128,
    patch_pods_fn=None,
) -> Optional[Prepared]:
    """Expand cluster + app workloads into an ordered pod stream and encode
    everything into device tensors. Returns None when there are no pods."""
    from ..obs import trace as obs
    from ..utils.gcpause import gc_paused
    from ..utils.trace import PREP_STATS

    check_deadline("prepare")
    t0 = time.monotonic()
    with obs.span("prepare"), gc_paused():
        prep = _prepare_inner(cluster, apps, use_greed, node_pad, patch_pods_fn)
    PREP_STATS.record("full", time.monotonic() - t0)
    return prep


def _prepare_inner(cluster, apps, use_greed, node_pad, patch_pods_fn):
    enc = ClusterEncoder(node_pad=node_pad)
    enc.add_nodes(cluster.nodes)

    ordered: List[Pod] = []
    forced: List[bool] = []

    cluster_pods, n_bare, ds_group_sizes = _cluster_pods(cluster)
    for p in cluster_pods:
        ordered.append(p)
        forced.append(bool(p.spec.node_name))
    n_cluster = len(ordered)  # pods below went through patch_pods_fn

    app_slices: List[Tuple[int, int]] = []
    for app in apps:
        lo = len(ordered)
        app_pods = expand.generate_pods_from_resources(app.resources, cluster.nodes)
        for p in app_pods:
            p.metadata.labels.setdefault(LABEL_APP_NAME, app.name)
        # simulator.go:238-241: affinity sort then toleration sort
        app_pods = queues.toleration_sort(queues.affinity_sort(app_pods))
        if use_greed:
            app_pods = queues.greed_sort(cluster.nodes, app_pods)
        if patch_pods_fn is not None:
            patch_pods_fn(app.name, app_pods)
        for p in app_pods:
            ordered.append(p)
            forced.append(bool(p.spec.node_name))
        app_slices.append((lo, len(ordered)))

    if not ordered:
        return None

    from ..obs import trace as obs

    # expansion is done; the encode pass below is the expensive half of a
    # cold prepare — an exhausted deadline bails here rather than encoding
    # tensors nobody will schedule (and chaos injects encode failures here)
    with obs.span("encode", pods=len(ordered)):
        check_deadline("encode")
        faults.fault_point("prep.encode")

        # pods of one workload share a template: the hint short-circuits
        # canonical extraction (TemplateSet._hint_index) and the lazy selector
        # callable skips the per-pod dict build on hint hits. patch_pods_fn may
        # have mutated individual app pods, which the workload-identity hint
        # cannot see — those pods take the content-keyed extraction path.
        tmpl_ids = np.array(
            [
                enc.add_pod(
                    p,
                    (lambda p=p: _owner_selector(p)),
                    hint=None if (patch_pods_fn is not None and i >= n_cluster) else _tmpl_hint(p),
                )
                for i, p in enumerate(ordered)
            ],
            dtype=np.int32,
        )
        ec_np, st0, meta = enc.build()
        features = kernels.features_of(ec_np)
        ec, st0 = to_device(ec_np, st0)
    node_idx = {name: i for i, name in enumerate(meta.node_names)}
    # only DaemonSet expansion creates metadata.name matchFields pins; the
    # consumers (planner/defrag scenario masks) specifically want "DaemonSet
    # pod pinned to node i" — a bare pinned pod must stay in the stream and
    # fail visibly when its node vanishes, not be masked out like a DS pod
    ds_target = [
        node_idx.get(pinned_node_name(p), -1)
        if p.metadata.annotations.get(ANNO_WORKLOAD_KIND) == "DaemonSet"
        else -1
        for p in ordered
    ]
    return Prepared(
        ec=ec,
        st0=st0,
        meta=meta,
        ordered=ordered,
        tmpl_ids=tmpl_ids,
        forced=np.array(forced, dtype=bool),
        ds_target=ds_target,
        features=features,
        ec_np=ec_np,
        encoder=enc,
        n_cluster=n_cluster,
        n_bare=n_bare,
        ds_group_sizes=ds_group_sizes,
        app_slices=app_slices,
    )


def _run_segments(
    prep, segments, pod_valid, forced, tmpl_ids, extra_plugins, tie_seed,
    nv_mask, skips, log, explain=False,
):
    """Consecutive scans over contiguous same-profile segments, sharing the
    scheduling carry — the segmented multi-profile path
    (``utils.go:304-381``). Each segment runs the full padded stream with
    out-of-segment pods masked invalid (engines skip them without touching
    state), so binds happen in exact stream order; the final state of
    segment k seeds segment k+1. Returns (ScheduleOutput, engine_name);
    the output's static_fail is PER POD ([P, n_static], callers index it
    with sf_rows=arange) because static filter tables are config-dependent
    and failure attribution resolves per segment."""
    from ..obs import trace as obs
    from . import nativepath
    from .scheduler import pad_pod_stream, schedule_pods, scan_unroll

    P = len(tmpl_ids)
    n_dyn = kernels.NUM_FILTERS - kernels.F_PORTS
    R = int(prep.ec_np.alloc.shape[1])
    Gd = int(prep.ec_np.node_gpu_mem.shape[1])
    n_static = kernels.F_PORTS
    chosen = np.full((P,), -1, dtype=np.int32)
    fail_counts = np.zeros((P, n_dyn), np.int32)
    insufficient = np.zeros((P, R), np.int32)
    gpu_take = np.zeros((P, Gd), np.float32)
    sf_pod = np.zeros((P, n_static), np.int32)

    use_native = all(
        nativepath.why_not(prep, cfg, extra_plugins, tie_seed=tie_seed) is None
        for cfg, _, _ in segments
    )
    if not use_native:
        reasons = {
            nativepath.why_not(prep, cfg, extra_plugins, tie_seed=tie_seed)
            for cfg, _, _ in segments
        } - {None}
        skips["native"] = "; ".join(sorted(reasons)) or "segment config unsupported"
        log.info("segmented run on the XLA scan: %s", skips["native"])

    st = prep.st0
    final_state = None
    seg_stats = []
    for cfg, lo, hi in segments:
        seg_valid = np.zeros((P,), dtype=bool)
        seg_valid[lo:hi] = pod_valid[lo:hi]
        with obs.span(
            "engine.native" if use_native else "engine.xla", segment=f"{lo}:{hi}"
        ):
            if use_native:
                out = nativepath.schedule(
                    prep, seg_valid, config=cfg, node_valid=nv_mask,
                    tie_seed=tie_seed, st0=st, explain=explain,
                )
                if out.native_stats is not None:
                    seg_stats.append(out.native_stats)
            else:
                tmpl_p, valid_p, forced_p = pad_pod_stream(tmpl_ids, seg_valid, forced)
                ec_run = (
                    prep.ec._replace(node_valid=jnp.asarray(nv_mask))
                    if nv_mask is not None
                    else prep.ec
                )
                st_dev = ScanState(*[jnp.asarray(a) for a in st])
                out = schedule_pods(
                    ec_run, st_dev, tmpl_p, valid_p, forced_p,
                    features=prep.features, config=cfg, extra_plugins=extra_plugins,
                    unroll=scan_unroll(), tie_seed=tie_seed, explain=explain,
                )
                jax.block_until_ready(out.chosen)
        chosen[lo:hi] = np.asarray(out.chosen)[lo:hi]
        fail_counts[lo:hi] = np.asarray(out.fail_counts)[lo:hi]
        insufficient[lo:hi] = np.asarray(out.insufficient)[lo:hi]
        gpu_take[lo:hi] = np.asarray(out.gpu_take)[lo:hi]
        sf_seg = np.asarray(out.static_fail)
        sf_pod[lo:hi] = sf_seg[tmpl_ids[lo:hi]]
        st = out.final_state
        final_state = out.final_state

    from .scheduler import ScheduleOutput

    merged_stats = None
    if seg_stats:
        counts = {"incremental": 0, "generic": 0, "full_evals": 0}
        for st_ in seg_stats:
            for k in counts:
                counts[k] += int(st_["steps"].get(k, 0))
        inc, gen = counts["incremental"], counts["generic"]
        path = "mixed" if inc and gen else "incremental" if inc else "generic" if gen else "none"
        merged_stats = {"path": path, "steps": counts}

    stitched = ScheduleOutput(
        chosen=chosen,
        fail_counts=fail_counts,
        insufficient=insufficient,
        gpu_take=gpu_take,
        static_fail=sf_pod,  # per POD, not per template (sf_rows=arange)
        final_state=final_state,
        native_stats=merged_stats,
    )
    return stitched, ("native" if use_native else "xla")


def _run_engine_ladder(
    prep, segments, sched_config, pod_valid, forced, tmpl_ids, extra_plugins,
    tie_seed, nv_mask, ec, st0, log, explain=False,
):
    """The engine fallback ladder (megakernel → C++ native → XLA scan) for
    one prepared stream: selection pre-checks, breaker gating, runtime
    demotion. Returns ``(out, engine_name, skips, sf_rows)``. Split out of
    ``simulate`` so the whole ladder sits under one traced ``schedule``
    span with a child span per engine actually *attempted* (ISSUE 5) — a
    skipped rung gets a demotion event, not a span."""
    from ..obs import trace as obs

    out = None
    engine_name = "xla"
    skips: Dict[str, str] = {}
    require_tpu = envknobs.raw("OPENSIM_REQUIRE_TPU") == "1"
    interpret = envknobs.raw("OPENSIM_FASTPATH") == "interpret"
    sf_rows = tmpl_ids  # decode: static_fail row per pod
    if segments is not None:
        skips["megakernel"] = (
            f"segmented multi-profile stream ({len(segments)} segments)"
        )
        out, engine_name = _run_segments(
            prep, segments, pod_valid, forced, tmpl_ids, extra_plugins,
            tie_seed, nv_mask, skips, log, explain=explain,
        )
        sf_rows = np.arange(len(tmpl_ids), dtype=np.int32)
    # decision audit (ISSUE 7): explain mode needs every step's per-filter
    # verdicts — only the C++ generic path and the XLA count_all scan
    # produce them; the megakernel never materializes per-filter masks
    elif explain:
        skips["megakernel"] = "explain mode audits per-filter verdicts (C++/XLA engines)"
    # importing the megakernel module costs ~1 s of pallas Python-module
    # compile — only pay it where it can actually run (TPU backend, or
    # the tests' interpret mode); CPU hosts go straight to the C++ path.
    # These pre-import gates mirror the first checks of fastpath.why_not
    # (which stays authoritative once the module is imported) — they
    # exist only so the import itself can be skipped.
    elif nv_mask is not None:
        skips["megakernel"] = "masked re-simulation (planner prep reuse) runs on the C++/XLA engines"
    elif sched_config is not None:
        skips["megakernel"] = "non-default scheduler config"
    elif extra_plugins:
        skips["megakernel"] = "out-of-tree extra_plugins run on the XLA scan"
    elif tie_seed is not None:
        skips["megakernel"] = "sampled tie-break runs on the C++ engine or XLA scan"
    elif jax.default_backend() != "tpu" and not interpret:
        skips["megakernel"] = (
            f"no TPU backend (jax.default_backend()={jax.default_backend()!r})"
        )
    else:
        from . import fastpath

        miss = fastpath.why_not(prep)
        if miss is not None:
            skips["megakernel"] = miss
            log.info("megakernel envelope miss: %s", miss)
        elif (
            not require_tpu
            and not interpret
            and not breakers.engine_breaker("megakernel").allow()
        ):
            # runtime-failure circuit breaker (resilience/breaker.py):
            # after repeated compile/run failures the doomed attempt is
            # skipped outright until the cooldown's half-open probe.
            # Checked AFTER why_not so an envelope miss never consumes
            # the probe slot (allow() marks it; only an actual attempt
            # can release it). REQUIRE_TPU and the tests' interpret mode
            # bypass gating — both demand the real attempt (and its hard
            # failure) over a silent demotion.
            skips["megakernel"] = breakers.engine_breaker("megakernel").describe_block()
            log.warning("megakernel skipped: %s", skips["megakernel"])
        else:
            # Pallas megakernel fast path: identical placements, ~4×
            # the XLA scan's step rate. A Mosaic COMPILE failure (a
            # construct that passes interpret mode but not the real
            # compiler) must degrade to the slower engines — unless
            # --backend tpu demanded the TPU engine, where silently
            # benchmarking a fallback would be a lie (VERDICT r4 #3).
            try:
                with obs.span("engine.megakernel"):
                    f_chosen, f_used, sf, f_take, f_gpu, f_vg, f_dev = fastpath.schedule(
                        prep, tmpl_ids, pod_valid, forced
                    )
                # a clean kernel RUN is a breaker success even if the
                # result is later discarded for mid-stream attribution —
                # and recording here releases a half-open probe slot no
                # matter which path the result takes
                breakers.engine_breaker("megakernel").record_success()
            except Exception as e:
                if interpret:
                    # test/CI mode: a broken megakernel contract must
                    # FAIL, not silently validate the fallback engine
                    raise
                if require_tpu:
                    raise RuntimeError(
                        "--backend tpu: the Pallas megakernel failed to "
                        f"compile/run ({type(e).__name__}: {e}); refusing "
                        "to silently fall back to a slower engine"
                    ) from e
                breakers.engine_breaker("megakernel").record_failure(e)
                log.warning(
                    "megakernel failed (%s: %s); falling back to a "
                    "slower engine", type(e).__name__, e,
                )
                skips["megakernel"] = f"{type(e).__name__}: {e}"
                f_chosen = None
            if f_chosen is not None:
                failed = (f_chosen < 0) & pod_valid & ~forced
                if not failed.any():
                    out = _fast_output(f_chosen, f_used, sf, f_take, f_gpu, f_vg, f_dev, prep)
                    engine_name = "megakernel"
                else:
                    # Failure reasons without a second full scan: exact
                    # whenever nothing bound after the first failure (the
                    # state a failed pod saw is then the final carry —
                    # failed pods mutate nothing). Otherwise fall through
                    # to the XLA scan for exact mid-stream attribution.
                    first_fail = int(np.argmax(failed))
                    if not (f_chosen[first_fail + 1 :] >= 0).any():
                        out = _fast_output(
                            f_chosen, f_used, sf, f_take, f_gpu, f_vg, f_dev, prep
                        )
                        out = _fast_failure_details(out, prep, np.nonzero(failed)[0])
                        engine_name = "megakernel"
                    else:
                        skips["megakernel"] = (
                            "mid-stream scheduling failures need exact "
                            "in-stream attribution (full re-scan engine)"
                        )
                        log.info("megakernel result discarded: %s", skips["megakernel"])
    if out is None:
        from . import nativepath

        miss = nativepath.why_not(prep, sched_config, extra_plugins, tie_seed=tie_seed)
        native_breaker = breakers.engine_breaker("native")
        if miss is None and not native_breaker.allow():
            miss = native_breaker.describe_block()
        if miss is None:
            # C++ scan engine: identical placements to the XLA scan with
            # exact in-stream failure attribution; the default on hosts
            # without an accelerator (tests/test_native.py asserts parity).
            # A RUNTIME failure (ABI drift past the size check, injected
            # engine.compile fault, a crash in the .so) demotes this
            # request to the XLA scan and counts against the breaker —
            # the fallback ladder's bottom rung never silently loses work.
            try:
                with obs.span("engine.native"):
                    out = nativepath.schedule(
                        prep, pod_valid, config=sched_config, node_valid=nv_mask,
                        tie_seed=tie_seed, explain=explain,
                    )
                native_breaker.record_success()
                engine_name = "native"
            except Exception as e:
                native_breaker.record_failure(e)
                skips["native"] = f"{type(e).__name__}: {e}"
                log.warning(
                    "native engine failed (%s: %s); falling back to the "
                    "XLA scan", type(e).__name__, e,
                )
                out = None
        else:
            skips["native"] = miss
            log.info("native engine skipped: %s", miss)
    if out is None:
        with obs.span("engine.xla"):
            tmpl_p, valid_p, forced_p = pad_pod_stream(tmpl_ids, pod_valid, forced)
            ec_run = (
                ec._replace(node_valid=jnp.asarray(nv_mask)) if nv_mask is not None else ec
            )
            out = schedule_pods(
                ec_run, st0, tmpl_p, valid_p, forced_p,
                features=prep.features, config=sched_config, extra_plugins=extra_plugins,
                unroll=scan_unroll(), tie_seed=tie_seed, explain=explain,
            )
            jax.block_until_ready(out.chosen)  # dispatch is async; trace real device time
    return out, engine_name, skips, sf_rows


_REASON_EVENT_CAP = 8  # per-pod unschedulable events per schedule span


def _schedule_reason_events(
    obs, out, ordered, tmpl_ids, pod_valid, forced, sf_rows, meta, nv_mask,
    chosen=None, exclude=frozenset(),
):
    """Decision telemetry on the span tree (ISSUE 7): one instant event per
    unschedulable pod (capped at :data:`_REASON_EVENT_CAP`) plus a
    primary-reason histogram event, so the flight recorder answers *what*
    the scheduler decided, not only how long it took. Usually emitted under
    the schedule span; preemption runs pass the post-preemption ``chosen``
    (and the victim set to ``exclude`` — victims fail by eviction, not by a
    filter) so the events never contradict the response. A no-op without an
    ambient trace or without failures."""
    if obs.current_trace() is None:
        return
    P = len(ordered)
    if chosen is None:
        chosen = np.asarray(out.chosen)[:P]
    failed = pod_valid & ~forced & (np.asarray(chosen)[:P] < 0)
    if not failed.any():
        return
    from . import explain as explain_mod

    static_fail = np.asarray(out.static_fail)
    fail_counts = np.asarray(out.fail_counts)[:P]
    insufficient = np.asarray(out.insufficient)[:P]
    n_nodes = int(nv_mask.sum()) if nv_mask is not None else meta.n_real_nodes
    idx = np.array([i for i in np.nonzero(failed)[0] if int(i) not in exclude])
    if not len(idx):
        return
    hist = explain_mod.primary_reason_histogram(static_fail, sf_rows, fail_counts, idx)
    obs.event(
        "placement.reasons",
        unschedulable=int(len(idx)),
        **{f"reason_{k}": v for k, v in sorted(hist.items())},
    )
    for i in idx[:_REASON_EVENT_CAP]:
        pod = ordered[i]
        obs.event(
            "placement.unschedulable",
            pod=f"{pod.metadata.namespace}/{pod.metadata.name}",
            reason=_reason_string(
                static_fail[int(sf_rows[i])], fail_counts[i], insufficient[i],
                meta, n_nodes,
            ),
        )


def parse_tie_break(spec: str):
    """CLI ``--tie-break`` value → tie_seed (None = deterministic default).
    Accepted: ``sample`` (seed 0) or ``sample:<int>``."""
    if not spec or spec == "lowest":
        return None
    if spec == "sample":
        return 0
    if spec.startswith("sample:"):
        try:
            return int(spec.split(":", 1)[1])
        except ValueError:
            pass
    raise ValueError(f"--tie-break must be 'lowest' or 'sample[:seed]', got {spec!r}")


def simulate(
    cluster: ResourceTypes,
    apps: List[AppResource],
    use_greed: bool = False,
    node_pad: int = 128,
    sched_config=None,
    patch_pods_fn=None,
    extra_plugins: tuple = (),
    enable_preemption: bool = False,
    tie_seed: Optional[int] = None,
    prep: Optional["Prepared"] = None,
    node_valid: Optional[np.ndarray] = None,
    drop_pods: Optional[np.ndarray] = None,
    deadline: Optional[Deadline] = None,
    explain: bool = False,
) -> SimulateResult:
    """One full simulation: cluster pods then apps in order. `sched_config`
    is an optional SchedulerConfig (the --default-scheduler-config merge);
    `patch_pods_fn(app_name, pods)` mirrors WithPatchPodsFuncMap
    (pkg/simulator/simulator.go:243-249, :471-500) — a caller hook that may
    mutate each app's expanded pods before they are scheduled.
    `extra_plugins` is the WithExtraRegistry equivalent: out-of-tree
    jittable filter/score plugins (see kernels.pod_step).

    `prep`/`node_valid` (planner prep reuse — VERDICT r4 #5): run against
    an existing Prepared whose node axis is masked down to `node_valid`.
    `cluster.nodes` must be exactly the valid prefix of the prepared node
    order (the planner slices its candidate list). Placements, reasons and
    node annotations are identical to a fresh prepare of the sub-cluster:
    invalid nodes never enter any filter-failure bucket
    (kernels.precompute_static starts its fold from node_valid) and
    DaemonSet pods pinned to masked-out candidates are dropped from the
    stream exactly as a smaller expansion would never create them.

    `drop_pods` (incremental prepare): a bool mask over the prepared pod
    stream; marked pods are excluded from scheduling AND from the report,
    exactly as if the pods had never been in the input — the valid-mask
    flip that lets a cached Prepared serve a cluster whose pods shrank
    (e.g. scale-apps removing a workload's existing pods).

    `deadline` (resilience): a request time budget enforced at phase
    boundaries (prepare/encode/schedule/decode) — exhaustion raises
    ``DeadlineExceeded`` naming the phase instead of hanging. Callers may
    equivalently install a ``resilience.deadline.deadline_scope``.

    `explain` (decision audit, ISSUE 7): attach one typed
    ``PlacementExplanation`` per pod plus the per-filter reject totals to
    ``result.engine`` (``explanations`` / ``filter_rejects`` /
    ``explain_ctx`` for the deep evaluator). Runs on the C++ generic path
    or the XLA count_all scan — engine-consistent by the reason-parity
    gate — and costs nothing when False (the default compiled scan and the
    incremental C++ path are untouched)."""
    from ..obs import trace as obs
    from ..utils.trace import Trace

    if deadline is not None:
        # install the request deadline as the ambient scope once, then run
        # the body with deadline=None — phase checks (prepare/encode/
        # schedule/decode) read the contextvar, so callers that already
        # installed a scope (the REST server) compose with direct callers
        with deadline_scope(deadline):
            return simulate(
                cluster, apps, use_greed=use_greed, node_pad=node_pad,
                sched_config=sched_config, patch_pods_fn=patch_pods_fn,
                extra_plugins=extra_plugins, enable_preemption=enable_preemption,
                tie_seed=tie_seed, prep=prep, node_valid=node_valid,
                drop_pods=drop_pods, explain=explain,
            )

    _validate_extra_plugins(extra_plugins)
    if prep is not None and enable_preemption:
        raise ValueError("prep reuse does not support enable_preemption; pass prep=None")
    if drop_pods is not None and prep is None:
        raise ValueError("drop_pods is a mask over an existing Prepared; pass prep=")
    with Trace("Simulate", threshold_s=1.0) as tr:
        if prep is None:
            prep = prepare(
                cluster, apps, use_greed=use_greed, node_pad=node_pad, patch_pods_fn=patch_pods_fn
            )
            tr.step("expand and encode")
        else:
            tr.step("reuse prepared encoding")
        if prep is None:
            return SimulateResult(
                node_status=[NodeStatus(node=n, pods=[]) for n in cluster.nodes]
            )
        ec, st0, meta = prep.ec, prep.st0, prep.meta
        ordered, tmpl_ids, forced = prep.ordered, prep.tmpl_ids, prep.forced

        nv_mask: Optional[np.ndarray] = None
        drops: set = set()
        if drop_pods is not None:
            dm = np.asarray(drop_pods, dtype=bool)
            if dm.shape[0] != len(prep.ordered):
                raise ValueError("drop_pods mask must cover the prepared pod stream")
            drops |= {int(i) for i in np.nonzero(dm)[0]}
        if node_valid is not None:
            nv_mask = np.asarray(node_valid, dtype=bool)
            if nv_mask.shape[0] != int(np.asarray(prep.ec_np.node_valid).shape[0]):
                raise ValueError("node_valid mask must cover the prepared (padded) node axis")
            names = [n.metadata.name for n in cluster.nodes]
            if names != list(meta.node_names[: len(names)]):
                raise ValueError(
                    "cluster.nodes must be the valid prefix of the prepared node order"
                )
            n_valid = int(nv_mask.sum())
            if n_valid != len(names) or not nv_mask[:n_valid].all():
                raise ValueError("node_valid must select exactly cluster.nodes as a prefix")
            # DaemonSet pods pinned to masked-out nodes would not exist in a
            # fresh expansion of the sub-cluster: drop them from the stream
            drops |= {
                i for i, t in enumerate(prep.ds_target) if t >= 0 and not nv_mask[t]
            }

        pod_valid = np.ones((len(ordered),), dtype=bool)
        for i in drops:
            pod_valid[i] = False
        # multi-profile KubeSchedulerConfiguration: route the stream onto one
        # effective config; pods naming an unknown profile never enter any
        # scheduling queue (kube event-handler filtering) and are reported
        # unschedulable with an explicit reason. Force-bound pods bypass the
        # scheduler entirely (simulator.go:329-331) — profiles don't apply.
        custom_reasons: Dict[int, str] = {}
        segments = None
        if sched_config is not None:
            from .schedconfig import DEFAULT_CONFIG, resolve_profile_segments

            segs, custom_reasons = resolve_profile_segments(
                sched_config, ordered, meta.resource_names, forced=forced
            )
            for i in custom_reasons:
                pod_valid[i] = False
            if len(segs) == 1:
                sched_config = segs[0][0]
                if sched_config == DEFAULT_CONFIG:
                    sched_config = None  # fast-path eligible
            else:
                # differing profiles (utils.go:304-381): consecutive scans
                # per contiguous same-profile segment, sharing the carry
                if enable_preemption:
                    raise ValueError(
                        "segmented multi-profile simulation does not support "
                        "enable_preemption"
                    )
                segments = [
                    (None if c == DEFAULT_CONFIG else c, lo, hi) for c, lo, hi in segs
                ]
                sched_config = None
        import logging

        log = logging.getLogger("opensim_tpu")
        check_deadline("schedule")
        with obs.span("schedule", pods=len(ordered)) as _sched_span:
            out, engine_name, skips, sf_rows = _run_engine_ladder(
                prep, segments, sched_config, pod_valid, forced, tmpl_ids,
                extra_plugins, tie_seed, nv_mask, ec, st0, log, explain=explain,
            )
            nstats = getattr(out, "native_stats", None)
            engine = EngineDecision(
                name=engine_name,
                skipped=skips,
                native_path=nstats["path"] if nstats else None,
                native_steps=dict(nstats["steps"]) if nstats else None,
            )
            # every rung that did NOT run is an instant demotion span, so
            # the flight-recorder tree carries exactly the attribution
            # EngineDecision.skipped reports (tests assert they match)
            for k, v in sorted(skips.items()):
                obs.event(f"engine.{k}.skipped", status="demoted", engine=k, reason=v)
            engine_label = engine_name if nstats is None else f"{engine_name}/{nstats['path']}"
            _sched_span.set(engine=engine_label)
            if not enable_preemption:
                # preemption rewrites `chosen` in decode: emitting here
                # would report pods the preempt pass later schedules
                _schedule_reason_events(
                    obs, out, ordered, tmpl_ids, pod_valid, forced, sf_rows,
                    meta, nv_mask,
                )
        tr.step(f"schedule {len(ordered)} pods [engine={engine_label}]")
    check_deadline("decode")
    with obs.span("decode", pods=len(ordered)):
        out = out._replace(
            chosen=out.chosen[: len(ordered)],
            fail_counts=out.fail_counts[: len(ordered)],
            insufficient=out.insufficient[: len(ordered)],
            gpu_take=out.gpu_take[: len(ordered)],
        )
        chosen = np.asarray(out.chosen)
        fail_counts = np.asarray(out.fail_counts)
        insufficient = np.asarray(out.insufficient)
        gpu_take = np.asarray(out.gpu_take)
        static_fail = np.asarray(out.static_fail)

        victims_of: Dict[int, int] = {}
        if enable_preemption and (chosen[~forced] < 0).any():
            from . import preemption

            fs = out.final_state
            # np.asarray of a jax array is a read-only view — preemption mutates
            gpu_take = np.array(gpu_take, copy=True)
            used = np.array(np.asarray(fs.used), copy=True)
            state = {
                "port_used": np.array(np.asarray(fs.port_used), copy=True),
                "gpu_free": np.array(np.asarray(fs.gpu_free), copy=True),
                "vg_free": np.array(np.asarray(fs.vg_free), copy=True),
                "dev_free": np.array(np.asarray(fs.dev_free), copy=True),
            }
            all_pdbs = tuple(cluster.pdbs) + tuple(
                pdb for app in apps for pdb in app.resources.pdbs
            )
            chosen, victims_of = preemption.preempt_pass(
                prep, chosen, cluster.nodes, used, np.asarray(prep.ec_np.alloc),
                gpu_take=gpu_take, pdbs=all_pdbs, eligible=pod_valid, **state,
            )
            out = out._replace(final_state=fs._replace(used=used, **state))
        if enable_preemption:
            # post-preemption telemetry: the events reflect the FINAL
            # outcome (victims are excluded — they fail by eviction)
            _schedule_reason_events(
                obs, out, ordered, tmpl_ids, pod_valid, forced, sf_rows, meta,
                nv_mask, chosen=chosen, exclude=frozenset(victims_of),
            )

        unscheduled, statuses = finish_decode(
            prep, out, cluster, chosen, gpu_take, fail_counts, insufficient,
            static_fail, sf_rows, pod_valid, forced, custom_reasons,
            victims_of, drops, nv_mask, sched_config, segments, extra_plugins,
            engine, engine_name, explain,
        )
    return SimulateResult(unscheduled_pods=unscheduled, node_status=statuses, engine=engine)


def finish_decode(
    prep: "Prepared",
    out,
    cluster: ResourceTypes,
    chosen: np.ndarray,
    gpu_take: np.ndarray,
    fail_counts: np.ndarray,
    insufficient: np.ndarray,
    static_fail: np.ndarray,
    sf_rows: np.ndarray,
    pod_valid: np.ndarray,
    forced: np.ndarray,
    custom_reasons: Dict[int, str],
    victims_of: Dict[int, int],
    drops: set,
    nv_mask: Optional[np.ndarray],
    sched_config,
    segments,
    extra_plugins: tuple,
    engine: EngineDecision,
    engine_name: str,
    explain: bool,
) -> Tuple[List[UnscheduledPod], List[NodeStatus]]:
    """The host-side decode tail shared by :func:`simulate` and the
    request-axis batch entry (``engine/reqbatch.py``): bind pods into node
    buckets, render unschedulable reasons, write node usage annotations,
    bump the always-on decision metrics, and attach the explain audit.
    All array arguments are host numpy, already trimmed to
    ``len(prep.ordered)``. ``drops`` is an index set or a bool mask (the
    request-axis batch path builds masks by slice assignment instead of
    unioning per-rider index ranges)."""
    from ..utils.gcpause import gc_paused

    decode_drops = drops
    if isinstance(drops, np.ndarray):
        # set semantics only for the consumers that need membership:
        # custom-reason metrics and the explain audit (rare paths)
        drops = (
            set(np.nonzero(drops)[0].tolist())
            if (custom_reasons or explain)
            else set()
        )

    meta, ordered = prep.meta, prep.ordered
    node_pods: Dict[str, List[Pod]] = {n.metadata.name: [] for n in cluster.nodes}
    unscheduled: List[UnscheduledPod] = []
    n_nodes = int(nv_mask.sum()) if nv_mask is not None else meta.n_real_nodes
    node_names = meta.node_names
    # masked runs: candidate nodes beyond the valid prefix have no report
    # bucket (chosen never points at an invalid node)
    pod_lists = [node_pods.get(n) for n in node_names]
    gpu_any = gpu_take.sum(axis=1) > 0  # one vectorized pass, not per-pod sums

    with gc_paused():
        statuses = _decode(
            ordered, chosen, forced, custom_reasons, victims_of, gpu_any, gpu_take,
            sf_rows, static_fail, fail_counts, insufficient, meta, n_nodes,
            node_names, pod_lists, node_pods, unscheduled, cluster, out,
            decode_drops,
        )
    _record_decision_metrics(
        chosen, pod_valid, forced, custom_reasons, victims_of, drops,
        static_fail, sf_rows, fail_counts,
    )
    if explain:
        from . import explain as explain_mod

        ctx = explain_mod.ExplainContext(
            prep=prep, chosen=chosen, gpu_take=gpu_take,
            static_fail=static_fail, sf_rows=np.asarray(sf_rows),
            fail_counts=fail_counts, insufficient=insufficient,
            n_nodes=n_nodes, node_names=node_names,
            resource_names=meta.resource_names, config=sched_config,
            segments=segments, extra_plugins=extra_plugins,
            engine=engine_name, node_valid=nv_mask,
        )
        engine.explain_ctx = ctx
        engine.explanations = explain_mod.build_explanations(
            ctx, custom_reasons, victims_of, drops
        )
        # per-filter reject totals across ALL audited steps: the C++
        # engine accumulated them in-engine (ScanArgs.filter_rejects,
        # abi v4); the XLA/segmented paths derive the identical vector
        # from the count_all per-pod rows
        rejects_vec = getattr(out, "filter_rejects", None)
        if rejects_vec is None:
            rejects_vec = explain_mod.audit_rejects(
                static_fail, sf_rows, fail_counts, pod_valid & ~forced
            )
        engine.filter_rejects = reasons.rejects_dict(rejects_vec)
    return unscheduled, statuses


def _record_decision_metrics(
    chosen, pod_valid, forced, custom_reasons, victims_of, drops,
    static_fail, sf_rows, fail_counts,
):
    """Always-on decision counters (ISSUE 7, /metrics):
    ``simon_unschedulable_total{reason=}`` — pods by primary reason — and
    ``simon_filter_reject_total{filter=}`` — node-level rejects from the
    failure attribution every engine computes for unschedulable pods.
    Independent of explain mode so dashboards see identical series either
    way."""
    from ..obs.metrics import RECORDER
    from . import explain as explain_mod

    failed = pod_valid & ~forced & (np.asarray(chosen) < 0)
    attributed = [
        int(i)
        for i in np.nonzero(failed)[0]
        if int(i) not in victims_of and int(i) not in custom_reasons
    ]
    hist = explain_mod.primary_reason_histogram(
        static_fail, sf_rows, fail_counts, attributed
    )
    nnf = int((forced & (np.asarray(chosen) < 0) & pod_valid).sum())
    if nnf:
        hist["node_not_found"] = hist.get("node_not_found", 0) + nnf
    n_unknown = sum(1 for i in custom_reasons if i not in drops)
    if n_unknown:
        hist["unknown_profile"] = hist.get("unknown_profile", 0) + n_unknown
    if victims_of:
        hist["preempted"] = hist.get("preempted", 0) + len(victims_of)
    if hist:
        RECORDER.count_unschedulable(hist)
    if attributed:
        mask = np.zeros(len(pod_valid), dtype=bool)
        mask[attributed] = True
        rejects = explain_mod.audit_rejects(static_fail, sf_rows, fail_counts, mask)
        RECORDER.count_filter_rejects(reasons.rejects_dict(rejects))


def snapshot_bind_state(prep: "Prepared") -> list:
    """Capture everything ``_decode`` mutates on the prepared pods so a
    caller re-running simulations over one Prepared (the planner's
    sequential differing-profile probes) can restore between runs. Kept
    NEXT TO ``_decode`` on purpose: any new bind-time pod mutation must be
    added to both."""
    return [
        (
            p.spec.node_name,
            p.phase,
            p.metadata.annotations.get(ANNO_GPU_INDEX),
            p.metadata.annotations.get(ANNO_GPU_ASSUME_TIME),
        )
        for p in prep.ordered
    ]


def restore_bind_state(prep: "Prepared", snap: list) -> None:
    for p, (node_name, phase, gpu_idx, assume) in zip(prep.ordered, snap):
        p.spec.node_name = node_name
        p.phase = phase
        if gpu_idx is None:
            p.metadata.annotations.pop(ANNO_GPU_INDEX, None)
        else:
            p.metadata.annotations[ANNO_GPU_INDEX] = gpu_idx
        if assume is None:
            p.metadata.annotations.pop(ANNO_GPU_ASSUME_TIME, None)
        else:
            p.metadata.annotations[ANNO_GPU_ASSUME_TIME] = assume


def _drop_mask(drop_pods, n: int) -> Optional[np.ndarray]:
    """Normalize the drop specification — a bool mask, an index iterable,
    or empty — into one [n] bool mask (None when nothing drops)."""
    if isinstance(drop_pods, np.ndarray):
        if drop_pods.dtype == bool:
            return drop_pods[:n] if drop_pods.any() else None
        mask = np.zeros(n, dtype=bool)
        mask[drop_pods.astype(np.intp)] = True
        return mask
    if drop_pods:
        mask = np.zeros(n, dtype=bool)
        mask[np.fromiter(drop_pods, dtype=np.intp, count=len(drop_pods))] = True
        return mask
    return None


def _decode(
    ordered, chosen, forced, custom_reasons, victims_of, gpu_any, gpu_take,
    sf_rows, static_fail, fail_counts, insufficient, meta, n_nodes,
    node_names, pod_lists, node_pods, unscheduled, cluster, out, drop_pods=(),
):
    # Vectorized decode (ISSUE 16): one numpy pass classifies the whole
    # stream — dropped / placed / failed — and Python only touches the
    # pods that actually need mutation or a reason string. In the
    # request-axis batch path most of the stream is foreign drops, so the
    # old per-pod `i in drop_pods` + `int(chosen[i])` loop paid N set
    # lookups and N scalar conversions per rider for pods it then skipped.
    # Both output lists stay in ascending stream order (placed pods and
    # failures land in DISJOINT lists, so two ordered passes are
    # bit-identical to the one interleaved loop).
    n = len(ordered)
    chosen_np = np.asarray(chosen)
    active = np.ones(n, dtype=bool)
    dropm = _drop_mask(drop_pods, n)
    if dropm is not None:
        # dropped pods (scale-removed, twin-deleted, foreign riders, or a
        # DaemonSet pod pinned to a masked-out candidate node): a fresh
        # expansion of the sub-cluster would never have created them
        active &= ~dropm
    placed_idx = np.nonzero(active & (chosen_np >= 0))[0]
    failed_idx = np.nonzero(active & (chosen_np < 0))[0]
    forced_np = np.asarray(forced, dtype=bool)

    for i, c in zip(placed_idx.tolist(), chosen_np[placed_idx].astype(int).tolist()):
        pod = ordered[i]
        pod.spec.node_name = node_names[c]
        pod.phase = "Running"
        # gpu-index annotation parity (GetUpdatedPodAnnotationSpec,
        # gpushare utils/pod.go:116-127): device ids, one per packed slot
        if gpu_any[i]:
            ids: List[str] = []
            for d, cnt in enumerate(gpu_take[i]):
                ids.extend([str(d)] * int(round(float(cnt))))
            pod.metadata.annotations[ANNO_GPU_INDEX] = "-".join(ids)
            # assume-time annotation (gpushare utils/pod.go:125): bind
            # timestamp in nanoseconds
            pod.metadata.annotations[ANNO_GPU_ASSUME_TIME] = str(time.time_ns())
        pod_lists[c].append(pod)

    for i in failed_idx.tolist():
        pod = ordered[i]
        if forced_np[i]:
            unscheduled.append(UnscheduledPod(pod, reasons.node_not_found(pod.spec.node_name)))
        elif i in custom_reasons:
            unscheduled.append(UnscheduledPod(pod, custom_reasons[i]))
        elif i in victims_of:
            preemptor = ordered[victims_of[i]]
            unscheduled.append(
                UnscheduledPod(
                    pod,
                    reasons.preempted(
                        preemptor.metadata.namespace, preemptor.metadata.name
                    ),
                )
            )
        else:
            unscheduled.append(
                UnscheduledPod(
                    pod,
                    _reason_string(
                        static_fail[int(sf_rows[i])], fail_counts[i], insufficient[i], meta, n_nodes
                    ),
                )
            )

    return _node_statuses(cluster.nodes, node_pods, out, meta)


def _node_statuses(nodes, node_pods, out, meta: ClusterMeta) -> List[NodeStatus]:
    """Write final storage/GPU usage back into node annotations — parity
    with the Bind plugins updating the fake cluster's node objects
    (open-local.go:175-254 writes simon/node-local-storage;
    open-gpu-share.go Reserve writes simon/node-gpu-share)."""
    vg_free = np.asarray(out.final_state.vg_free)
    dev_free = np.asarray(out.final_state.dev_free)
    gpu_free = np.asarray(out.final_state.gpu_free)

    statuses: List[NodeStatus] = []
    for idx, orig in enumerate(nodes):
        # annotations get storage/GPU usage written back; shallow-copy the
        # node and give it fresh metadata so the caller's objects stay
        # untouched without deep-copying 5k raw dicts
        node = copy.copy(orig)
        node.metadata = copy.copy(orig.metadata)
        node.metadata.annotations = dict(orig.metadata.annotations)
        node.metadata.labels = dict(orig.metadata.labels)
        pods = node_pods[node.metadata.name]
        vg_names = meta.node_vg_names[idx] if idx < len(meta.node_vg_names) else []
        dev_names = meta.node_dev_names[idx] if idx < len(meta.node_dev_names) else []
        if vg_names or dev_names:
            vgs = []
            for j, name in enumerate(vg_names):
                cap = float(meta.node_vg_cap[idx, j])
                vgs.append({"name": name, "capacity": int(cap), "requested": int(cap - vg_free[idx, j])})
            devices = []
            for j, name in enumerate(dev_names):
                devices.append(
                    {
                        "name": name,
                        "device": name,
                        "capacity": int(meta.node_dev_cap[idx, j]),
                        "mediaType": "ssd" if int(meta.node_dev_media[idx, j]) == 0 else "hdd",
                        "isAllocated": bool(dev_free[idx, j] == 0 and meta.node_dev_cap[idx, j] > 0),
                    }
                )
            node.metadata.annotations[ANNO_NODE_LOCAL_STORAGE] = json.dumps({"vgs": vgs, "devices": devices})
        gpu_count = int(meta.node_gpu_count[idx]) if meta.node_gpu_count is not None else 0
        if gpu_count > 0:
            devs = {}
            for d in range(gpu_count):
                total = float(meta.node_gpu_mem[idx, d])
                devs[str(d)] = {
                    "GpuTotalMemory": int(total),
                    "GpuUsedMemory": int(total - gpu_free[idx, d]),
                    "PodList": [p.metadata.name for p in pods if _pod_uses_device(p, d)],
                }
            info = {
                "GpuCount": gpu_count,
                "GpuTotalMemory": int(sum(v["GpuTotalMemory"] for v in devs.values())),
                "GpuModel": node.metadata.labels.get(LABEL_GPU_CARD_MODEL, "N/A"),
                "NumPods": sum(1 for p in pods if ANNO_GPU_INDEX in p.metadata.annotations),
                "DevsBrief": devs,
            }
            node.metadata.annotations[ANNO_NODE_GPU_SHARE] = json.dumps(info)
        statuses.append(NodeStatus(node=node, pods=pods))
    return statuses


def _pod_uses_device(pod: Pod, device: int) -> bool:
    idx = pod.metadata.annotations.get(ANNO_GPU_INDEX, "")
    return str(device) in idx.split("-") if idx else False
