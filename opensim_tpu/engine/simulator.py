"""Simulation facade — parity with ``pkg/simulator/core.go``.

``simulate(cluster, apps, ...)`` mirrors ``Simulate()``
(``pkg/simulator/core.go:67-117``): expand the cluster's workloads into
pods, schedule cluster pods first, then each app in configured order, and
return which pods landed where plus unschedulable reasons. The fake
apiserver + informers + scheduler goroutine of the reference collapse into
one encoded tensor state and one jitted scan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..encoding.state import ClusterEncoder, ClusterMeta
from ..models import expand
from ..models.objects import (
    ANNO_WORKLOAD_KIND,
    LABEL_APP_NAME,
    Node,
    Pod,
    ResourceTypes,
)
from ..ops import kernels
from . import queues
from .scheduler import schedule_pods, to_device


@dataclass
class AppResource:
    """Parity with core.go:54-57."""

    name: str
    resources: ResourceTypes


@dataclass
class UnscheduledPod:
    """Parity with core.go:25-28."""

    pod: Pod
    reason: str


@dataclass
class NodeStatus:
    """Parity with core.go:31-36."""

    node: Node
    pods: List[Pod] = field(default_factory=list)


@dataclass
class SimulateResult:
    """Parity with core.go:19-23."""

    unscheduled_pods: List[UnscheduledPod] = field(default_factory=list)
    node_status: List[NodeStatus] = field(default_factory=list)

    def pods_on(self, node_name: str) -> List[Pod]:
        for ns in self.node_status:
            if ns.node.metadata.name == node_name:
                return ns.pods
        return []


def _owner_selector(pod: Pod) -> Optional[dict]:
    """Selector used for system-default topology spreading: the owning
    workload's pods share identical labels, so matching on the pod's own
    labels reproduces the RS/STS selector grouping that k8s
    buildDefaultConstraints derives from the owning objects."""
    if pod.metadata.annotations.get(ANNO_WORKLOAD_KIND) and pod.metadata.labels:
        return {"matchLabels": dict(pod.metadata.labels)}
    return None


def _cluster_pods(cluster: ResourceTypes) -> List[Pod]:
    """GetValidPodExcludeDaemonSet (pkg/simulator/utils.go:77-230): bare
    cluster pods minus DaemonSet-owned ones (those are re-expanded per
    node), plus expanded cluster workloads."""
    ds_names = {(d.metadata.namespace, d.metadata.name) for d in cluster.daemon_sets}
    rt = ResourceTypes(
        pods=[
            p
            for p in cluster.pods
            if not any(
                r.kind == "DaemonSet" and (p.metadata.namespace, r.name) in ds_names
                for r in p.metadata.owner_references
            )
        ],
        deployments=cluster.deployments,
        replica_sets=cluster.replica_sets,
        stateful_sets=cluster.stateful_sets,
        daemon_sets=cluster.daemon_sets,
        jobs=cluster.jobs,
        cron_jobs=cluster.cron_jobs,
    )
    return expand.generate_pods_from_resources(rt, cluster.nodes)


def _reason_string(
    fail_counts: np.ndarray, insufficient: np.ndarray, meta: ClusterMeta, n_nodes: int
) -> str:
    """Reconstruct the kube-scheduler FitError message format the reference
    surfaces (e.g. '0/4 nodes are available: 3 node(s) had taints...')."""
    parts: List[Tuple[int, str]] = []
    for k in range(kernels.NUM_FILTERS):
        cnt = int(fail_counts[k])
        if cnt <= 0:
            continue
        if k == kernels.F_FIT:
            for r, rname in enumerate(meta.resource_names):
                rcnt = int(insufficient[r])
                if rcnt > 0:
                    parts.append((rcnt, f"Insufficient {rname}"))
        else:
            parts.append((cnt, kernels.FILTER_REASONS[k]))
    if not parts:
        return f"0/{n_nodes} nodes are available."
    body = ", ".join(f"{cnt} {msg}" for cnt, msg in sorted(parts, key=lambda x: x[1]))
    return f"0/{n_nodes} nodes are available: {body}."


def simulate(
    cluster: ResourceTypes,
    apps: List[AppResource],
    use_greed: bool = False,
    node_pad: int = 8,
) -> SimulateResult:
    """One full simulation: cluster pods then apps in order."""
    enc = ClusterEncoder(node_pad=node_pad)
    enc.add_nodes(cluster.nodes)

    ordered: List[Pod] = []
    forced: List[bool] = []

    for p in _cluster_pods(cluster):
        ordered.append(p)
        forced.append(bool(p.spec.node_name))

    for app in apps:
        app_pods = expand.generate_pods_from_resources(app.resources, cluster.nodes)
        for p in app_pods:
            p.metadata.labels.setdefault(LABEL_APP_NAME, app.name)
        # simulator.go:238-241: affinity sort then toleration sort
        app_pods = queues.toleration_sort(queues.affinity_sort(app_pods))
        if use_greed:
            app_pods = queues.greed_sort(cluster.nodes, app_pods)
        for p in app_pods:
            ordered.append(p)
            forced.append(bool(p.spec.node_name))

    if not ordered:
        return SimulateResult(
            node_status=[NodeStatus(node=n, pods=[]) for n in cluster.nodes]
        )

    tmpl_ids = np.array([enc.add_pod(p, _owner_selector(p)) for p in ordered], dtype=np.int32)
    ec, st0, meta = enc.build()
    ec, st0 = to_device(ec, st0)

    pod_valid = np.ones((len(ordered),), dtype=bool)
    out = schedule_pods(ec, st0, tmpl_ids, pod_valid, np.array(forced, dtype=bool))
    chosen = np.asarray(out.chosen)
    fail_counts = np.asarray(out.fail_counts)
    insufficient = np.asarray(out.insufficient)

    node_pods: Dict[str, List[Pod]] = {n.metadata.name: [] for n in cluster.nodes}
    unscheduled: List[UnscheduledPod] = []
    n_nodes = meta.n_real_nodes

    for i, pod in enumerate(ordered):
        c = int(chosen[i])
        if forced[i] and c < 0:
            unscheduled.append(UnscheduledPod(pod, f'node "{pod.spec.node_name}" not found'))
            continue
        if c >= 0:
            pod.spec.node_name = meta.node_names[c]
            pod.phase = "Running"
            node_pods[meta.node_names[c]].append(pod)
        else:
            unscheduled.append(
                UnscheduledPod(pod, _reason_string(fail_counts[i], insufficient[i], meta, n_nodes))
            )

    return SimulateResult(
        unscheduled_pods=unscheduled,
        node_status=[NodeStatus(node=n, pods=node_pods[n.metadata.name]) for n in cluster.nodes],
    )
