"""Pod-ordering queues — parity with ``pkg/algo``.

The reference sorts app pods before feeding them one at a time to the
scheduler (``pkg/simulator/simulator.go:238-241``): AffinityQueue (pods with
a nodeSelector first, ``pkg/algo/affinity.go:22``), then TolerationQueue
(pods with tolerations first, ``toleration.go:19``). GreedQueue
(``greed.go:37-67``) is flag-gated (``--use-greed``): nodeName-pinned pods
first, then descending dominant-resource share of cluster-total cpu+memory.
"""

from __future__ import annotations

from typing import List

from ..models.objects import Node, Pod


def affinity_sort(pods: List[Pod]) -> List[Pod]:
    """Stable partition: pods with a nodeSelector first."""
    with_sel = [p for p in pods if p.spec.node_selector]
    without = [p for p in pods if not p.spec.node_selector]
    return with_sel + without


def toleration_sort(pods: List[Pod]) -> List[Pod]:
    """Stable partition: pods with tolerations first."""
    with_tol = [p for p in pods if p.spec.tolerations]
    without = [p for p in pods if not p.spec.tolerations]
    return with_tol + without


def _share(alloc: float, total: float) -> float:
    if total == 0:
        return 0.0 if alloc == 0 else 1.0
    return alloc / total


def greed_sort(nodes: List[Node], pods: List[Pod]) -> List[Pod]:
    """GreedQueue: nodeName-pinned pods first, then descending dominant
    share of pod request vs cluster-total cpu+memory."""
    total_cpu = sum(n.allocatable.get("cpu", 0.0) for n in nodes)
    total_mem = sum(n.allocatable.get("memory", 0.0) for n in nodes)

    def pod_share(p: Pod) -> float:
        req = p.resource_requests()
        if not req:
            return 0.0
        return max(_share(req.get("cpu", 0.0), total_cpu), _share(req.get("memory", 0.0), total_mem))

    pinned = [p for p in pods if p.spec.node_name]
    rest = [p for p in pods if not p.spec.node_name]
    rest.sort(key=pod_share, reverse=True)
    return pinned + rest
