"""Request-axis batched simulation — the engine half of the concurrent
serving core (ISSUE 8).

The per-scenario sweep machinery (``parallel/scenarios.py``,
``fastpath.sweep``) proved the shape: S what-ifs over one ``Prepared``
differ only in boolean masks, so the whole batch is one vmapped dispatch.
This module lifts that batching from the *scenario* axis to the *request*
axis: N compatible REST simulate requests, folded onto one shared warm
prep (``prepcache.derive_with_app_slices`` appends every request's app
onto ONE fork of the cached base arenas), run as a single batched schedule
where request ``s``'s mask enables the base cluster region plus its own
app slice. Foreign pods are mask-invalid and never touch engine state, so
each demultiplexed result is bit-identical to running that request alone —
the same mask-flip argument ``drop_pods`` and the scenario sweeps rest on,
and gated end-to-end by ``tests/test_admission.py``.

Engine routing mirrors ``scenarios.sweep_auto``: the default is the
vmapped XLA scan (one compiled dispatch for the whole batch, request axis
prepended by ``jax.vmap``); ``OPENSIM_BATCH_ENGINE=native`` routes through
sequential C++ scans instead (accelerator-less hosts that want zero XLA
compiles), and ``auto`` picks native only when the vmapped scan cannot run
the stream. Either way the decode demultiplexes through the same
``simulator.finish_decode`` tail the solo path uses, restoring bind state
between requests so shared pod objects never leak one request's binds into
another's report.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

import jax
import numpy as np

from ..encoding.state import EncodedCluster, ScanState
from ..models.objects import ResourceTypes
from ..obs import trace as obs
from ..resilience.deadline import Deadline, DeadlineExceeded
from ..utils import envknobs
from .scheduler import (
    ScheduleOutput,
    _schedule_pods_jit as _schedule_pods_traced,
    pad_pod_stream,
    scan_unroll,
)
from .simulator import (
    AppResource,
    EngineDecision,
    Prepared,
    SimulateResult,
    finish_decode,
    restore_bind_state,
    snapshot_bind_state,
)

__all__ = [
    "BatchItem",
    "BatchDispatch",
    "run_request_batch",
    "dispatch_request_batch",
    "decode_request_batch",
    "batch_engine_mode",
]

# request-axis pad buckets: the batch size participates in the jit
# signature, so S is padded up to a small fixed set of shapes (padded
# scenarios are all-invalid and never bind) — the same reasoning as
# pad_pod_stream's 256-pod buckets
_S_BUCKETS = (1, 2, 4, 8, 16, 32)


@dataclass
class BatchItem:
    """One request's view of the shared batch stream."""

    cluster: ResourceTypes  # the cluster this request simulates against
    apps: List[AppResource]  # its own apps (already appended to the stream)
    lo: int  # its app slice in prep.ordered (half-open)
    hi: int
    # report-level drops: scale-removed pods + the twin's event-deleted
    # pods (CacheEntry.base_drop), as indices over the BATCH stream
    drops: set = field(default_factory=set)
    explain: bool = False
    # the rider's request deadline (NOTES.md rough edge, ISSUE 9
    # satellite): enforced BETWEEN sequential C++ rider scans, so an
    # in-flight batch sheds expired riders with the typed 504 instead of
    # running them to completion (the vmapped XLA path is one atomic
    # dispatch and keeps queue-boundary-only enforcement)
    deadline: Optional[Deadline] = None


def batch_engine_mode() -> str:
    """``OPENSIM_BATCH_ENGINE``: ``auto`` (default) = the vmapped XLA scan,
    falling back to sequential C++ scans when the stream cannot take the
    XLA path; ``xla`` / ``native`` force a rung (native still requires the
    C++ engine to be applicable)."""
    raw = envknobs.raw("OPENSIM_BATCH_ENGINE", "auto").strip().lower() or "auto"
    if raw not in ("auto", "xla", "native"):
        raise ValueError(
            f"OPENSIM_BATCH_ENGINE must be auto|xla|native, got {raw!r}"
        )
    return raw


@functools.partial(jax.jit, static_argnames=("features", "unroll", "explain"))
def _batched_schedule(ec: EncodedCluster, st0: ScanState, tmpl_ids,
                      pod_valid_masks, forced, features, unroll,
                      explain=False):
    """ALL requests in ONE compiled dispatch: ``jax.vmap`` over the
    per-request pod-validity masks prepends a request axis to the scan
    (shared EncodedCluster/ScanState operands are not duplicated). Module
    level + jitted so repeat batch shapes hit the jit cache.

    ``explain`` (batched decision audit, ISSUE 15 satellite) runs the
    count_all scan variant so every rider's per-pod fail rows are filled
    — the shared carry is untouched, so non-explain riders' placements
    are unchanged and each explain rider's rows are bit-identical to its
    solo count_all run.

    The vmapped body calls the raw jit entry, not the observed
    ``schedule_pods`` wrapper: inside this trace the compile watch's
    host-side bookkeeping (locks, clocks, signature dicts) must not run —
    OSL1601 gates that statically. THIS boundary is the one the compile
    watch instruments instead (the ``observed_jit_call`` at the dispatch
    site below)."""
    return jax.vmap(
        lambda pv: _schedule_pods_traced(
            ec, st0, tmpl_ids, pv, forced, features=features, unroll=unroll,
            explain=explain,
        )
    )(pod_valid_masks)


def _pad_batch(pod_valid: np.ndarray) -> np.ndarray:
    """Pad the request axis up to the next shape bucket with all-invalid
    rows (they schedule nothing and are sliced off after the dispatch)."""
    S = pod_valid.shape[0]
    for b in _S_BUCKETS:
        if S <= b:
            pad = b - S
            break
    else:
        pad = (-S) % _S_BUCKETS[-1]
    if pad == 0:
        return pod_valid
    return np.concatenate([pod_valid, np.zeros((pad, pod_valid.shape[1]), bool)])


def _request_masks(prep: Prepared, items: List[BatchItem]) -> np.ndarray:
    """[S, P] bool: request s sees the base region plus its own app slice,
    minus its report-level drops."""
    P = len(prep.ordered)
    n_base = min(i.lo for i in items) if items else P
    valid = np.zeros((len(items), P), dtype=bool)
    for s, it in enumerate(items):
        valid[s, :n_base] = True
        valid[s, it.lo : it.hi] = True
        for i in it.drops:
            valid[s, i] = False
    return valid


def _slice_outputs(batched: ScheduleOutput, S: int, P: int) -> List[ScheduleOutput]:
    """Every request's host-side view of the batched outputs in ONE
    device→host pass per field: the per-rider ``np.asarray`` calls this
    replaced each re-materialized the FULL batched array (N transfers of
    the whole [S, P] tensor, the hottest decode-side span in
    ``obs/profile.py``); converting once and slicing numpy views is the
    vectorized path."""
    chosen = np.asarray(batched.chosen)
    fail_counts = np.asarray(batched.fail_counts)
    insufficient = np.asarray(batched.insufficient)
    gpu_take = np.asarray(batched.gpu_take)
    static_fail = np.asarray(batched.static_fail)
    fs = batched.final_state
    leaves = [np.asarray(leaf) for leaf in fs]
    state_type = type(fs)
    return [
        ScheduleOutput(
            chosen=chosen[s, :P],
            fail_counts=fail_counts[s, :P],
            insufficient=insufficient[s, :P],
            gpu_take=gpu_take[s, :P],
            static_fail=static_fail[s],
            final_state=state_type(*[leaf[s] for leaf in leaves]),
        )
        for s in range(S)
    ]


@dataclass
class BatchDispatch:
    """The engine half's outputs, handed from the dispatch stage to the
    decode stage (server/admission.py pipeline). Everything in here is
    host-side numpy (or a typed shed) — the decode stage never touches a
    device buffer."""

    outs: List[Optional[ScheduleOutput]]
    shed: Dict[int, BaseException]
    engine_name: str
    skips: Dict[str, str]
    pod_valid: np.ndarray


def run_request_batch(
    prep: Prepared, items: List[BatchItem]
) -> List[Union[SimulateResult, BaseException]]:
    """Schedule N requests' shared stream in one batched pass and
    demultiplex one :class:`SimulateResult` per request —
    :func:`dispatch_request_batch` followed by
    :func:`decode_request_batch` (the staged pipeline calls the halves
    separately so batch k+1's host prep can overlap batch k's dispatch).

    The caller (``server/rest.py``) owns the base entry lock and the
    derived prep; this function only reads ``prep`` and restores the bind
    state it mutates. Results are bit-identical to solo runs of each
    request (mask-invalid foreign pods never touch engine state).

    Deadline shedding (ISSUE 9 satellite + ISSUE 15 satellite): on the
    sequential C++ path the rider's :class:`Deadline` is re-checked
    between scans — an expired rider's slot comes back as a typed
    :class:`DeadlineExceeded` (``phase="schedule"``) instead of a result,
    and its scan never runs. On the vmapped XLA path, riders already
    expired BEFORE the dispatch are dropped from the request mask the
    same way (their lane schedules nothing), so one slow queue wait can
    never ride a whole batch; a batch already IN FLIGHT stays atomic by
    design — the vmapped scan is one compiled dispatch with no host
    boundary to shed at (the C++ sequential path has those boundaries and
    sheds there).

    Batched explain (ISSUE 15 satellite): a rider with ``explain=True``
    rides the shared dispatch like any other — the batch runs the
    count_all scan variant (or the C++ generic path) so its per-pod fail
    rows exist, and only that rider's decode pays the audit build."""
    return decode_request_batch(prep, items, dispatch_request_batch(prep, items))


def dispatch_request_batch(prep: Prepared, items: List[BatchItem]) -> BatchDispatch:
    """The ENGINE stage: mask build + one batched schedule dispatch, no
    decode. Lock contract (the pipeline's overlap hinges on it): this
    function touches ONLY the derived prep's arrays and device buffers —
    never the shared pod objects, never the base entry's bind state — so
    the caller runs it WITHOUT the base-entry lock while the next batch's
    prep (which does hold it) overlaps. The C++/XLA engines release the
    GIL inside."""
    from . import nativepath

    P = len(prep.ordered)
    pod_valid = _request_masks(prep, items)
    mode = batch_engine_mode()
    native_miss = nativepath.why_not(prep, None, ())
    # auto routing mirrors scenarios.sweep_auto: on an accelerator-less
    # single-device host — or under --backend native (OPENSIM_NATIVE=1) —
    # the sequential C++ scans win (ms-scale per request, zero XLA
    # compiles; the batch's saving is the ONE shared derive + assemble +
    # upload); with an accelerator the whole batch is one vmapped dispatch
    use_native = mode == "native" or (
        mode == "auto"
        and native_miss is None
        and (
            envknobs.raw("OPENSIM_NATIVE") == "1"
            or (len(jax.devices()) == 1 and jax.default_backend() != "tpu")
        )
    )
    if use_native and native_miss is not None:
        if mode == "native":
            raise RuntimeError(
                f"OPENSIM_BATCH_ENGINE=native but the C++ engine cannot run "
                f"this stream: {native_miss}"
            )
        use_native = False

    skips: Dict[str, str] = {
        "megakernel": "request-axis batches run on the vmapped XLA scan "
        "(or sequential C++ scans)",
    }

    def _shed_rider(s: int, dl: Deadline) -> DeadlineExceeded:
        obs.event(
            "batch.rider_shed", status="deadline-exceeded",
            rider=s, over_by_s=round(-dl.remaining(), 6),
        )
        return DeadlineExceeded(
            "request deadline exceeded at the 'schedule' phase "
            f"(shed between batched rider scans, over by "
            f"{-dl.remaining():.3f}s)",
            phase="schedule",
        )

    outs: List[Optional[ScheduleOutput]] = []
    shed: Dict[int, BaseException] = {}
    if use_native:
        engine_name = "native"
        skips["xla"] = "OPENSIM_BATCH_ENGINE routed the batch to the C++ engine"
        with obs.span("engine.native", requests=len(items), pods=P):
            for s in range(len(items)):
                dl = items[s].deadline
                if dl is not None and dl.expired():
                    # shed BEFORE this rider's scan: its deadline died while
                    # earlier riders ran — same typed 504 a solo run's
                    # schedule boundary raises, without the wasted scan
                    shed[s] = _shed_rider(s, dl)
                    outs.append(None)
                    continue
                outs.append(
                    nativepath.schedule(prep, pod_valid[s], explain=items[s].explain)
                )
    else:
        engine_name = "xla"
        if native_miss is None:
            skips["native"] = "request-axis batching dispatches ONE vmapped scan"
        # pre-dispatch deadline shedding (ISSUE 15 satellite): an already-
        # expired rider never enters the compiled dispatch — its lane's
        # mask is all-invalid (it schedules nothing and cannot perturb the
        # others, whose masks never included its pods anyway). Once the
        # dispatch is running the batch is atomic by design: the vmapped
        # scan has no host boundary to shed at.
        for s, it in enumerate(items):
            dl = it.deadline
            if dl is not None and dl.expired():
                shed[s] = _shed_rider(s, dl)
                pod_valid[s, :] = False
        # computed AFTER shedding: a shed rider's audit has no consumer,
        # and the count_all variant is its own jit cache entry — an
        # expired explain rider must not force that compile on the batch
        explain_any = any(
            it.explain for s, it in enumerate(items) if s not in shed
        )
        tmpl_p, _pv0, forced_p = pad_pod_stream(
            prep.tmpl_ids, pod_valid[0], prep.forced
        )
        pv_all = np.zeros((pod_valid.shape[0], len(tmpl_p)), dtype=bool)
        pv_all[:, :P] = pod_valid
        pv_all = _pad_batch(pv_all)
        with obs.span("engine.xla", requests=len(items), pods=P):
            import jax.numpy as jnp

            from ..obs.profile import observed_jit_call

            # the batch dispatch is the outer jit boundary: the compile
            # watch observes it HERE, on the host, never under the trace
            batched = observed_jit_call(
                "batched_schedule",
                _batched_schedule,
                args=(
                    prep.ec, prep.st0, jnp.asarray(tmpl_p), jnp.asarray(pv_all),
                    jnp.asarray(forced_p),
                ),
                static={
                    "features": prep.features, "unroll": scan_unroll(),
                    "explain": explain_any,
                },
            )
            jax.block_until_ready(batched.chosen)
        # ONE device→host conversion per output field for the whole batch
        # (N redundant full-tensor transfers before — the vectorized path)
        outs = list(_slice_outputs(batched, len(items), P))
        for s in shed:
            outs[s] = None
    return BatchDispatch(
        outs=outs, shed=shed, engine_name=engine_name, skips=skips,
        pod_valid=pod_valid,
    )


def decode_request_batch(
    prep: Prepared, items: List[BatchItem], dispatch: BatchDispatch
) -> List[Union[SimulateResult, BaseException]]:
    """The DECODE stage: demultiplex one :class:`SimulateResult` (or typed
    shed) per rider from the dispatch outputs. Mutates shared pod objects
    (binds, GPU annotations) through ``finish_decode`` and restores bind
    state between riders and on exit — the caller MUST hold the base-entry
    lock, exactly like the serial path."""
    P = len(prep.ordered)
    outs, shed = dispatch.outs, dispatch.shed
    pod_valid = dispatch.pod_valid
    sf_rows = prep.tmpl_ids
    snap = snapshot_bind_state(prep)
    results: List[Union[SimulateResult, BaseException]] = []
    with obs.span("decode", pods=P, requests=len(items)):
        for s, it in enumerate(items):
            if s in shed:
                results.append(shed[s])
                continue
            out = outs[s]
            nstats = getattr(out, "native_stats", None)
            engine = EngineDecision(
                name=dispatch.engine_name,
                skipped=dict(dispatch.skips),
                native_path=nstats["path"] if nstats else None,
                native_steps=dict(nstats["steps"]) if nstats else None,
            )
            # the drop mask by slice assignment (vectorized): foreign
            # riders' app ranges + this rider's own report-level drops —
            # the old per-rider `set(drops) | _foreign(...)` built and
            # unioned index sets spanning most of the stream
            dropm = np.zeros(P, dtype=bool)
            for k, other in enumerate(items):
                if k != s:
                    dropm[other.lo : other.hi] = True
            if it.drops:
                for i in it.drops:
                    dropm[i] = True
            try:
                unsched, statuses = finish_decode(
                    prep, out, it.cluster,
                    np.asarray(out.chosen), np.asarray(out.gpu_take),
                    np.asarray(out.fail_counts), np.asarray(out.insufficient),
                    np.asarray(out.static_fail), sf_rows,
                    pod_valid[s], np.asarray(prep.forced, dtype=bool),
                    {}, {}, dropm,
                    None, None, None, (), engine, dispatch.engine_name,
                    it.explain,
                )
                results.append(
                    SimulateResult(
                        unscheduled_pods=unsched, node_status=statuses, engine=engine
                    )
                )
            finally:
                # shared pod objects: request s's binds must not leak into
                # request s+1's decode (or the cached entry's pristine state)
                restore_bind_state(prep, snap)
    return results


