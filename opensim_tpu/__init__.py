"""opensim-tpu: a TPU-native Kubernetes cluster simulator and capacity
planner with the capabilities of alibaba/open-simulator.

Public API:

    from opensim_tpu import AppResource, ResourceTypes, simulate
    from opensim_tpu import load_cluster_from_dir, load_yaml_objects
"""

__version__ = "0.7.0"


def __getattr__(name):
    """Lazy re-exports: importing opensim_tpu must not initialize jax."""
    if name in ("simulate", "prepare", "AppResource", "SimulateResult", "UnscheduledPod", "NodeStatus"):
        from .engine import simulator

        return getattr(simulator, name)
    if name in ("ResourceTypes", "Pod", "Node", "Workload"):
        from .models import objects

        return getattr(objects, name)
    if name in ("load_cluster_from_dir", "load_yaml_objects", "resources_from_dicts", "generate_pods_from_resources"):
        from .models import expand

        return getattr(expand, name)
    if name == "SchedulerConfig":
        from .engine.schedconfig import SchedulerConfig

        return SchedulerConfig
    if name == "plan_drains":
        from .planner.defrag import plan_drains

        return plan_drains
    raise AttributeError(f"module 'opensim_tpu' has no attribute {name!r}")
