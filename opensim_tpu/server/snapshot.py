"""Live-cluster snapshot — parity with ``CreateClusterResourceFromClient``
(``pkg/simulator/simulator.go:503-601``): list Nodes; Pods (Running +
Pending, skip DaemonSet-owned and deleting); PDBs, Services, StorageClasses,
PVCs, ConfigMaps, DaemonSets — via the Kubernetes Python client when
available, else a stdlib REST fallback speaking the list endpoints directly
(urllib + the kubeconfig's server/token), so kubeConfig mode works even
without the ``kubernetes`` package (it is absent from this base image)."""

from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import yaml

from ..models.objects import Node, Pod, PodDisruptionBudget, RawObject, ResourceTypes, Workload
from ..utils import envknobs


class SnapshotFetchError(RuntimeError):
    """A *transient* snapshot list failure (connection refused/reset, DNS,
    timeout, apiserver 5xx) — the retryable class. Config/auth problems
    (bad kubeconfig, unsupported auth, 4xx) stay plain RuntimeError: they
    will not heal by retrying and must surface immediately."""


class SnapshotUnavailable(RuntimeError):
    """The apiserver stayed down through every retry and no previous
    snapshot exists to degrade to — the REST layer maps this to a typed 503
    (retryable) instead of a raw 500."""


def snapshot_timeout_s() -> float:
    """Per-list urllib timeout in seconds, from ``OPENSIM_SNAPSHOT_TIMEOUT_S``
    (default 60 — the old hardcoded value). Validation matches
    :func:`snapshot_retry_policy`: an unparseable value raises immediately
    instead of silently restoring the default."""
    raw = envknobs.raw("OPENSIM_SNAPSHOT_TIMEOUT_S", "60")
    try:
        timeout = float(raw)
    except ValueError:
        raise ValueError("OPENSIM_SNAPSHOT_TIMEOUT_S must be a number") from None
    if timeout <= 0:
        raise ValueError("OPENSIM_SNAPSHOT_TIMEOUT_S must be positive")
    return timeout


def snapshot_retry_policy() -> tuple:
    """(attempts, base_delay_s) for the whole-snapshot fetch retry in
    ``SimonServer._refresh_snapshot`` — the ONE bounded retry layer — from
    ``OPENSIM_SNAPSHOT_RETRIES`` (default 3 attempts total) and
    ``OPENSIM_SNAPSHOT_BACKOFF_S`` (default 0.1; jittered exponential)."""
    try:
        attempts = max(1, int(envknobs.raw("OPENSIM_SNAPSHOT_RETRIES", "3")))
    except ValueError:
        raise ValueError("OPENSIM_SNAPSHOT_RETRIES must be an integer") from None
    try:
        base = float(envknobs.raw("OPENSIM_SNAPSHOT_BACKOFF_S", "0.1"))
    except ValueError:
        raise ValueError("OPENSIM_SNAPSHOT_BACKOFF_S must be a number") from None
    return attempts, base


def _pod_admissible(d: dict) -> bool:
    """The snapshot's pod filter (simulator.go:527-543): Running/Pending,
    not deleting, not DaemonSet-owned (those re-expand per node)."""
    phase = (d.get("status") or {}).get("phase", "")
    if phase not in ("Running", "Pending"):
        return False
    if (d.get("metadata") or {}).get("deletionTimestamp"):
        return False
    owners = (d.get("metadata") or {}).get("ownerReferences") or []
    return not any(o.get("kind") == "DaemonSet" for o in owners)


@dataclass(frozen=True)
class ResourceSpec:
    """One listable (and watchable) resource: the REST path, the
    ``ResourceTypes`` field it fills, the wire→model decoder, and whether a
    minimal-RBAC cluster may legitimately refuse it (403) or not serve the
    API group at all (404). The watch consumer (``server/watch.py``) and the
    polling snapshot share this table — one code path for bootstrap lists
    and per-refresh lists."""

    path: str
    field: str
    wrap: Callable[[dict], object]
    optional: bool = False


# the list calls CreateClusterResourceFromClient performs, as raw REST
# paths. pdbs/storage_classes/pvcs/services/config_maps are all optional:
# minimal-RBAC clusters 403 them (services/config_maps included — a
# read-only `nodes+pods` ServiceAccount is common) and old clusters may
# 404 whole API groups.
RESOURCES: Tuple[ResourceSpec, ...] = (
    ResourceSpec("/api/v1/nodes", "nodes", Node.from_dict),
    ResourceSpec("/api/v1/pods", "pods", Pod.from_dict),
    ResourceSpec("/apis/apps/v1/daemonsets", "daemon_sets", Workload.from_dict),
    # PDBs decode TYPED (models.PodDisruptionBudget) so live-twin campaigns
    # see real disruption budgets (ISSUE 13); still optional — minimal-RBAC
    # clusters 403 the policy group like services/config_maps
    ResourceSpec("/apis/policy/v1/poddisruptionbudgets", "pdbs", PodDisruptionBudget.from_dict, optional=True),
    ResourceSpec("/api/v1/services", "services", RawObject.from_dict, optional=True),
    ResourceSpec("/apis/storage.k8s.io/v1/storageclasses", "storage_classes", RawObject.from_dict, optional=True),
    ResourceSpec("/api/v1/persistentvolumeclaims", "pvcs", RawObject.from_dict, optional=True),
    ResourceSpec("/api/v1/configmaps", "config_maps", RawObject.from_dict, optional=True),
)

RESOURCE_BY_FIELD: Dict[str, ResourceSpec] = {spec.field: spec for spec in RESOURCES}


def _load_kubeconfig(kubeconfig: str, master: Optional[str]) -> tuple:
    """(server, headers, ssl_context) from a kubeconfig's current context.
    Supports bearer-token auth and insecure-skip-tls-verify; client-cert
    auth needs the real kubernetes client."""
    with open(kubeconfig) as f:
        doc = yaml.safe_load(f) or {}
    ctx_name = doc.get("current-context", "")
    contexts = {e.get("name"): e.get("context") or {} for e in doc.get("contexts") or []}
    clusters = {e.get("name"): e.get("cluster") or {} for e in doc.get("clusters") or []}
    users = {e.get("name"): e.get("user") or {} for e in doc.get("users") or []}
    ctx = contexts.get(ctx_name) or (next(iter(contexts.values())) if contexts else {})
    cluster = clusters.get(ctx.get("cluster")) or (next(iter(clusters.values())) if clusters else {})
    user = users.get(ctx.get("user")) or {}
    server = master or cluster.get("server", "")
    if not server:
        raise RuntimeError(f"{kubeconfig}: no cluster server in kubeconfig")
    headers = {"Accept": "application/json"}
    if user.get("token"):
        headers["Authorization"] = f"Bearer {user['token']}"
    else:
        # any non-bearer auth the fallback can't speak must fail HERE with a
        # clear error, not proceed unauthenticated into an opaque 401 —
        # including basic auth and bare client-key material (ADVICE r5 #2)
        unsupported = [
            k for k in (
                "client-certificate", "client-certificate-data", "exec",
                "auth-provider", "tokenFile", "username", "password",
                "client-key", "client-key-data",
            ) if user.get(k)
        ]
        if unsupported:
            raise RuntimeError(
                f"{kubeconfig}: auth method {unsupported[0]!r} needs the "
                "`kubernetes` Python client (the stdlib REST fallback "
                "supports bearer-token auth only)"
            )
    ssl_ctx = None
    if server.startswith("https"):
        if cluster.get("insecure-skip-tls-verify"):
            # public-API equivalent of ssl._create_unverified_context()
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ssl_ctx.check_hostname = False
            ssl_ctx.verify_mode = ssl.CERT_NONE
        elif cluster.get("certificate-authority-data"):
            import base64

            cadata = base64.b64decode(cluster["certificate-authority-data"]).decode()
            ssl_ctx = ssl.create_default_context(cadata=cadata)
        elif cluster.get("certificate-authority"):
            ssl_ctx = ssl.create_default_context(cafile=cluster["certificate-authority"])
    return server.rstrip("/"), headers, ssl_ctx


def list_resource(
    server: str,
    headers: dict,
    ssl_ctx: Optional[ssl.SSLContext],
    spec: ResourceSpec,
) -> Optional[Tuple[List[dict], str]]:
    """GET one list endpoint; returns ``(raw items, list resourceVersion)``
    or None for a tolerated missing optional endpoint (403/404). EVERY list
    passes ``resourceVersion=0`` (serve-from-cache semantics — the
    apiserver answers from its watch cache instead of quorum-reading etcd,
    exactly what the reference's informers request), and the returned
    list-level resourceVersion is captured so a watch can resume from it —
    the polling snapshot and the watch bootstrap are this one code path.

    Single attempt, TYPED: transient failures become SnapshotFetchError so
    the one bounded retry layer (the caller's retry_call) can retry them.
    Retrying here too would multiply the attempt budget per endpoint."""
    from ..obs import trace as obs

    sep = "&" if "?" in spec.path else "?"
    req = urllib.request.Request(
        f"{server}{spec.path}{sep}resourceVersion=0", headers=headers
    )
    try:
        with obs.span("snapshot.list", path=spec.path):
            with urllib.request.urlopen(
                req, timeout=snapshot_timeout_s(), context=ssl_ctx
            ) as resp:
                body = json.load(resp)
    except urllib.error.HTTPError as e:
        if spec.optional and e.code in (403, 404):
            return None
        if e.code >= 500:  # apiserver-side transient: retryable
            raise SnapshotFetchError(f"list {spec.path} failed: HTTP {e.code}") from e
        raise RuntimeError(f"list {spec.path} failed: HTTP {e.code}") from e
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise SnapshotFetchError(f"list {spec.path} failed: {e}") from e
    items: List[dict] = body.get("items") or []
    rv = str((body.get("metadata") or {}).get("resourceVersion", ""))
    return items, rv


def _cluster_via_rest(
    kubeconfig: str, master: Optional[str]
) -> Tuple[ResourceTypes, Dict[str, str]]:
    """Stdlib fallback: GET the list endpoints directly. Endpoint JSON is
    already the wire form ``from_dict`` consumes (no client sanitization
    needed). A missing optional endpoint (403/404 in a minimal-RBAC
    cluster) yields an empty list rather than failing the snapshot.
    Returns the cluster plus each list's resourceVersion keyed by field —
    the watch bootstrap resumes streams from exactly these."""
    server, headers, ssl_ctx = _load_kubeconfig(kubeconfig, master)
    rt = ResourceTypes()
    rvs: Dict[str, str] = {}
    for spec in RESOURCES:
        got = list_resource(server, headers, ssl_ctx, spec)
        if got is None:
            continue
        items, rvs[spec.field] = got
        dest = getattr(rt, spec.field)
        for d in items:
            if spec.field == "pods" and not _pod_admissible(d):
                continue
            dest.append(spec.wrap(d))
    return rt, rvs


def cluster_from_kubeconfig(kubeconfig: str, master: Optional[str] = None) -> ResourceTypes:
    try:
        from kubernetes import client, config  # type: ignore
    except ImportError:
        return _cluster_via_rest(kubeconfig, master)[0]

    config.load_kube_config(config_file=kubeconfig)
    core = client.CoreV1Api()
    apps = client.AppsV1Api()
    # policy/v1beta1 was removed in k8s 1.25 / kubernetes client v26
    policy = client.PolicyV1Api() if hasattr(client, "PolicyV1Api") else client.PolicyV1beta1Api()
    storage = client.StorageV1Api()
    api = client.ApiClient()

    def to_dict(obj) -> dict:
        return api.sanitize_for_serialization(obj)

    # resourceVersion=0 on EVERY list (not just pods): serve-from-cache
    # semantics, consistent with the REST path so watch bootstrap and
    # polling share one list contract
    rt = ResourceTypes()
    for n in core.list_node(resource_version="0").items:
        rt.nodes.append(Node.from_dict(to_dict(n)))
    for p in core.list_pod_for_all_namespaces(resource_version="0").items:
        d = to_dict(p)
        if not _pod_admissible(d):
            continue
        rt.pods.append(Pod.from_dict(d))
    for ds in apps.list_daemon_set_for_all_namespaces(resource_version="0").items:
        rt.daemon_sets.append(Workload.from_dict(to_dict(ds)))
    for pdb in policy.list_pod_disruption_budget_for_all_namespaces(resource_version="0").items:
        rt.pdbs.append(PodDisruptionBudget.from_dict(to_dict(pdb)))
    for svc in core.list_service_for_all_namespaces(resource_version="0").items:
        rt.services.append(RawObject.from_dict(to_dict(svc)))
    for sc in storage.list_storage_class(resource_version="0").items:
        rt.storage_classes.append(RawObject.from_dict(to_dict(sc)))
    for pvc in core.list_persistent_volume_claim_for_all_namespaces(resource_version="0").items:
        rt.pvcs.append(RawObject.from_dict(to_dict(pvc)))
    for cm in core.list_config_map_for_all_namespaces(resource_version="0").items:
        rt.config_maps.append(RawObject.from_dict(to_dict(cm)))
    return rt
