"""Live-cluster snapshot — parity with ``CreateClusterResourceFromClient``
(``pkg/simulator/simulator.go:503-601``): list Nodes; Pods (Running +
Pending, skip DaemonSet-owned and deleting); PDBs, Services, StorageClasses,
PVCs, ConfigMaps, DaemonSets — via the Kubernetes Python client when
available (gated: the client is not in the base image)."""

from __future__ import annotations

from typing import Optional

from ..models.objects import Node, Pod, RawObject, ResourceTypes, Workload


def cluster_from_kubeconfig(kubeconfig: str, master: Optional[str] = None) -> ResourceTypes:
    try:
        from kubernetes import client, config  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "live-cluster mode needs the `kubernetes` Python client, which is "
            "not installed in this environment; use spec.cluster.customConfig "
            "with a YAML directory instead"
        ) from e

    config.load_kube_config(config_file=kubeconfig)
    core = client.CoreV1Api()
    apps = client.AppsV1Api()
    # policy/v1beta1 was removed in k8s 1.25 / kubernetes client v26
    policy = client.PolicyV1Api() if hasattr(client, "PolicyV1Api") else client.PolicyV1beta1Api()
    storage = client.StorageV1Api()
    api = client.ApiClient()

    def to_dict(obj) -> dict:
        return api.sanitize_for_serialization(obj)

    rt = ResourceTypes()
    for n in core.list_node().items:
        rt.nodes.append(Node.from_dict(to_dict(n)))
    for p in core.list_pod_for_all_namespaces(resource_version="0").items:
        d = to_dict(p)
        phase = (d.get("status") or {}).get("phase", "")
        if phase not in ("Running", "Pending"):
            continue
        if (d.get("metadata") or {}).get("deletionTimestamp"):
            continue
        owners = (d.get("metadata") or {}).get("ownerReferences") or []
        if any(o.get("kind") == "DaemonSet" for o in owners):
            continue
        rt.pods.append(Pod.from_dict(d))
    for ds in apps.list_daemon_set_for_all_namespaces().items:
        rt.daemon_sets.append(Workload.from_dict(to_dict(ds)))
    for pdb in policy.list_pod_disruption_budget_for_all_namespaces().items:
        rt.pdbs.append(RawObject.from_dict(to_dict(pdb)))
    for svc in core.list_service_for_all_namespaces().items:
        rt.services.append(RawObject.from_dict(to_dict(svc)))
    for sc in storage.list_storage_class().items:
        rt.storage_classes.append(RawObject.from_dict(to_dict(sc)))
    for pvc in core.list_persistent_volume_claim_for_all_namespaces().items:
        rt.pvcs.append(RawObject.from_dict(to_dict(pvc)))
    for cm in core.list_config_map_for_all_namespaces().items:
        rt.config_maps.append(RawObject.from_dict(to_dict(cm)))
    return rt
