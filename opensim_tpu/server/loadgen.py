"""``simon loadgen`` — open/closed-loop load harness for the live server
(ISSUE 8).

The success metric of the concurrent serving core is a CLOSED LOOP, not a
microbench: drive the live server at a target concurrency (closed loop:
each worker waits for its response before issuing the next request) or a
target arrival rate (open loop: requests fire on a fixed schedule whether
or not earlier ones returned), and read the latency distribution straight
from the server's own ``simon_request_seconds_bucket`` histogram — the
same series a production dashboard scrapes — rather than trusting
client-side clocks alone. Both views are reported; disagreement between
them is itself a finding (client-side queueing).

Shed handling mirrors a well-behaved client: a 503 with ``Retry-After``
backs off for the advertised interval (capped), and sheds are reported
separately from errors — shedding under overload is the server WORKING,
and the report says how much traffic it cost.

Library surface: :func:`run_loadgen` returns the report dict (the smoke
gate ``tools/loadgen_smoke.py`` and ``bench.py --config serving`` build on
it); the CLI in ``cli/main.py`` prints it as JSON.
"""

from __future__ import annotations

import json
import logging
import math
import re
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("opensim_tpu.loadgen")

__all__ = [
    "run_loadgen",
    "run_stub_benchmark",
    "run_fleet_benchmark",
    "run_pipeline_benchmark",
    "placement_parity",
    "parse_metrics",
    "histogram_quantile",
    "scrape_metrics",
]

# ---------------------------------------------------------------------------
# Prometheus text-format reading: the parse/merge/quantile machinery moved
# to obs/metrics.py (ISSUE 20 satellite — the fleet aggregator and the
# time-series ring need the same bucket-merge code); re-exported here so
# every published name (`from ..server.loadgen import parse_metrics`, the
# smoke tools, bench.py) keeps working.
# ---------------------------------------------------------------------------

from ..obs.metrics import (  # noqa: E402  (re-export, see __all__)
    MetricKey,
    histogram_quantile,
    parse_metrics,
    scrape_metrics,
)
from ..obs.metrics import bucket_deltas as _bucket_deltas  # noqa: E402,F401
from ..obs.metrics import counter_delta as _counter_delta  # noqa: E402


# ---------------------------------------------------------------------------
# the loop
# ---------------------------------------------------------------------------


def _payload(worker: int, seq: int, replicas: int, cpu: str, mem: str) -> bytes:
    """Distinct-per-request deploy payload: identical repeated payloads
    would measure the full-key prep cache, not the serving core."""
    name = f"lg-{worker}-{seq}"
    reps = 1 + (seq % max(1, replicas))
    return json.dumps(
        {
            "deployments": [
                {
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "metadata": {"name": name, "namespace": "default"},
                    "spec": {
                        "replicas": reps,
                        "selector": {"matchLabels": {"app": name}},
                        "template": {
                            "metadata": {"labels": {"app": name}},
                            "spec": {
                                "containers": [
                                    {
                                        "name": "c",
                                        "resources": {
                                            "requests": {"cpu": cpu, "memory": mem}
                                        },
                                    }
                                ]
                            },
                        },
                    },
                }
            ]
        }
    ).encode()


class _Stats:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.ok = 0
        self.shed = 0
        self.errors = 0
        self.latencies: List[float] = []

    def record(self, outcome: str, seconds: float) -> None:
        with self.lock:
            if outcome == "ok":
                self.ok += 1
                self.latencies.append(seconds)
            elif outcome == "shed":
                self.shed += 1
            else:
                self.errors += 1


class _Client:
    """One worker's persistent HTTP/1.1 connection (keep-alive): connection
    churn must not pollute the latency measurement — the server speaks
    HTTP/1.1 with Content-Length on every response."""

    def __init__(self, url: str, timeout_s: float) -> None:
        import urllib.parse

        parsed = urllib.parse.urlparse(url)
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or (443 if parsed.scheme == "https" else 80)
        self.timeout_s = timeout_s
        self.conn: Optional[object] = None

    def _connect(self):
        import http.client

        self.conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout_s
        )
        return self.conn

    def request(self, body: bytes) -> Tuple[str, float, float]:
        """POST one deploy; returns (outcome, latency_s, retry_after_s)."""
        t0 = time.monotonic()
        conn = self.conn or self._connect()
        try:
            conn.request(
                "POST", "/api/deploy-apps", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            resp.read()
            lat = time.monotonic() - t0
            if resp.status == 503:
                try:
                    retry = float(resp.headers.get("Retry-After") or 1.0)
                except ValueError:
                    retry = 1.0
                return "shed", lat, retry
            if resp.status != 200:
                return "error", lat, 0.0
            return "ok", lat, 0.0
        except Exception as e:
            # drop the (possibly wedged) connection; the next request dials
            # fresh — a connection reset is an ERROR SAMPLE in the report,
            # never a crash of the harness
            log.debug("request failed: %s: %s", type(e).__name__, e)
            try:
                conn.close()
            except OSError as ce:
                log.debug("connection close failed: %s", ce)
            self.conn = None
            return "error", time.monotonic() - t0, 0.0

    def close(self) -> None:
        if self.conn is not None:
            try:
                self.conn.close()
            except OSError as ce:
                log.debug("connection close failed: %s", ce)
            self.conn = None


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, int(math.ceil(q * len(sorted_vals))) - 1))
    return sorted_vals[idx]


def run_loadgen(
    url: str,
    mode: str = "closed",
    concurrency: int = 8,
    qps: float = 0.0,
    duration_s: float = 10.0,
    replicas: int = 3,
    cpu: str = "500m",
    mem: str = "1Gi",
    timeout_s: float = 60.0,
    warmup_requests: int = 1,
    metrics_url: str = "",
) -> dict:
    """Drive the server and report sustained QPS + latency percentiles.

    - ``closed``: ``concurrency`` workers, each issuing its next request
      only after the previous response (or after the advertised
      ``Retry-After`` on a shed) — throughput self-adjusts to the server's
      capacity, the honest "sustained QPS at bounded p99" measurement.
    - ``open``: requests fire every ``1/qps`` seconds regardless of
      completions (up to ``concurrency`` in flight; arrivals past that are
      counted ``dropped`` — client-side overload, reported, never silently
      skipped).

    ``metrics_url`` overrides where the server-side histograms are
    scraped: against a multi-worker fleet the public port lands on ONE
    worker per connection, so the scrape must hit the fleet admin
    endpoint (aggregated across workers) instead.
    """
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be closed|open, got {mode!r}")
    if mode == "open" and qps <= 0:
        raise ValueError("open loop needs --qps > 0")
    metrics_url = metrics_url or url

    # warmup outside the measured window: the first request pays the cold
    # prepare + engine compile and would dominate a short run
    wcli = _Client(url, timeout_s)
    for i in range(max(0, warmup_requests)):
        wcli.request(_payload(999, i, replicas, cpu, mem))
    wcli.close()

    before = scrape_metrics(metrics_url)
    stats = _Stats()
    stop = time.monotonic() + duration_s
    dropped = [0]

    def closed_worker(w: int) -> None:
        cli = _Client(url, timeout_s)
        seq = 0
        try:
            while time.monotonic() < stop:
                outcome, lat, retry = cli.request(
                    _payload(w, seq, replicas, cpu, mem)
                )
                stats.record(outcome, lat)
                seq += 1
                if outcome == "shed":
                    time.sleep(min(retry, max(0.0, stop - time.monotonic()), 2.0))
        finally:
            cli.close()

    def open_driver() -> None:
        interval = 1.0 / qps
        inflight = threading.Semaphore(concurrency)
        seq = 0
        next_at = time.monotonic()

        def fire(s: int) -> None:
            cli = _Client(url, timeout_s)
            try:
                outcome, lat, _ = cli.request(_payload(0, s, replicas, cpu, mem))
            finally:
                cli.close()
            stats.record(outcome, lat)
            inflight.release()

        while time.monotonic() < stop:
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(interval, next_at - now))
                continue
            next_at += interval
            if not inflight.acquire(blocking=False):
                dropped[0] += 1
                continue
            threading.Thread(target=fire, args=(seq,), daemon=True).start()
            seq += 1

    t_start = time.monotonic()
    if mode == "closed":
        workers = [
            threading.Thread(target=closed_worker, args=(w,), daemon=True)
            for w in range(concurrency)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
    else:
        open_driver()
        # drain stragglers briefly so the final scrape sees them
        time.sleep(min(2.0, timeout_s))
    measured_s = time.monotonic() - t_start
    after = scrape_metrics(metrics_url)

    lats = sorted(stats.latencies)
    ok_match = {"endpoint": "deploy-apps", "status": "ok"}
    batches = _counter_delta(before, after, "simon_batches_total")
    batched_reqs = _counter_delta(before, after, "simon_batch_size_sum")
    shed_by_reason = {}
    for (name, labels), v in after.items():
        if name == "simon_shed_total":
            reason = dict(labels).get("reason", "")
            shed_by_reason[reason] = int(v - before.get((name, labels), 0.0))
    report = {
        "mode": mode,
        "duration_s": round(measured_s, 3),
        "concurrency": concurrency,
        "target_qps": qps if mode == "open" else None,
        "requests": stats.ok + stats.shed + stats.errors,
        "ok": stats.ok,
        "shed": stats.shed,
        "errors": stats.errors,
        "dropped": dropped[0],
        "qps": round(stats.ok / measured_s, 2) if measured_s > 0 else 0.0,
        "client_p50_s": _quantile(lats, 0.50),
        "client_p99_s": _quantile(lats, 0.99),
        # straight from the server's own exposition (the closed loop's
        # other half): simon_request_seconds_bucket over the run's delta
        "server_p50_s": histogram_quantile(
            before, after, "simon_request_seconds", 0.50, ok_match
        ),
        "server_p99_s": histogram_quantile(
            before, after, "simon_request_seconds", 0.99, ok_match
        ),
        "queue_wait_p99_s": histogram_quantile(
            before, after, "simon_queue_wait_seconds", 0.99
        ),
        "batches": int(batches),
        "batched_requests": int(batched_reqs),
        "mean_batch_size": round(batched_reqs / batches, 2) if batches else 0.0,
        "shed_total": shed_by_reason,
    }
    return report


# ---------------------------------------------------------------------------
# the closed loop against the stub apiserver (the ISSUE 8 success metric)
# ---------------------------------------------------------------------------


def _seed_stub(n_nodes: int, n_pods: int):
    """Stub apiserver seeded with a small live cluster (nodes + running
    pods) so the twin's warm base prep is non-trivial — the shape the
    request-axis batcher serves."""
    from ..models import fixtures as fx
    from .stubapi import StubApiServer

    stub = StubApiServer(bookmark_interval_s=0.2).start()
    stub.seed(
        "/api/v1/nodes",
        [fx.make_fake_node(f"n{i}", "16", "32Gi").raw for i in range(n_nodes)],
    )
    stub.seed(
        "/api/v1/pods",
        [
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {"name": f"seed-{i}", "namespace": "default"},
                "spec": {
                    "nodeName": f"n{i % n_nodes}",
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": "250m"}}}
                    ],
                },
                "status": {"phase": "Running"},
            }
            for i in range(n_pods)
        ],
    )
    for path in (
        "/apis/apps/v1/daemonsets", "/apis/policy/v1/poddisruptionbudgets",
        "/api/v1/services", "/apis/storage.k8s.io/v1/storageclasses",
        "/api/v1/persistentvolumeclaims", "/api/v1/configmaps",
    ):
        stub.seed(path, [])
    return stub


def _boot_server(kubeconfig: str, port: int, admission: bool, batch_max: int,
                 workers: int = 0, queue_bound: int = 0,
                 pipeline: "Optional[bool]" = None):
    """The simon server as a SUBPROCESS: the loadgen client and the server
    must not share a GIL, or the measurement reports the client's
    contention as server latency. ``workers`` ≥ 2 boots the multi-process
    fleet (``--workers N``); readiness then waits on the fleet admin
    ``/healthz`` reporting every worker alive (the public port is served
    by the workers via SO_REUSEPORT)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(
        os.environ,
        JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
        OPENSIM_ADMISSION="on" if admission else "off",
        OPENSIM_BATCH_MAX=str(batch_max),
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    if queue_bound:
        env["OPENSIM_QUEUE_BOUND"] = str(queue_bound)
    if pipeline is not None:
        env["OPENSIM_PIPELINE"] = "on" if pipeline else "off"
    cmd = [sys.executable, "-m", "opensim_tpu", "server",
           "--kubeconfig", kubeconfig, "--port", str(port), "--watch", "auto"]
    if workers >= 2:
        cmd += ["--workers", str(workers)]
    # Spool server output to a file, never a pipe: nobody drains the pipe
    # during the run, and at storm concurrency the 64 KiB buffer fills with
    # handler tracebacks (clients dropping mid-response), after which every
    # server thread that logs blocks in write() and the drain wedges.
    logf = open(os.path.join(os.path.dirname(kubeconfig) or ".",
                             f"server-{port}.log"), "w+b")
    proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=subprocess.STDOUT)
    proc._simon_logf = logf  # closed by _stop_server
    url = f"http://127.0.0.1:{port}"
    ready_url = f"http://127.0.0.1:{port + 1}/healthz" if workers >= 2 else f"{url}/healthz"
    deadline = time.monotonic() + (240.0 if workers >= 2 else 120.0)
    attempt = 0
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            logf.flush()
            logf.seek(0)
            out = (logf.read() or b"").decode(errors="replace")
            logf.close()
            raise RuntimeError(f"server exited at boot (rc={proc.returncode}): {out[-2000:]}")
        try:
            with urllib.request.urlopen(ready_url, timeout=1.0) as resp:
                if workers >= 2:
                    body = json.loads(resp.read().decode())
                    if body.get("status") != "ok" or body.get("generation", -1) < 0:
                        raise OSError("fleet not ready")
                    # the admin endpoint is up and every worker process is
                    # alive; confirm the shared public port answers too
                    with urllib.request.urlopen(f"{url}/healthz", timeout=1.0):
                        pass
                return proc, url
        except (OSError, ValueError) as e:
            log.debug("healthz probe %d: %s", attempt, e)
            attempt += 1
            time.sleep(min(0.5, 0.05 * attempt))
    proc.kill()
    proc.wait()
    logf.close()
    raise RuntimeError("server did not become healthy within the boot window")


def _stop_server(proc) -> None:
    """SIGTERM, bounded drain, SIGKILL fallback. The graceful drain is the
    normal path; the kill is insurance so one wedged server cannot hang an
    entire bench run in ``proc.wait()``."""
    import subprocess

    proc.terminate()
    try:
        proc.wait(timeout=60.0)
    except subprocess.TimeoutExpired:
        log.warning("server pid %d did not drain within 60s of SIGTERM; killing",
                    proc.pid)
        proc.kill()
        proc.wait()
    logf = getattr(proc, "_simon_logf", None)
    if logf is not None:
        logf.close()


def _warm_concurrent(url: str, n: int, timeout_s: float) -> None:
    """Concurrent warmup burst: a serial warmup never exercises the BATCH
    path, whose first run pays its own caches."""
    def one(i: int) -> None:
        cli = _Client(url, timeout_s)
        try:
            cli.request(_payload(888, i, 3, "500m", "1Gi"))
        finally:
            cli.close()

    threads = [threading.Thread(target=one, args=(i,), daemon=True) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def run_stub_benchmark(
    concurrency: int = 32,
    duration_s: float = 8.0,
    n_nodes: int = 8,
    n_pods: int = 16,
    batch_max: int = 32,
    base_port: int = 18180,
    client_procs: int = 0,
) -> dict:
    """The ISSUE 8 closed loop, end to end: stub apiserver → two live twin
    servers in subprocesses (single-flight vs admission queue) → closed-
    loop loadgen against each → one report carrying BOTH numbers. Used by
    ``make loadgen-smoke`` and ``bench.py --config serving``.
    ``client_procs`` ≥ 2 shards the clients over loadgen subprocesses
    (mandatory fidelity at hundreds of clients)."""
    import tempfile

    stub = _seed_stub(n_nodes, n_pods)
    tmp = tempfile.mkdtemp(prefix="loadgen-")
    kc = stub.kubeconfig(tmp)

    def drive(url: str) -> dict:
        if client_procs >= 2:
            return run_loadgen_sharded(url, concurrency, duration_s, client_procs)
        return run_loadgen(
            url, mode="closed", concurrency=concurrency, duration_s=duration_s
        )

    try:
        proc, url = _boot_server(kc, base_port, admission=False, batch_max=batch_max)
        try:
            _warm_concurrent(url, min(16, concurrency), 60.0)
            single = drive(url)
        finally:
            _stop_server(proc)
        proc, url = _boot_server(kc, base_port + 1, admission=True, batch_max=batch_max)
        try:
            _warm_concurrent(url, min(16, concurrency), 60.0)
            batched = drive(url)
        finally:
            _stop_server(proc)
    finally:
        stub.stop()
    speedup = (
        batched["qps"] / single["qps"] if single["qps"] > 0 else float("inf")
    )
    return {
        "concurrency": concurrency,
        "duration_s": duration_s,
        "nodes": n_nodes,
        "cluster_pods": n_pods,
        "qps_single_flight": single["qps"],
        "qps": batched["qps"],
        "speedup": round(speedup, 2),
        "p50_s": batched["server_p50_s"],
        "p99_s": batched["server_p99_s"],
        "p99_single_flight_s": single["server_p99_s"],
        "batches": batched["batches"],
        "mean_batch_size": batched["mean_batch_size"],
        "shed": batched["shed"],
        "shed_single_flight": single["shed"],
        "single_flight": single,
        "admission": batched,
    }


def run_pipeline_benchmark(
    concurrency: int = 32,
    duration_s: float = 8.0,
    n_nodes: int = 8,
    n_pods: int = 16,
    batch_max: int = 32,
    base_port: int = 18380,
    client_procs: int = 0,
    queue_bound: int = 0,
) -> dict:
    """The ISSUE 16 closed loop: the SAME admission server booted twice —
    ``OPENSIM_PIPELINE=off`` (serial inline batches) vs ``on`` (staged
    prep/dispatch/decode) — driven by the same closed-loop loadgen, plus
    the end-to-end placement-parity gate between the two modes and the
    measured prep-under-dispatch overlap scraped from the pipelined
    server's own counters. ``client_procs`` ≥ 2 shards the clients over
    loadgen subprocesses (mandatory fidelity at hundreds of clients)."""
    import os
    import tempfile

    stub = _seed_stub(n_nodes, n_pods)
    tmp = tempfile.mkdtemp(prefix="loadgen-pipe-")
    kc = stub.kubeconfig(tmp)
    qb = queue_bound or max(64, 2 * concurrency)

    def drive(url: str) -> dict:
        if client_procs >= 2:
            return run_loadgen_sharded(url, concurrency, duration_s, client_procs)
        return run_loadgen(
            url, mode="closed", concurrency=concurrency, duration_s=duration_s
        )

    try:
        proc, url = _boot_server(
            kc, base_port, admission=True, batch_max=batch_max,
            queue_bound=qb, pipeline=False,
        )
        try:
            _warm_concurrent(url, min(16, concurrency), 60.0)
            serial = drive(url)
        finally:
            _stop_server(proc)
        pproc, purl = _boot_server(
            kc, base_port + 2, admission=True, batch_max=batch_max,
            queue_bound=qb, pipeline=True,
        )
        try:
            _warm_concurrent(purl, min(16, concurrency), 60.0)
            before = scrape_metrics(purl)
            piped = drive(purl)
            after = scrape_metrics(purl)
            # parity gate between the two modes, against the same stub
            # cluster: a fresh non-pipelined server answers the same
            # probes the pipelined one does
            sproc, surl = _boot_server(
                kc, base_port + 40, admission=True, batch_max=batch_max,
                pipeline=False,
            )
            try:
                parity = placement_parity(surl, purl)
            finally:
                _stop_server(sproc)
        finally:
            _stop_server(pproc)
    finally:
        stub.stop()
    overlap_s = _counter_delta(
        before, after, "simon_pipeline_prep_overlap_seconds_total"
    )
    overlapped = _counter_delta(
        before, after, "simon_pipeline_overlapped_batches_total"
    )
    batches = _counter_delta(before, after, "simon_batches_total")
    speedup = piped["qps"] / serial["qps"] if serial["qps"] > 0 else float("inf")
    return {
        "concurrency": concurrency,
        "duration_s": duration_s,
        "nodes": n_nodes,
        "cluster_pods": n_pods,
        "client_procs": client_procs,
        "host_cores": os.cpu_count() or 1,
        "qps_non_pipelined": serial["qps"],
        "qps": piped["qps"],
        "vs_non_pipelined": round(speedup, 2),
        "p50_s": piped["server_p50_s"],
        "p99_s": piped["server_p99_s"],
        "p50_non_pipelined_s": serial["server_p50_s"],
        "p99_non_pipelined_s": serial["server_p99_s"],
        "batches": int(batches),
        "mean_batch_size": piped["mean_batch_size"],
        "overlapped_batches": int(overlapped),
        "prep_overlap_s": round(overlap_s, 4),
        "shed": piped["shed"],
        "errors": piped["errors"],
        "placements_identical": parity,
        "non_pipelined": serial,
        "pipelined": piped,
    }


def run_loadgen_sharded(
    url: str,
    concurrency: int,
    duration_s: float,
    procs: int,
    metrics_url: str = "",
    timeout_s: float = 60.0,
) -> dict:
    """The closed loop sharded over ``procs`` CLIENT PROCESSES: at
    hundreds of concurrent clients a single loadgen process's GIL throttles
    the offered load and bills client-side scheduling to the server.
    Each shard is one ``simon loadgen`` subprocess driving
    ``concurrency/procs`` workers; client-side QPS/shed/error counts sum
    across shards, and the server-side percentiles come from ONE
    before/after scrape of ``metrics_url`` around the whole run (the only
    view that covers every shard's traffic)."""
    import os
    import subprocess
    import sys

    metrics_url = metrics_url or url
    shares = [concurrency // procs] * procs
    for i in range(concurrency % procs):
        shares[i] += 1
    before = scrape_metrics(metrics_url)
    t_start = time.monotonic()
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    children = [
        subprocess.Popen(
            [
                sys.executable, "-m", "opensim_tpu", "loadgen", "--url", url,
                "--mode", "closed", "--concurrency", str(share),
                "--duration", str(duration_s), "--timeout", str(timeout_s),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        )
        for share in shares
        if share > 0
    ]
    reports = []
    try:
        for c in children:
            out, err = c.communicate(timeout=duration_s + 300.0)
            lines = [ln for ln in out.decode().strip().splitlines() if ln.strip()]
            if c.returncode != 0 or not lines:
                raise RuntimeError(
                    f"loadgen shard failed rc={c.returncode}: "
                    f"{(err or out)[-500:].decode(errors='replace')!r}"
                )
            reports.append(json.loads(lines[-1]))
    finally:
        # one failed/hung shard must not leave its siblings hammering the
        # server as orphans (they would skew every later measurement)
        for c in children:
            if c.poll() is None:
                c.kill()
                c.wait()
    measured_s = time.monotonic() - t_start
    after = scrape_metrics(metrics_url)
    ok_match = {"endpoint": "deploy-apps", "status": "ok"}
    batches = _counter_delta(before, after, "simon_batches_total")
    batched_reqs = _counter_delta(before, after, "simon_batch_size_sum")
    return {
        "mode": "closed-sharded",
        "client_procs": len(children),
        "duration_s": round(measured_s, 3),
        "concurrency": concurrency,
        "requests": sum(r["requests"] for r in reports),
        "ok": sum(r["ok"] for r in reports),
        "shed": sum(r["shed"] for r in reports),
        "errors": sum(r["errors"] for r in reports),
        "qps": round(sum(r["qps"] for r in reports), 2),
        "client_p99_s": max(
            (r["client_p99_s"] for r in reports if r["client_p99_s"] is not None),
            default=None,
        ),
        "server_p50_s": histogram_quantile(
            before, after, "simon_request_seconds", 0.50, ok_match
        ),
        "server_p99_s": histogram_quantile(
            before, after, "simon_request_seconds", 0.99, ok_match
        ),
        "queue_wait_p99_s": histogram_quantile(
            before, after, "simon_queue_wait_seconds", 0.99
        ),
        "batches": int(batches),
        "batched_requests": int(batched_reqs),
        "mean_batch_size": round(batched_reqs / batches, 2) if batches else 0.0,
        "shards": reports,
    }


# ---------------------------------------------------------------------------
# the fleet closed loop (ISSUE 15): N worker processes vs one process
# ---------------------------------------------------------------------------


def _canon_response(body: dict) -> tuple:
    """Placement identity view of a deploy response: expanded pod names
    carry per-process random suffixes (NOTES invariant), so pods are
    canonicalized onto their owning workload (the name minus the final
    suffix segment) and compared as (node, workload, count) triples plus
    the unscheduled (workload, reason) set."""
    def canon(ref: str) -> str:
        # strip every trailing generated segment (10-hex expansion
        # counters): a Deployment pod carries TWO — the ReplicaSet's and
        # its own — and the counters are process-global, so they differ
        # across servers by design
        ns, _, name = ref.partition("/")
        parts = name.split("-")
        while len(parts) > 1 and re.fullmatch(r"[0-9a-f]{10}", parts[-1]):
            parts.pop()
        return f"{ns}/{'-'.join(parts)}"

    placed = sorted(
        (e["node"], sorted(canon(p) for p in e["pods"]))
        for e in body.get("nodeStatus", [])
    )
    unsched = sorted(
        (canon(u["pod"]), u["reason"]) for u in body.get("unscheduledPods", [])
    )
    return placed, unsched


def _post_deploy(url: str, payload: bytes, timeout_s: float = 60.0) -> dict:
    req = urllib.request.Request(
        f"{url}/api/deploy-apps", data=payload,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def placement_parity(url_a: str, url_b: str, n_probes: int = 4) -> bool:
    """The fleet bit-identity gate, end to end over HTTP: the same deploy
    payloads against both servers must place onto the same nodes with the
    same per-workload counts and the same unschedulable reasons."""
    for i in range(n_probes):
        payload = _payload(777, i, 3, "500m", "1Gi")
        a = _canon_response(_post_deploy(url_a, payload))
        b = _canon_response(_post_deploy(url_b, payload))
        if a != b:
            log.warning("placement parity failed on probe %d: %r != %r", i, a, b)
            return False
    return True


def run_fleet_benchmark(
    workers: int = 2,
    concurrency: int = 64,
    duration_s: float = 8.0,
    n_nodes: int = 8,
    n_pods: int = 16,
    batch_max: int = 32,
    base_port: int = 18280,
    queue_bound: int = 0,
    client_procs: int = 0,
) -> dict:
    """The ISSUE 15 closed loop: stub apiserver → ONE single-process
    admission server and ONE ``--workers N`` fleet (twin owner + shm
    publication + SO_REUSEPORT workers), the same closed-loop loadgen
    against each, plus the end-to-end placement-parity gate between them.
    The fleet's server-side histograms come from the aggregated admin
    endpoint (scraping the public port would sample one worker).
    ``client_procs`` ≥ 2 shards the clients over that many loadgen
    subprocesses (``run_loadgen_sharded``) — mandatory fidelity at
    hundreds of concurrent clients, where one client process's GIL would
    throttle the offered load for both measurements equally but far below
    what the servers can actually sustain."""
    import tempfile

    stub = _seed_stub(n_nodes, n_pods)
    tmp = tempfile.mkdtemp(prefix="loadgen-fleet-")
    kc = stub.kubeconfig(tmp)
    qb = queue_bound or max(64, 2 * concurrency)

    def drive(url: str, metrics_url: str = "") -> dict:
        # a measured-length warm burst at full concurrency: the batcher's
        # big pad buckets compile lazily PER PROCESS, so without this a
        # worker pays multi-second XLA compiles inside the measured window
        # (randomly, per bucket) and the run-to-run variance swamps the
        # comparison. Applied to both servers — strictly fair.
        run_loadgen(
            url, mode="closed", concurrency=min(concurrency, 96),
            duration_s=max(3.0, duration_s / 3.0), warmup_requests=0,
            metrics_url=metrics_url,
        )
        if client_procs >= 2:
            return run_loadgen_sharded(
                url, concurrency, duration_s, client_procs,
                metrics_url=metrics_url,
            )
        return run_loadgen(
            url, mode="closed", concurrency=concurrency,
            duration_s=duration_s, metrics_url=metrics_url,
        )

    try:
        proc, url = _boot_server(
            kc, base_port, admission=True, batch_max=batch_max, queue_bound=qb,
        )
        try:
            _warm_concurrent(url, min(16, concurrency), 60.0)
            single = drive(url)
        finally:
            _stop_server(proc)
        fproc, furl = _boot_server(
            kc, base_port + 2, admission=True, batch_max=batch_max,
            workers=workers, queue_bound=qb,
        )
        admin_url = f"http://127.0.0.1:{base_port + 3}"
        try:
            _warm_concurrent(furl, min(16, concurrency), 60.0)
            fleet = drive(furl, metrics_url=admin_url)
            fleet_metrics = scrape_metrics(admin_url)
            with urllib.request.urlopen(
                f"{admin_url}/api/fleet/status", timeout=5.0
            ) as resp:
                status = json.loads(resp.read().decode())
            # parity gate: re-boot a fresh single-process server so both
            # sides answer the same probes against the same stub cluster
            pproc, purl = _boot_server(
                kc, base_port + 40, admission=True, batch_max=batch_max,
            )
            try:
                parity = placement_parity(purl, furl)
            finally:
                _stop_server(pproc)
        finally:
            _stop_server(fproc)
    finally:
        stub.stop()
    torn = int(
        fleet_metrics.get(("simon_fleet_attach_retries_exhausted_total", ()), 0.0)
    )
    speedup = fleet["qps"] / single["qps"] if single["qps"] > 0 else float("inf")
    return {
        "workers": workers,
        "concurrency": concurrency,
        "duration_s": duration_s,
        "nodes": n_nodes,
        "cluster_pods": n_pods,
        "qps_single_process": single["qps"],
        "qps": fleet["qps"],
        "vs_single_process": round(speedup, 2),
        "p50_s": fleet["server_p50_s"],
        "p99_s": fleet["server_p99_s"],
        "p50_single_process_s": single["server_p50_s"],
        "p99_single_process_s": single["server_p99_s"],
        "batches": fleet["batches"],
        "mean_batch_size": fleet["mean_batch_size"],
        "shed": fleet["shed"],
        "errors": fleet["errors"],
        "placements_identical": parity,
        "torn_generation_exhausted": torn,
        "fleet_generation": int(
            fleet_metrics.get(("simon_fleet_generation", ()), -1.0)
        ),
        "fleet_publishes": int(
            fleet_metrics.get(("simon_fleet_publishes_total", ()), 0.0)
        ),
        "respawns": status.get("respawns_total", 0),
        "single_process": single,
        "fleet": fleet,
    }
