"""Admission queue + dispatcher — the concurrent serving core (ISSUE 8,
pipelined + priority lanes in ISSUE 16).

The serving path used to be single-flight: one TryLock per endpoint, every
concurrent request 503ed on the spot (the reference's gin behavior,
``server.go:167,:234``). This module replaces that with a small queueing
discipline in front of the engines:

- **admission**: requests enter a bounded queue (``OPENSIM_QUEUE_BOUND``).
  Past the bound they are *shed* with a typed 503 carrying ``Retry-After``
  (:class:`QueueFull`) — overload degrades into fast, honest rejections,
  never unbounded queueing. Shed counts land in
  ``simon_shed_total{reason=}`` (and per-lane in
  ``simon_lane_shed_total{lane=,reason=}``) and the rejection latency is
  the real elapsed time, not a fake 0.0.
- **priority lanes** (``OPENSIM_PRIORITY_LANES``): the queue splits into an
  *interactive* lane (explain requests, and requests expanding to at most
  ``OPENSIM_LANE_INTERACTIVE_PODS`` pods) and a *bulk* lane, picked up
  weighted ``OPENSIM_LANE_WEIGHT``:1 in the interactive lane's favor with
  a hard starvation bound (``OPENSIM_LANE_STARVATION_S``): a bulk request
  waiting past the bound is picked next regardless of weight (counted in
  ``simon_lane_starvation_promotions_total``). Small interactive requests
  stop queueing behind bulk deploys; bulk still makes guaranteed progress.
- **coalescing**: the dispatcher waits one short window
  (``OPENSIM_BATCH_WINDOW_MS``) after the first arrival, then folds every
  *batchable* queued request (no newnodes, prep cache on) onto one shared
  warm prep and runs them as a single request-axis batched schedule
  (``engine/reqbatch.py``) — concurrency multiplies throughput instead of
  serializing behind one lock. A lone request takes the solo path (full
  engine ladder, full span fidelity); batching only engages when there is
  something to batch.
- **pipelining** (``OPENSIM_PIPELINE``, the ISSUE 16 tentpole): with the
  REST layer's staged executors (``prep_fn``/``dispatch_fn``/``decode_fn``)
  the batch lifecycle runs as a three-stage pipeline instead of one serial
  inline call. The dispatcher thread runs batch k+1's HOST PREP
  (expand + ``derive_with_app_slices`` + mask build, under the base-entry
  lock) while the engine thread runs batch k's DISPATCH (the C++/XLA
  engines release the GIL; dispatch reads only the derived prep's arrays,
  which generation swaps never mutate in place — ``twin_pod_delta`` builds
  a NEW entry from a forked encoder), and the decode thread demultiplexes
  batch k-1's results back onto its tickets. Stage handoffs are depth-1
  queues, so backpressure is structural: at most one batch per stage.
  The measured overlap (dispatch-busy seconds observed during a prep
  window) lands in ``simon_pipeline_prep_overlap_seconds_total`` — the
  overlap is observable, not assumed.
- **worker pool**: unbatchable requests run concurrently through the
  bounded :class:`server.pool.WorkerPool` instead of being rejected.
- **load-shedding deadlines**: a ticket whose deadline expires *while
  queued* is shed with a typed 504 naming the ``queue`` phase (and a
  ``simon_shed_total{reason="deadline"}`` bump). A ticket that was already
  expired at admission still executes — the first phase boundary raises
  the classic typed 504 naming snapshot/prepare/..., preserving the
  resilience layer's contract.

Locking discipline (enforced by opensim-lint OSL1001): nothing blocking —
no sleeps, no socket/file I/O, no future/event waits, no stage-queue puts —
happens while the queue condition lock is held. The window sleep, the
engine work, the handoff puts and the result waits all run outside it.
"""

from __future__ import annotations

import collections
import logging
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..obs.metrics import (
    BATCH_SIZE_BUCKETS,
    RECORDER,
    family_header,
    make_counter,
    make_histogram,
)
from ..resilience.deadline import Deadline, DeadlineExceeded
from ..utils import envknobs

log = logging.getLogger("opensim_tpu.server")

__all__ = [
    "AdmissionController",
    "QueueFull",
    "Ticket",
    "admission_enabled",
    "batch_window_s",
    "queue_bound",
    "batch_max",
    "pipeline_enabled",
    "priority_lanes_enabled",
    "lane_interactive_pods",
    "lane_weight",
    "lane_starvation_s",
    "classify_lane",
    "payload_pod_estimate",
]

LANES = ("interactive", "bulk")


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    raw = envknobs.raw(name)
    if not raw:
        return default
    try:
        return max(lo, float(raw))
    except ValueError:
        log.warning("ignoring unparseable %s=%r (using %s)", name, raw, default)
        return default


def admission_enabled() -> bool:
    """``OPENSIM_ADMISSION``: ``on`` (default) routes requests through the
    admission queue; ``off`` restores the single-flight TryLock path."""
    return envknobs.raw("OPENSIM_ADMISSION", "on").strip().lower() not in (
        "off", "0", "false",
    )


def batch_window_s() -> float:
    return _env_float("OPENSIM_BATCH_WINDOW_MS", 5.0) / 1000.0


def queue_bound() -> int:
    return int(_env_float("OPENSIM_QUEUE_BOUND", 64.0, lo=1.0))


def batch_max() -> int:
    return int(_env_float("OPENSIM_BATCH_MAX", 16.0, lo=1.0))


def pipeline_enabled() -> bool:
    """``OPENSIM_PIPELINE``: ``on`` (default) overlaps batch k+1 host prep
    with batch k engine dispatch; ``off`` restores the serial loop."""
    return envknobs.raw("OPENSIM_PIPELINE", "on").strip().lower() not in (
        "off", "0", "false",
    )


def priority_lanes_enabled() -> bool:
    return envknobs.raw("OPENSIM_PRIORITY_LANES", "on").strip().lower() not in (
        "off", "0", "false",
    )


def lane_interactive_pods() -> int:
    return int(_env_float("OPENSIM_LANE_INTERACTIVE_PODS", 8.0, lo=0.0))


def lane_weight() -> int:
    return int(_env_float("OPENSIM_LANE_WEIGHT", 4.0, lo=1.0))


def lane_starvation_s() -> float:
    return _env_float("OPENSIM_LANE_STARVATION_S", 0.5)


#: payload keys that carry workload lists (mirrors rest._decode_app's map,
#: replica-bearing kinds only — the lane estimate needs counts, not decode)
_WORKLOAD_KEYS = (
    "pods", "Pods", "deployments", "Deployments", "daemonsets", "DaemonSets",
    "statefulsets", "StatefulSets", "jobs", "Jobs", "cronjobs", "CronJobs",
)


def payload_pod_estimate(payload: dict) -> int:
    """Cheap upper-ish bound on how many pods a simulate payload expands
    to: sum of ``spec.replicas`` (min 1) across workload lists. Used only
    for lane classification — an estimate, never a correctness input."""
    total = 0
    for key in _WORKLOAD_KEYS:
        objs = payload.get(key)
        if not objs:
            continue
        for obj in objs:
            reps = 1
            if isinstance(obj, dict):
                spec = obj.get("spec")
                if isinstance(spec, dict):
                    try:
                        reps = int(spec.get("replicas") or 1)
                    except (TypeError, ValueError):
                        reps = 1
            total += max(1, reps)
    return total


def classify_lane(ticket: "Ticket") -> str:
    """Interactive = explain requests (a human is waiting on an audit) and
    anything expanding to at most ``OPENSIM_LANE_INTERACTIVE_PODS`` pods
    (deploy of a few pods, scale-down checks); everything else is bulk."""
    if ticket.explain:
        return "interactive"
    try:
        estimate = payload_pod_estimate(ticket.payload)
    except Exception as e:
        # a malformed payload fails in the executor with a typed error;
        # lane classification just routes it through the bulk lane
        log.debug("lane classification failed: %s: %s", type(e).__name__, e)
        return "bulk"
    return "interactive" if estimate <= lane_interactive_pods() else "bulk"


class QueueFull(RuntimeError):
    """Typed shed: the admission queue cannot take this request.
    ``retry_after_s`` is the dispatcher's drain estimate, surfaced as the
    503's ``Retry-After`` header; ``reason`` distinguishes overload
    (``queue_full`` — retrying later helps) from graceful shutdown
    (``shutting_down`` — retry against another replica) and is echoed in
    the 503 body and ``simon_shed_total{reason=}``."""

    def __init__(
        self, message: str, retry_after_s: float = 1.0,
        reason: str = "queue_full",
    ) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.reason = reason


@dataclass(eq=False)
class Ticket:
    """One queued simulate request and its completion slot."""

    kind: str  # "deploy" | "scale"
    payload: dict
    explain: bool = False
    deadline: Optional[Deadline] = None
    trace: Optional[object] = None  # the request's TraceContext (or None)
    request_id: str = ""
    has_new_nodes: bool = False
    lane: str = "bulk"  # assigned by the controller at submit
    enqueued: float = field(default_factory=time.monotonic)
    # completion slot, written exactly once by the executor
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[object] = None  # SimulateResult on success
    error: Optional[BaseException] = None
    stale: bool = False  # request_served_stale() observed on the exec thread
    queue_s: float = 0.0
    batch_size: int = 0  # 0 = solo path

    def batchable(self) -> bool:
        # newnodes get per-request randomized fake node names (a shared
        # node axis would replay one request's names into another's
        # response). explain requests batch like any other (ISSUE 15
        # satellite): the batch runs the count_all scan variant and only
        # the explain rider's decode pays the audit build — per-rider
        # fail rows over the shared derive, bit-identical to solo explain
        # (gated by tests/test_admission.py).
        return not self.has_new_nodes

    def resolve(self, result=None, error: Optional[BaseException] = None,
                stale: bool = False, batch_size: int = 0) -> None:
        self.result, self.error, self.stale = result, error, stale
        self.batch_size = batch_size
        self.done.set()

    def expired_in_queue(self) -> bool:
        """Deadline ran out while waiting — but only if it was still alive
        at admission (a pre-expired deadline keeps the legacy behavior:
        execute, and let the first phase boundary raise its typed 504)."""
        return (
            self.deadline is not None
            and not self._expired_at_admission
            and self.deadline.expired()
        )

    def __post_init__(self) -> None:
        self._expired_at_admission = (
            self.deadline is not None and self.deadline.expired()
        )


@dataclass(eq=False)
class _InFlight:
    """One batch riding the staged pipeline: the tickets, the REST layer's
    opaque stage state (PreppedBatch), and bookkeeping for telemetry."""

    tickets: List[Ticket]
    state: object = None
    error: Optional[BaseException] = None
    started: float = 0.0
    prep_s: float = 0.0


class AdmissionController:
    """The queue + dispatcher. ``solo_fn(ticket)`` and
    ``batch_fn(tickets)`` are provided by the REST layer (they own the
    snapshot/prep-cache internals); both MUST resolve every ticket they are
    handed, success or error — an unresolved ticket would hang its client
    until the wait backstop.

    The optional staged executors turn the batch path into a pipeline
    (``OPENSIM_PIPELINE``):

    - ``prep_fn(tickets) -> state | None`` — host prep under the
      base-entry lock (expand, derive, masks). May resolve individual
      tickets (malformed payloads); returning ``None`` means the batch
      cannot ride the shared base (unroutable/derive refusal) and the
      controller falls the unresolved tickets back to the solo pool.
    - ``dispatch_fn(state) -> state`` — the engine dispatch. Touches ONLY
      the derived prep's arrays (no base-entry lock): the engines release
      the GIL here, which is exactly the window prep k+1 overlaps.
    - ``decode_fn(state) -> None`` — demultiplex results per rider under
      the base-entry lock and resolve every remaining ticket.

    Without the staged executors (or with the knob off) ``batch_fn`` runs
    the proven serial inline path unchanged.
    """

    def __init__(
        self,
        solo_fn: Callable[[Ticket], None],
        batch_fn: Callable[[List[Ticket]], None],
        pool=None,
        window_s: Optional[float] = None,
        bound: Optional[int] = None,
        max_batch: Optional[int] = None,
        prep_fn: Optional[Callable[[List[Ticket]], object]] = None,
        dispatch_fn: Optional[Callable[[object], object]] = None,
        decode_fn: Optional[Callable[[object], None]] = None,
    ) -> None:
        from .pool import WorkerPool

        self.solo_fn = solo_fn
        self.batch_fn = batch_fn
        self.prep_fn = prep_fn
        self.dispatch_fn = dispatch_fn
        self.decode_fn = decode_fn
        self.window_s = batch_window_s() if window_s is None else window_s
        self.bound = queue_bound() if bound is None else bound
        self.max_batch = batch_max() if max_batch is None else max_batch
        # knobs are captured at construction: a server decides its serving
        # shape at boot, not per request (tests construct with the env set)
        self.pipelined = (
            prep_fn is not None and dispatch_fn is not None
            and decode_fn is not None and pipeline_enabled()
        )
        self.lanes_on = priority_lanes_enabled()
        self.lane_weight = lane_weight()
        self.starvation_s = lane_starvation_s()
        self._pool = pool if pool is not None else WorkerPool()
        self._cond = threading.Condition()
        self._lanes: Dict[str, "collections.deque[Ticket]"] = {
            lane: collections.deque() for lane in LANES
        }  # guarded-by: _cond
        self._inter_picks = 0  # interactive pickups since last bulk; guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cond
        self._engine_thread: Optional[threading.Thread] = None  # guarded-by: _cond
        self._decode_thread: Optional[threading.Thread] = None  # guarded-by: _cond
        # depth-1 stage handoffs: the structural backpressure that bounds
        # the pipeline to one batch per stage
        self._dispatch_q: "queue_mod.Queue[Optional[_InFlight]]" = queue_mod.Queue(maxsize=1)
        self._decode_q: "queue_mod.Queue[Optional[_InFlight]]" = queue_mod.Queue(maxsize=1)
        # dispatch-busy clock for the overlap measurement: the engine
        # thread marks busy intervals; the prep wrapper differences the
        # clock across its window — overlap = dispatch-busy seconds that
        # elapsed while prep ran
        self._busy_lock = threading.Lock()
        self._busy_accum = 0.0  # guarded-by: _busy_lock
        self._busy_since: Optional[float] = None  # guarded-by: _busy_lock
        # telemetry (rendered into /metrics via metrics_lines): families
        # come from the obs/metrics.py registry (OSL1101), all mutations
        # under the ONE recorder lock like every other family
        self.shed = make_counter("simon_shed_total", ("reason",))
        self.lane_shed = make_counter("simon_lane_shed_total", ("lane", "reason"))
        self.batch_sizes = make_histogram("simon_batch_size", (), buckets=BATCH_SIZE_BUCKETS)
        self.queue_wait = make_histogram("simon_queue_wait_seconds", ())
        self.stage_seconds = make_histogram("simon_pipeline_stage_seconds", ("stage",))
        self.batches_total = 0  # guarded-by: RECORDER.lock
        self.lane_admitted = {lane: 0 for lane in LANES}  # guarded-by: RECORDER.lock
        self.starvation_promotions = 0  # guarded-by: RECORDER.lock
        self.overlapped_batches = 0  # guarded-by: RECORDER.lock
        self.prep_overlap_s = 0.0  # guarded-by: RECORDER.lock
        self._stage_agg: Dict[str, List[float]] = {}  # stage -> [count, total, max]; guarded-by: RECORDER.lock
        # drain-rate estimate for Retry-After
        self.ewma_service_s = 0.05  # guarded-by: RECORDER.lock

    # -- client side --------------------------------------------------------

    def submit(self, ticket: Ticket) -> Ticket:
        """Admit (or shed) a ticket; starts the dispatcher on first use."""
        ticket.lane = classify_lane(ticket) if self.lanes_on else "bulk"
        with self._cond:
            if self._closed:
                with RECORDER.lock:
                    self.shed.inc(("shutting_down",))
                    self.lane_shed.inc((ticket.lane, "shutting_down"))
                raise QueueFull(
                    "the server is shutting down", retry_after_s=1.0,
                    reason="shutting_down",
                )
            depth = sum(len(q) for q in self._lanes.values())
            if depth >= self.bound:
                with RECORDER.lock:
                    retry = max(
                        0.05, depth * self.ewma_service_s / max(1, self.max_batch)
                    )
                    self.shed.inc(("queue_full",))
                    self.lane_shed.inc((ticket.lane, "queue_full"))
                raise QueueFull(
                    f"admission queue at bound ({depth}/{self.bound}); "
                    "try again later",
                    retry_after_s=retry,
                )
            self._lanes[ticket.lane].append(ticket)
            with RECORDER.lock:
                self.lane_admitted[ticket.lane] += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="simon-dispatch", daemon=True
                )
                self._thread.start()
                if self.pipelined:
                    self._engine_thread = threading.Thread(
                        target=self._engine_loop, name="simon-pipe-engine",
                        daemon=True,
                    )
                    self._decode_thread = threading.Thread(
                        target=self._decode_loop, name="simon-pipe-decode",
                        daemon=True,
                    )
                    self._engine_thread.start()
                    self._decode_thread.start()
            self._cond.notify()
        return ticket

    def wait(self, ticket: Ticket) -> Ticket:
        """Block the REST handler thread until the ticket resolves. The
        backstop bounds a lost ticket (a dispatcher bug) to a typed error
        instead of a hung client."""
        backstop = 600.0
        if ticket.deadline is not None:
            backstop = max(1.0, ticket.deadline.remaining() + 30.0)
        if not ticket.done.wait(timeout=backstop):
            raise RuntimeError(
                "admission dispatcher unresponsive "
                f"(ticket not resolved within {backstop:.0f}s)"
            )
        if ticket.error is not None:
            raise ticket.error
        return ticket

    def depth(self) -> int:
        with self._cond:
            return sum(len(q) for q in self._lanes.values())

    def lane_depths(self) -> Dict[str, int]:
        with self._cond:
            return {lane: len(q) for lane, q in self._lanes.items()}

    def stop(self, drain_s: float = 30.0) -> None:
        """Graceful drain (SIGTERM/SIGINT, docs/serving.md): queued tickets
        shed typed 503 ``shutting_down``; the batches/solos already IN
        FLIGHT complete (their clients get real results) before the worker
        pool stops — the dispatcher thread is joined up to ``drain_s`` and
        the pipeline stages drain through sentinels."""
        with self._cond:
            self._closed = True
            pending: List[Ticket] = []
            for q in self._lanes.values():
                pending.extend(q)
                q.clear()
            self._cond.notify_all()
            thread = self._thread
            engine_thread = self._engine_thread
            decode_thread = self._decode_thread
        if pending:
            with RECORDER.lock:
                for _t in pending:
                    self.shed.inc(("shutting_down",))
                    self.lane_shed.inc((_t.lane, "shutting_down"))
        for t in pending:
            t.resolve(
                error=QueueFull(
                    "the server is shutting down", reason="shutting_down"
                )
            )
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=drain_s)
        if engine_thread is not None:
            # the sentinel rides BEHIND any in-flight batch (depth-1 queue):
            # the engine stage finishes it, forwards the sentinel, and the
            # decode stage resolves the last clients before exiting
            self._dispatch_q.put(None)
            engine_thread.join(timeout=drain_s)
        if decode_thread is not None:
            decode_thread.join(timeout=drain_s)
        self._pool.shutdown()

    # -- dispatcher ---------------------------------------------------------

    def _first_arrival_locked(self) -> float:
        return min(
            q[0].enqueued for q in self._lanes.values() if q
        )

    def _pick_locked(self, now: float) -> Optional[Ticket]:
        """Weighted two-lane pickup (guarded-by: _cond). Interactive wins
        ``lane_weight`` picks per bulk pick; a bulk head older than the
        starvation bound is promoted immediately (counted)."""
        inter, bulk = self._lanes["interactive"], self._lanes["bulk"]
        if not inter and not bulk:
            return None
        if not inter:
            lane = "bulk"
        elif not bulk:
            lane = "interactive"
        else:
            starved = now - bulk[0].enqueued > self.starvation_s
            if starved or self._inter_picks >= self.lane_weight:
                lane = "bulk"
                if starved and self._inter_picks < self.lane_weight:
                    with RECORDER.lock:
                        self.starvation_promotions += 1
            else:
                lane = "interactive"
        if lane == "interactive":
            self._inter_picks += 1
        else:
            self._inter_picks = 0
        return self._lanes[lane].popleft()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not any(self._lanes.values()) and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return
                first_arrival = self._first_arrival_locked()
            # coalescing window, measured from the FIRST waiter's arrival so
            # a busy queue drains at window cadence instead of re-arming per
            # arrival. Outside the lock: admission must never block on it.
            delay = first_arrival + self.window_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                drained, kept = [], []
                while len(drained) < self.max_batch:
                    t = self._pick_locked(now)
                    if t is None:
                        break
                    drained.append(t)
                # non-batchable tickets never consume batch slots
                for t in list(drained):
                    if not t.batchable():
                        drained.remove(t)
                        kept.append(t)
            self._dispatch(drained, kept)

    def _dispatch(self, batchable: List[Ticket], solos: List[Ticket]) -> None:
        now = time.monotonic()
        ready: List[Ticket] = []
        for t in batchable + solos:
            t.queue_s = now - t.enqueued
            if t.expired_in_queue():
                with RECORDER.lock:
                    self.shed.inc(("deadline",))
                    self.lane_shed.inc((t.lane, "deadline"))
                    self.queue_wait.observe(t.queue_s, ())
                t.resolve(
                    error=DeadlineExceeded(
                        "request deadline expired while queued "
                        f"(waited {t.queue_s:.3f}s)",
                        phase="queue",
                    )
                )
            else:
                ready.append(t)
        batchable = [t for t in batchable if t in ready]
        solos = [t for t in solos if t in ready]
        # a ticket whose deadline is ALREADY dead (pre-expired at admission
        # — kept for the legacy phase contract) must not ride a batch: the
        # batch installs no deadline scope, so only the solo path can raise
        # its typed 504 at the first phase boundary
        dead = [t for t in batchable if t.deadline is not None and t.deadline.expired()]
        if dead:
            batchable = [t for t in batchable if t not in dead]
            solos = solos + dead
        with RECORDER.lock:
            for t in ready:
                self.queue_wait.observe(t.queue_s, ())
        for t in solos:
            self._pool.submit(self._run_solo, t)
        if len(batchable) == 1:
            # a batch of one is just overhead: the solo path keeps the full
            # engine ladder (megakernel included) and per-phase span tree
            self._pool.submit(self._run_solo, batchable[0])
        elif batchable and self.pipelined:
            # staged: prep INLINE on this thread (so the next drain's prep
            # naturally overlaps the engine thread's dispatch), then hand
            # off. The blocking put IS the backpressure — one batch per
            # stage — and happens outside every lock (OSL1001).
            inflight = self._run_prep(batchable)
            if inflight is not None:
                self._dispatch_q.put(inflight)
        elif batchable:
            # INLINE, not pooled: one batch in flight at a time (groups
            # would only serialize on the base-entry lock anyway), so new
            # arrivals accumulate in the queue while this batch runs and
            # the next drain folds them into one bigger batch — batch size
            # adapts to the service rate under load (the classic serving-
            # system dynamic-batching loop)
            self._run_group(batchable)

    def _run_solo(self, ticket: Ticket) -> None:
        t0 = time.monotonic()
        try:
            self.solo_fn(ticket)
        except BaseException as e:  # the backstop of last resort: the
            # error is transported to the waiting client, not dropped
            log.warning("solo executor raised %s: %s", type(e).__name__, e)
            if not ticket.done.is_set():
                ticket.resolve(error=e)
        finally:
            self._note_service(time.monotonic() - t0)
        if not ticket.done.is_set():
            ticket.resolve(
                error=RuntimeError("solo executor returned without resolving")
            )

    def _run_group(self, tickets: List[Ticket]) -> None:
        t0 = time.monotonic()
        # recorded at batch START (size is known upfront): a client whose
        # ticket just resolved must already see the batch in /metrics —
        # recording after resolution races every scrape-after-response
        with RECORDER.lock:
            self.batches_total += 1
            self.batch_sizes.observe(float(len(tickets)), ())
        try:
            self.batch_fn(tickets)
        except BaseException as e:
            # transported to every waiting client as a typed error
            log.warning("batch executor raised %s: %s", type(e).__name__, e)
            for t in tickets:
                if not t.done.is_set():
                    t.resolve(error=e)
        finally:
            self._note_service(time.monotonic() - t0)
        for t in tickets:
            if not t.done.is_set():
                t.resolve(
                    error=RuntimeError("batch executor returned without resolving")
                )

    # -- pipeline stages ----------------------------------------------------

    def _busy_seconds(self, now: float) -> float:
        with self._busy_lock:
            busy = self._busy_accum
            if self._busy_since is not None:
                busy += now - self._busy_since
            return busy

    def _observe_stage(self, stage: str, seconds: float) -> None:
        with RECORDER.lock:
            self.stage_seconds.observe(seconds, (stage,))
            agg = self._stage_agg.setdefault(stage, [0.0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += seconds
            agg[2] = max(agg[2], seconds)

    def _run_prep(self, tickets: List[Ticket]) -> Optional[_InFlight]:
        t0 = time.monotonic()
        busy0 = self._busy_seconds(t0)
        with RECORDER.lock:
            self.batches_total += 1
            self.batch_sizes.observe(float(len(tickets)), ())
        inflight = _InFlight(tickets=tickets, started=t0)
        state = None
        try:
            state = self.prep_fn(tickets)
        except BaseException as e:
            log.warning("prep stage raised %s: %s", type(e).__name__, e)
            for t in tickets:
                if not t.done.is_set():
                    t.resolve(error=e)
            self._note_service(time.monotonic() - t0)
            return None
        finally:
            t1 = time.monotonic()
            overlap = max(0.0, self._busy_seconds(t1) - busy0)
            self._observe_stage("prep", t1 - t0)
            with RECORDER.lock:
                if overlap > 0.0:
                    self.overlapped_batches += 1
                    self.prep_overlap_s += overlap
        if state is None:
            # the batch cannot ride the shared base (derive refusal /
            # unroutable): unresolved tickets fall back to the solo pool,
            # exactly like the serial path's _BatchUnroutable fallback
            for t in tickets:
                if not t.done.is_set():
                    self._pool.submit(self._run_solo, t)
            self._note_service(time.monotonic() - t0)
            return None
        inflight.state = state
        inflight.prep_s = t1 - t0
        return inflight

    def _engine_loop(self) -> None:
        while True:
            item = self._dispatch_q.get()
            if item is None:
                self._decode_q.put(None)
                return
            t0 = time.monotonic()
            with self._busy_lock:
                self._busy_since = t0
            try:
                item.state = self.dispatch_fn(item.state)
            except BaseException as e:
                log.warning("dispatch stage raised %s: %s", type(e).__name__, e)
                item.error = e
            finally:
                t1 = time.monotonic()
                with self._busy_lock:
                    self._busy_accum += t1 - t0
                    self._busy_since = None
                self._observe_stage("dispatch", t1 - t0)
            self._decode_q.put(item)

    def _decode_loop(self) -> None:
        while True:
            item = self._decode_q.get()
            if item is None:
                return
            t0 = time.monotonic()
            try:
                if item.error is not None:
                    raise item.error
                self.decode_fn(item.state)
            except BaseException as e:
                log.warning("decode stage raised %s: %s", type(e).__name__, e)
                for t in item.tickets:
                    if not t.done.is_set():
                        t.resolve(error=e)
            finally:
                self._observe_stage("decode", time.monotonic() - t0)
                # the EWMA feeds Retry-After: whole-batch latency through
                # the pipeline, prep start to decode end
                self._note_service(time.monotonic() - item.started)
            for t in item.tickets:
                if not t.done.is_set():
                    t.resolve(
                        error=RuntimeError(
                            "decode stage returned without resolving"
                        )
                    )

    def _note_service(self, seconds: float) -> None:
        with RECORDER.lock:
            self.ewma_service_s = 0.8 * self.ewma_service_s + 0.2 * max(
                0.001, seconds
            )

    # -- /metrics + profile -------------------------------------------------

    def pipeline_snapshot(self) -> dict:
        """The ``simon profile`` pipeline section: stage aggregates, the
        measured overlap, and lane counters (served via
        ``/api/debug/profile``)."""
        depths = self.lane_depths()
        with RECORDER.lock:
            return {
                "enabled": self.pipelined,
                "lanes_enabled": self.lanes_on,
                "batches": self.batches_total,
                "overlapped_batches": self.overlapped_batches,
                "prep_overlap_s": round(self.prep_overlap_s, 6),
                "starvation_promotions": self.starvation_promotions,
                "lane_admitted": dict(self.lane_admitted),
                "lane_depth": depths,
                "stages": {
                    stage: {
                        "count": int(agg[0]),
                        "total_s": round(agg[1], 6),
                        "max_s": round(agg[2], 6),
                    }
                    for stage, agg in sorted(self._stage_agg.items())
                },
            }

    def metrics_lines(self) -> List[str]:
        lines = list(family_header("simon_admission_queue_depth"))
        lines.append(f"simon_admission_queue_depth {self.depth()}")
        depths = self.lane_depths()
        lines += family_header("simon_lane_depth")
        for lane in LANES:
            lines.append(f'simon_lane_depth{{lane="{lane}"}} {depths.get(lane, 0)}')
        with RECORDER.lock:
            lines += family_header("simon_batches_total")
            lines.append(f"simon_batches_total {self.batches_total}")
            shed = self.shed.render_lines()
            if not shed:
                # conformance: the family must exist from the first scrape,
                # not only after the first shed
                shed = family_header("simon_shed_total")
            lines += shed
            lane_shed = self.lane_shed.render_lines()
            if not lane_shed:
                lane_shed = family_header("simon_lane_shed_total")
            lines += lane_shed
            lines += family_header("simon_lane_admitted_total")
            for lane in LANES:
                lines.append(
                    f'simon_lane_admitted_total{{lane="{lane}"}} '
                    f"{self.lane_admitted[lane]}"
                )
            lines += family_header("simon_lane_starvation_promotions_total")
            lines.append(
                "simon_lane_starvation_promotions_total "
                f"{self.starvation_promotions}"
            )
            stage = self.stage_seconds.render_lines()
            if not stage:
                stage = family_header("simon_pipeline_stage_seconds")
            lines += stage
            lines += family_header("simon_pipeline_prep_overlap_seconds_total")
            lines.append(
                "simon_pipeline_prep_overlap_seconds_total "
                f"{self.prep_overlap_s:.6f}"
            )
            lines += family_header("simon_pipeline_overlapped_batches_total")
            lines.append(
                "simon_pipeline_overlapped_batches_total "
                f"{self.overlapped_batches}"
            )
            lines += self.batch_sizes.render_lines()
            lines += self.queue_wait.render_lines()
        return lines
