"""Admission queue + dispatcher — the concurrent serving core (ISSUE 8).

The serving path used to be single-flight: one TryLock per endpoint, every
concurrent request 503ed on the spot (the reference's gin behavior,
``server.go:167,:234``). This module replaces that with a small queueing
discipline in front of the engines:

- **admission**: requests enter a bounded queue (``OPENSIM_QUEUE_BOUND``).
  Past the bound they are *shed* with a typed 503 carrying ``Retry-After``
  (:class:`QueueFull`) — overload degrades into fast, honest rejections,
  never unbounded queueing. Shed counts land in
  ``simon_shed_total{reason=}`` and the rejection latency is the real
  elapsed time, not a fake 0.0.
- **coalescing**: the dispatcher waits one short window
  (``OPENSIM_BATCH_WINDOW_MS``) after the first arrival, then folds every
  *batchable* queued request (no newnodes, no explain, prep cache on) onto
  one shared warm prep and runs them as a single request-axis batched
  schedule (``engine/reqbatch.py``) — concurrency multiplies throughput
  instead of serializing behind one lock. A lone request takes the solo
  path (full engine ladder, full span fidelity); batching only engages
  when there is something to batch.
- **worker pool**: unbatchable requests run concurrently through the
  bounded :class:`server.pool.WorkerPool` instead of being rejected.
- **load-shedding deadlines**: a ticket whose deadline expires *while
  queued* is shed with a typed 504 naming the ``queue`` phase (and a
  ``simon_shed_total{reason="deadline"}`` bump). A ticket that was already
  expired at admission still executes — the first phase boundary raises
  the classic typed 504 naming snapshot/prepare/..., preserving the
  resilience layer's contract.

Locking discipline (enforced by opensim-lint OSL1001): nothing blocking —
no sleeps, no socket/file I/O, no future/event waits — happens while the
queue condition lock is held. The window sleep, the engine work and the
result waits all run outside it.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..obs.metrics import (
    BATCH_SIZE_BUCKETS,
    RECORDER,
    family_header,
    make_counter,
    make_histogram,
)
from ..resilience.deadline import Deadline, DeadlineExceeded
from ..utils import envknobs

log = logging.getLogger("opensim_tpu.server")

__all__ = [
    "AdmissionController",
    "QueueFull",
    "Ticket",
    "admission_enabled",
    "batch_window_s",
    "queue_bound",
    "batch_max",
]

def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    raw = envknobs.raw(name)
    if not raw:
        return default
    try:
        return max(lo, float(raw))
    except ValueError:
        log.warning("ignoring unparseable %s=%r (using %s)", name, raw, default)
        return default


def admission_enabled() -> bool:
    """``OPENSIM_ADMISSION``: ``on`` (default) routes requests through the
    admission queue; ``off`` restores the single-flight TryLock path."""
    return envknobs.raw("OPENSIM_ADMISSION", "on").strip().lower() not in (
        "off", "0", "false",
    )


def batch_window_s() -> float:
    return _env_float("OPENSIM_BATCH_WINDOW_MS", 5.0) / 1000.0


def queue_bound() -> int:
    return int(_env_float("OPENSIM_QUEUE_BOUND", 64.0, lo=1.0))


def batch_max() -> int:
    return int(_env_float("OPENSIM_BATCH_MAX", 16.0, lo=1.0))


class QueueFull(RuntimeError):
    """Typed shed: the admission queue cannot take this request.
    ``retry_after_s`` is the dispatcher's drain estimate, surfaced as the
    503's ``Retry-After`` header; ``reason`` distinguishes overload
    (``queue_full`` — retrying later helps) from graceful shutdown
    (``shutting_down`` — retry against another replica) and is echoed in
    the 503 body and ``simon_shed_total{reason=}``."""

    def __init__(
        self, message: str, retry_after_s: float = 1.0,
        reason: str = "queue_full",
    ) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.reason = reason


@dataclass(eq=False)
class Ticket:
    """One queued simulate request and its completion slot."""

    kind: str  # "deploy" | "scale"
    payload: dict
    explain: bool = False
    deadline: Optional[Deadline] = None
    trace: Optional[object] = None  # the request's TraceContext (or None)
    request_id: str = ""
    has_new_nodes: bool = False
    enqueued: float = field(default_factory=time.monotonic)
    # completion slot, written exactly once by the executor
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[object] = None  # SimulateResult on success
    error: Optional[BaseException] = None
    stale: bool = False  # request_served_stale() observed on the exec thread
    queue_s: float = 0.0
    batch_size: int = 0  # 0 = solo path

    def batchable(self) -> bool:
        # newnodes get per-request randomized fake node names (a shared
        # node axis would replay one request's names into another's
        # response). explain requests batch like any other (ISSUE 15
        # satellite): the batch runs the count_all scan variant and only
        # the explain rider's decode pays the audit build — per-rider
        # fail rows over the shared derive, bit-identical to solo explain
        # (gated by tests/test_admission.py).
        return not self.has_new_nodes

    def resolve(self, result=None, error: Optional[BaseException] = None,
                stale: bool = False, batch_size: int = 0) -> None:
        self.result, self.error, self.stale = result, error, stale
        self.batch_size = batch_size
        self.done.set()

    def expired_in_queue(self) -> bool:
        """Deadline ran out while waiting — but only if it was still alive
        at admission (a pre-expired deadline keeps the legacy behavior:
        execute, and let the first phase boundary raise its typed 504)."""
        return (
            self.deadline is not None
            and not self._expired_at_admission
            and self.deadline.expired()
        )

    def __post_init__(self) -> None:
        self._expired_at_admission = (
            self.deadline is not None and self.deadline.expired()
        )


class AdmissionController:
    """The queue + dispatcher. ``solo_fn(ticket)`` and
    ``batch_fn(tickets)`` are provided by the REST layer (they own the
    snapshot/prep-cache internals); both MUST resolve every ticket they are
    handed, success or error — an unresolved ticket would hang its client
    until the wait backstop."""

    def __init__(
        self,
        solo_fn: Callable[[Ticket], None],
        batch_fn: Callable[[List[Ticket]], None],
        pool=None,
        window_s: Optional[float] = None,
        bound: Optional[int] = None,
        max_batch: Optional[int] = None,
    ) -> None:
        from .pool import WorkerPool

        self.solo_fn = solo_fn
        self.batch_fn = batch_fn
        self.window_s = batch_window_s() if window_s is None else window_s
        self.bound = queue_bound() if bound is None else bound
        self.max_batch = batch_max() if max_batch is None else max_batch
        self._pool = pool if pool is not None else WorkerPool()
        self._cond = threading.Condition()
        self._queue: "collections.deque[Ticket]" = collections.deque()  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cond
        # telemetry (rendered into /metrics via metrics_lines): families
        # come from the obs/metrics.py registry (OSL1101), all mutations
        # under the ONE recorder lock like every other family
        self.shed = make_counter("simon_shed_total", ("reason",))
        self.batch_sizes = make_histogram("simon_batch_size", (), buckets=BATCH_SIZE_BUCKETS)
        self.queue_wait = make_histogram("simon_queue_wait_seconds", ())
        self.batches_total = 0  # guarded-by: RECORDER.lock
        # drain-rate estimate for Retry-After
        self.ewma_service_s = 0.05  # guarded-by: RECORDER.lock

    # -- client side --------------------------------------------------------

    def submit(self, ticket: Ticket) -> Ticket:
        """Admit (or shed) a ticket; starts the dispatcher on first use."""
        with self._cond:
            if self._closed:
                with RECORDER.lock:
                    self.shed.inc(("shutting_down",))
                raise QueueFull(
                    "the server is shutting down", retry_after_s=1.0,
                    reason="shutting_down",
                )
            if len(self._queue) >= self.bound:
                depth = len(self._queue)
                with RECORDER.lock:
                    retry = max(
                        0.05, depth * self.ewma_service_s / max(1, self.max_batch)
                    )
                    self.shed.inc(("queue_full",))
                raise QueueFull(
                    f"admission queue at bound ({depth}/{self.bound}); "
                    "try again later",
                    retry_after_s=retry,
                )
            self._queue.append(ticket)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="simon-dispatch", daemon=True
                )
                self._thread.start()
            self._cond.notify()
        return ticket

    def wait(self, ticket: Ticket) -> Ticket:
        """Block the REST handler thread until the ticket resolves. The
        backstop bounds a lost ticket (a dispatcher bug) to a typed error
        instead of a hung client."""
        backstop = 600.0
        if ticket.deadline is not None:
            backstop = max(1.0, ticket.deadline.remaining() + 30.0)
        if not ticket.done.wait(timeout=backstop):
            raise RuntimeError(
                "admission dispatcher unresponsive "
                f"(ticket not resolved within {backstop:.0f}s)"
            )
        if ticket.error is not None:
            raise ticket.error
        return ticket

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def stop(self, drain_s: float = 30.0) -> None:
        """Graceful drain (SIGTERM/SIGINT, docs/serving.md): queued tickets
        shed typed 503 ``shutting_down``; the batch/solo already IN FLIGHT
        completes (its clients get real results) before the worker pool
        stops — the dispatcher thread is joined up to ``drain_s``."""
        with self._cond:
            self._closed = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
            thread = self._thread
        if pending:
            with RECORDER.lock:
                for _t in pending:
                    self.shed.inc(("shutting_down",))
        for t in pending:
            t.resolve(
                error=QueueFull(
                    "the server is shutting down", reason="shutting_down"
                )
            )
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=drain_s)
        self._pool.shutdown()

    # -- dispatcher ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return
                first_arrival = self._queue[0].enqueued
            # coalescing window, measured from the FIRST waiter's arrival so
            # a busy queue drains at window cadence instead of re-arming per
            # arrival. Outside the lock: admission must never block on it.
            delay = first_arrival + self.window_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            with self._cond:
                if self._closed:
                    return
                drained, kept = [], []
                while self._queue and len(drained) < self.max_batch:
                    drained.append(self._queue.popleft())
                # non-batchable tickets never consume batch slots
                for t in list(drained):
                    if not t.batchable():
                        drained.remove(t)
                        kept.append(t)
            self._dispatch(drained, kept)

    def _dispatch(self, batchable: List[Ticket], solos: List[Ticket]) -> None:
        now = time.monotonic()
        ready: List[Ticket] = []
        for t in batchable + solos:
            t.queue_s = now - t.enqueued
            if t.expired_in_queue():
                with RECORDER.lock:
                    self.shed.inc(("deadline",))
                    self.queue_wait.observe(t.queue_s, ())
                t.resolve(
                    error=DeadlineExceeded(
                        "request deadline expired while queued "
                        f"(waited {t.queue_s:.3f}s)",
                        phase="queue",
                    )
                )
            else:
                ready.append(t)
        batchable = [t for t in batchable if t in ready]
        solos = [t for t in solos if t in ready]
        # a ticket whose deadline is ALREADY dead (pre-expired at admission
        # — kept for the legacy phase contract) must not ride a batch: the
        # batch installs no deadline scope, so only the solo path can raise
        # its typed 504 at the first phase boundary
        dead = [t for t in batchable if t.deadline is not None and t.deadline.expired()]
        if dead:
            batchable = [t for t in batchable if t not in dead]
            solos = solos + dead
        with RECORDER.lock:
            for t in ready:
                self.queue_wait.observe(t.queue_s, ())
        for t in solos:
            self._pool.submit(self._run_solo, t)
        if len(batchable) == 1:
            # a batch of one is just overhead: the solo path keeps the full
            # engine ladder (megakernel included) and per-phase span tree
            self._pool.submit(self._run_solo, batchable[0])
        elif batchable:
            # INLINE, not pooled: one batch in flight at a time (groups
            # would only serialize on the base-entry lock anyway), so new
            # arrivals accumulate in the queue while this batch runs and
            # the next drain folds them into one bigger batch — batch size
            # adapts to the service rate under load (the classic serving-
            # system dynamic-batching loop)
            self._run_group(batchable)

    def _run_solo(self, ticket: Ticket) -> None:
        t0 = time.monotonic()
        try:
            self.solo_fn(ticket)
        except BaseException as e:  # the backstop of last resort: the
            # error is transported to the waiting client, not dropped
            log.warning("solo executor raised %s: %s", type(e).__name__, e)
            if not ticket.done.is_set():
                ticket.resolve(error=e)
        finally:
            self._note_service(time.monotonic() - t0)
        if not ticket.done.is_set():
            ticket.resolve(
                error=RuntimeError("solo executor returned without resolving")
            )

    def _run_group(self, tickets: List[Ticket]) -> None:
        t0 = time.monotonic()
        # recorded at batch START (size is known upfront): a client whose
        # ticket just resolved must already see the batch in /metrics —
        # recording after resolution races every scrape-after-response
        with RECORDER.lock:
            self.batches_total += 1
            self.batch_sizes.observe(float(len(tickets)), ())
        try:
            self.batch_fn(tickets)
        except BaseException as e:
            # transported to every waiting client as a typed error
            log.warning("batch executor raised %s: %s", type(e).__name__, e)
            for t in tickets:
                if not t.done.is_set():
                    t.resolve(error=e)
        finally:
            self._note_service(time.monotonic() - t0)
        for t in tickets:
            if not t.done.is_set():
                t.resolve(
                    error=RuntimeError("batch executor returned without resolving")
                )

    def _note_service(self, seconds: float) -> None:
        with RECORDER.lock:
            self.ewma_service_s = 0.8 * self.ewma_service_s + 0.2 * max(
                0.001, seconds
            )

    # -- /metrics -----------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        lines = list(family_header("simon_admission_queue_depth"))
        lines.append(f"simon_admission_queue_depth {self.depth()}")
        with RECORDER.lock:
            lines += family_header("simon_batches_total")
            lines.append(f"simon_batches_total {self.batches_total}")
            shed = self.shed.render_lines()
            if not shed:
                # conformance: the family must exist from the first scrape,
                # not only after the first shed
                shed = family_header("simon_shed_total")
            lines += shed
            lines += self.batch_sizes.render_lines()
            lines += self.queue_wait.render_lines()
        return lines
