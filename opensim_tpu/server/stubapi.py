"""Canned stub apiserver speaking list + watch — the chaos harness's fake
cluster (tests/test_watch.py, ``make twin-smoke``).

Just enough of the kube API machinery to prove the live twin's failure
surface deterministically, with no kubernetes package and no real cluster:

- ``GET <path>?resourceVersion=0`` — ``kind: List`` JSON with a list-level
  ``metadata.resourceVersion`` (a process-global counter, monotonically
  bumped by every mutation, like etcd's revision);
- ``GET <path>?watch=1&resourceVersion=<rv>`` — a line-delimited JSON event
  stream (``{"type": "ADDED"|"MODIFIED"|"DELETED"|"BOOKMARK", "object":
  …}``), replaying retained events past ``rv`` and then following live
  mutations, with BOOKMARK keepalives while idle;
- **410 Gone** — :meth:`StubApiServer.compact` discards the retained event
  log (etcd compaction); a watch asking for an rv behind the compaction
  floor gets the mid-stream ``ERROR`` event with ``code: 410``;
- **server-side drops** — :meth:`StubApiServer.force_disconnect` severs
  every open watch connection (LB idle reset, apiserver rolling restart);
- **RBAC shaping** — :attr:`StubApiServer.forbidden_paths` returns 403 for
  chosen endpoints (minimal-RBAC clusters).

Mutations (:meth:`upsert` / :meth:`delete`) assign object resourceVersions
and notify watchers; :meth:`kubeconfig` writes a bearer-token kubeconfig
pointing at the server, so the whole stdlib REST + watch ladder runs
end-to-end against it.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple


def _key(obj: dict) -> Tuple[str, str]:
    meta = obj.get("metadata") or {}
    return (str(meta.get("namespace") or ""), str(meta.get("name") or ""))


class StubApiServer:
    def __init__(self, bookmark_interval_s: float = 0.2) -> None:
        self.bookmark_interval_s = bookmark_interval_s
        self._cond = threading.Condition()
        self._rv = 1000
        self._stores: Dict[str, "dict[Tuple[str, str], dict]"] = {}
        self._events: List[Tuple[int, str, str, dict]] = []  # (rv, path, type, obj)
        self._compacted_rv = 0
        self._disconnect_epoch = 0
        self.forbidden_paths: set = set()
        #: every GET as (path, {param: [values]}) — tests assert on the
        #: query contract (resourceVersion=0 lists, watch resumption rvs)
        self.requests_seen: List[Tuple[str, dict]] = []
        self._httpd: Optional[ThreadingHTTPServer] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "StubApiServer":
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 (stdlib name)
                pass

            def do_GET(self):  # noqa: N802
                stub._handle(self)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    def stop(self) -> None:
        self.force_disconnect()
        if self._httpd is not None:
            self._httpd.shutdown()

    @property
    def url(self) -> str:
        assert self._httpd is not None, "call start() first"
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def kubeconfig(self, dirpath: str) -> str:
        """Write a bearer-token kubeconfig pointing at this stub; returns
        its path."""
        import os

        path = os.path.join(str(dirpath), "stub-kubeconfig")
        with open(path, "w") as f:
            f.write(
                "apiVersion: v1\nkind: Config\ncurrent-context: stub\n"
                "contexts:\n  - name: stub\n    context: {cluster: stub, user: stub}\n"
                f"clusters:\n  - name: stub\n    cluster: {{server: '{self.url}'}}\n"
                "users:\n  - name: stub\n    user: {token: stub-token}\n"
            )
        return path

    # -- mutation API --------------------------------------------------------

    def rv(self) -> int:
        with self._cond:
            return self._rv

    def seed(self, path: str, objs: List[dict]) -> None:
        """Install initial objects WITHOUT emitting watch events (they
        predate every watcher, like objects created before the server)."""
        with self._cond:
            store = self._stores.setdefault(path, {})
            for obj in objs:
                self._rv += 1
                obj = json.loads(json.dumps(obj))  # private copy
                obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
                store[_key(obj)] = obj

    def upsert(self, path: str, obj: dict, ev_type: Optional[str] = None) -> int:
        """Create/replace an object; emits ADDED or MODIFIED (or a forced
        ``ev_type`` — chaos tests use this to send duplicates and other
        malformed sequences). Returns the assigned resourceVersion."""
        with self._cond:
            store = self._stores.setdefault(path, {})
            k = _key(obj)
            self._rv += 1
            obj = json.loads(json.dumps(obj))
            obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
            kind = ev_type or ("MODIFIED" if k in store else "ADDED")
            store[k] = obj
            self._events.append((self._rv, path, kind, obj))
            self._cond.notify_all()
            return self._rv

    def delete(self, path: str, name: str, namespace: str = "default") -> Optional[int]:
        with self._cond:
            store = self._stores.setdefault(path, {})
            obj = store.pop((namespace, name), None)
            if obj is None:
                return None
            self._rv += 1
            obj = json.loads(json.dumps(obj))
            obj["metadata"]["resourceVersion"] = str(self._rv)  # final rv
            self._events.append((self._rv, path, "DELETED", obj))
            self._cond.notify_all()
            return self._rv

    def compact(self) -> None:
        """Discard the retained event log (etcd compaction): any watch
        resuming from an rv at or behind the floor now gets 410 Gone."""
        with self._cond:
            self._compacted_rv = self._rv
            self._events.clear()
            self._cond.notify_all()

    def force_disconnect(self) -> None:
        """Sever every open watch connection server-side."""
        with self._cond:
            self._disconnect_epoch += 1
            self._cond.notify_all()

    # -- HTTP ----------------------------------------------------------------

    def _handle(self, h: BaseHTTPRequestHandler) -> None:
        path, _, query = h.path.partition("?")
        params = urllib.parse.parse_qs(query)
        with self._cond:
            self.requests_seen.append((path, params))
        if path in self.forbidden_paths:
            self._send_json(h, 403, {"kind": "Status", "code": 403, "reason": "Forbidden"})
            return
        if path not in self._stores:
            self._send_json(h, 404, {"kind": "Status", "code": 404, "reason": "NotFound"})
            return
        if params.get("watch") == ["1"]:
            try:
                rv = int((params.get("resourceVersion") or ["0"])[0] or 0)
            except ValueError:
                rv = 0
            self._serve_watch(h, path, rv)
            return
        with self._cond:
            items = [json.loads(json.dumps(o)) for o in self._stores[path].values()]
            rv_now = self._rv
        self._send_json(
            h, 200,
            {"kind": "List", "metadata": {"resourceVersion": str(rv_now)}, "items": items},
        )

    def _send_json(self, h: BaseHTTPRequestHandler, code: int, body: dict) -> None:
        data = json.dumps(body).encode()
        h.send_response(code)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()
        h.wfile.write(data)

    def _serve_watch(self, h: BaseHTTPRequestHandler, path: str, rv: int) -> None:
        h.send_response(200)
        h.send_header("Content-Type", "application/json")
        h.end_headers()

        def emit(ev: dict) -> bool:
            try:
                h.wfile.write(json.dumps(ev).encode() + b"\n")
                h.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False

        with self._cond:
            epoch = self._disconnect_epoch
            expired = bool(rv) and rv < self._compacted_rv
            floor = self._compacted_rv
        if expired:
            emit(
                {
                    "type": "ERROR",
                    "object": {
                        "kind": "Status", "code": 410, "reason": "Expired",
                        "message": f"too old resource version: {rv} ({floor})",
                    },
                }
            )
            return
        cursor = rv
        while True:
            with self._cond:
                if self._disconnect_epoch != epoch:
                    return  # server-side drop: close the connection
                batch = [
                    (erv, etype, obj)
                    for erv, epath, etype, obj in self._events
                    if epath == path and erv > cursor
                ]
                if not batch:
                    self._cond.wait(self.bookmark_interval_s)
                    if self._disconnect_epoch != epoch:
                        return
                    batch = [
                        (erv, etype, obj)
                        for erv, epath, etype, obj in self._events
                        if epath == path and erv > cursor
                    ]
                    if not batch:
                        # idle: BOOKMARK keepalive carrying the current rv
                        bookmark_rv = self._rv
                        batch = [
                            (
                                cursor,
                                "BOOKMARK",
                                {"kind": "Bookmark",
                                 "metadata": {"resourceVersion": str(bookmark_rv)}},
                            )
                        ]
            for erv, etype, obj in batch:
                if not emit({"type": etype, "object": obj}):
                    return
                cursor = max(cursor, erv)
