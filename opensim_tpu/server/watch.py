"""Self-healing live twin: watch-driven incremental snapshots (ISSUE 6).

The reference simulator is informer-driven end to end (PAPER.md L1: fake
clientset + SharedInformerFactory reacting to object events); until this
module, our server re-listed the whole cluster per TTL window and a
fingerprint flip invalidated whole prepare-cache entries. This module
mirrors the informer architecture over the same transport ladder the
snapshot already uses — the real ``kubernetes`` client when the package is
present, a stdlib chunked-HTTP ``?watch=1`` consumer otherwise — and keeps a
continuously-warm :class:`~..engine.prepcache.PrepareCache` base entry, so a
request pays O(changes since the last event) host-side instead of
O(cluster).

The robustness core is an explicit supervised state machine::

    syncing ──bootstrap ok──▶ live ◀──reconverged── resyncing
                               │ ▲                      ▲
             stream stale/down │ │ traffic resumes      │ relist+rebase
                               ▼ │                      │ (410 Gone, drift)
                            degraded ───────────────────┘

- **Bootstrap** lists every resource through the one shared list code path
  (``snapshot.list_resource``, ``resourceVersion=0``), capturing each list's
  resourceVersion so the watch streams resume from exactly that point.
- **Reflectors** (one thread per watched resource, pods + nodes by default;
  everything else converges via anti-entropy) consume the event stream and
  reconnect with *bounded* full-jitter backoff via ``resilience/retry.py``
  (opensim-lint OSL801 forbids hand-rolled ``while True`` watch loops).
- **410 Gone** — an expired resourceVersion, mid-stream or at connect —
  triggers a clean relist-and-rebase, never a crash loop.
- **Staleness deadline**: no event or BOOKMARK within
  ``OPENSIM_WATCH_STALE_S`` flips the state to ``degraded``; requests served
  from a degraded twin carry the existing ``X-Simon-Snapshot: stale``
  header, exactly like the polling path's stale-serve.
- **Anti-entropy**: every ``OPENSIM_WATCH_RESYNC_S`` the supervisor relists,
  diffs the result against the twin's object set, counts mismatches in
  ``simon_twin_drift_total``, and rebases on any drift — the defense against
  *lost* events (``watch.drop_event`` in the chaos suite), which no stream
  error handler can see.
- **Graceful fallback**: until the twin has synced (or if bootstrap keeps
  failing), ``SimonServer`` serves through the existing polling snapshot
  path — ``--watch`` defaults on without a regression path.

Chaos points (``OPENSIM_FAULTS``, ``resilience/faults.py``):
``watch.disconnect``, ``watch.gone``, ``watch.drop_event``,
``watch.reorder``. Telemetry: ``simon_watch_state{state=}`` one-hot gauge,
``simon_watch_events_total{kind=}``, ``simon_watch_reconnects_total``,
``simon_twin_drift_total``; bootstrap/resync/rebase cycles are traced into
the flight recorder (``/api/debug/requests``, ids ``watch-<op>-<n>``) when
tracing is enabled. See docs/live-twin.md.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import TYPE_CHECKING, Dict, Iterator, List, Optional, Tuple

if TYPE_CHECKING:
    from ..engine.prepcache import PrepareCache

from ..models.objects import ResourceTypes
from ..obs import trace as tracing
from ..obs.fleetobs import FRESHNESS, new_event_id
from ..utils import envknobs
from ..obs.metrics import RECORDER, escape_label_value, family_header
from ..obs.recorder import FLIGHT_RECORDER
from ..resilience import faults
from ..resilience.retry import retry_call
from .snapshot import (
    RESOURCE_BY_FIELD,
    RESOURCES,
    SnapshotFetchError,
    _load_kubeconfig,
    _pod_admissible,
    list_resource,
)

log = logging.getLogger("opensim_tpu.server.watch")

__all__ = [
    "STATES",
    "ClusterTwin",
    "GoneError",
    "KubeWatchSource",
    "RestWatchSource",
    "WatchSupervisor",
    "source_from_kubeconfig",
    "watch_policy",
]

#: the supervisor's states, in the order the one-hot gauge renders them
STATES = ("syncing", "live", "degraded", "resyncing")

#: resources with their own watch stream by default; the rest of the
#: RESOURCES table still enters the twin at bootstrap/anti-entropy time
#: (services/PDBs/etc. change orders of magnitude slower than pods)
DEFAULT_WATCHED = ("pods", "nodes")

_UID = itertools.count(1)


class GoneError(RuntimeError):
    """The watch stream's resourceVersion expired (HTTP 410 / ERROR event
    with code 410): the only recovery is a fresh list and a twin rebase."""


def watch_policy() -> dict:
    """Env-tunable policy knobs, validated like ``snapshot_retry_policy``
    (an unparseable value raises immediately; silently restoring a default
    would mask an operator typo until an incident):

    - ``OPENSIM_WATCH_STALE_S`` (default 30): no event or bookmark for this
      long → the stream is stale and the twin degrades;
    - ``OPENSIM_WATCH_RESYNC_S`` (default 300, 0 disables): anti-entropy
      relist-and-diff interval;
    - ``OPENSIM_WATCH_RECONNECTS`` (default 5): bounded attempts per
      reconnect incident (``retry_call``);
    - ``OPENSIM_WATCH_BACKOFF_S`` (default 0.2): full-jitter backoff base.
    """
    out = {}
    for key, env, default, cast in (
        ("stale_s", "OPENSIM_WATCH_STALE_S", 30.0, float),
        ("resync_s", "OPENSIM_WATCH_RESYNC_S", 300.0, float),
        ("reconnects", "OPENSIM_WATCH_RECONNECTS", 5, int),
        ("backoff_s", "OPENSIM_WATCH_BACKOFF_S", 0.2, float),
    ):
        raw = envknobs.raw(env, str(default))
        try:
            out[key] = cast(raw)
        except ValueError:
            raise ValueError(f"{env} must be {'an integer' if cast is int else 'a number'}") from None
    if out["stale_s"] <= 0:
        raise ValueError("OPENSIM_WATCH_STALE_S must be positive")
    if out["resync_s"] < 0:
        raise ValueError("OPENSIM_WATCH_RESYNC_S must be >= 0 (0 disables)")
    if out["reconnects"] < 1:
        raise ValueError("OPENSIM_WATCH_RECONNECTS must be >= 1")
    if out["backoff_s"] < 0:
        raise ValueError("OPENSIM_WATCH_BACKOFF_S must be >= 0")
    return out


def _obj_key(d: dict) -> Tuple[str, str]:
    meta = d.get("metadata") or {}
    return (str(meta.get("namespace") or ""), str(meta.get("name") or ""))


def _obj_rv(d: dict) -> Optional[int]:
    """Numeric resourceVersion for ordering, None when non-numeric (kube
    documents rvs as opaque; they are numeric in practice, and a
    non-numeric one simply disables the duplicate/reorder guard for that
    object rather than breaking event application)."""
    raw = (d.get("metadata") or {}).get("resourceVersion")
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


# ---------------------------------------------------------------------------
# the twin: an event-sourced mirror of the cluster object set
# ---------------------------------------------------------------------------


class ClusterTwin:
    """The in-memory mirror the watch streams maintain. Object stores are
    insertion-ordered per resource — the same order an apiserver list +
    appended events produces — so a converged twin materializes a cluster
    whose content fingerprint equals a fresh full relist's.

    Event application is **rv-monotonic**: an event whose object
    resourceVersion is not newer than the stored one (duplicate delivery,
    out-of-order stream) is a no-op, and deletions leave a tombstone rv so
    a reordered stale MODIFIED cannot resurrect a deleted object.
    """

    #: retained deletion markers per resource — enough to absorb any
    #: realistic reorder window while bounding steady-state churn memory
    #: (pods on a busy cluster delete forever; the guard only needs to
    #: outlive in-flight stream reordering, not history)
    TOMBSTONE_CAP = 4096

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._stores: Dict[str, "OrderedDict[Tuple[str, str], object]"] = {  # guarded-by: _lock
            spec.field: OrderedDict() for spec in RESOURCES
        }
        self._rvs: Dict[str, Dict[Tuple[str, str], Optional[int]]] = {  # guarded-by: _lock
            spec.field: {} for spec in RESOURCES
        }
        self._tombstones: Dict[str, "OrderedDict[Tuple[str, str], Optional[int]]"] = {  # guarded-by: _lock
            spec.field: OrderedDict() for spec in RESOURCES
        }
        self.generation = 0
        self.synced_fields: set = set()
        self._mat: Optional[ResourceTypes] = None  # guarded-by: _lock
        self._mat_gen = -1  # guarded-by: _lock

    def _bury(self, field: str, k: Tuple[str, str], rv: Optional[int]) -> None:
        tomb = self._tombstones[field]
        tomb[k] = rv
        tomb.move_to_end(k)
        while len(tomb) > self.TOMBSTONE_CAP:
            tomb.popitem(last=False)

    # -- list-side -----------------------------------------------------------

    def rebase(self, field: str, items: List[dict]) -> int:
        """Replace one resource's store wholesale from a fresh list (the
        bootstrap, a 410 recovery, or an anti-entropy rebase). Returns the
        post-rebase generation, captured under the lock — callers that
        label journal records with it must not re-read ``generation``
        unlocked (a concurrent event apply could bump it first)."""
        spec = RESOURCE_BY_FIELD[field]
        with self._lock:
            store: "OrderedDict[Tuple[str, str], object]" = OrderedDict()
            rvs: Dict[Tuple[str, str], Optional[int]] = {}
            for d in items:
                if field == "pods" and not _pod_admissible(d):
                    continue
                k = _obj_key(d)
                store[k] = spec.wrap(d)
                rvs[k] = _obj_rv(d)
            self._stores[field] = store
            self._rvs[field] = rvs
            self._tombstones[field].clear()
            self.synced_fields.add(field)
            self.generation += 1
            return self.generation

    def rebase_all(self, listing: Dict[str, Tuple[List[dict], str]]) -> None:
        for field, (items, _rv) in listing.items():
            self.rebase(field, items)

    def snapshot_raw(
        self, fields: Optional[List[str]] = None
    ) -> Tuple[Dict[str, List[dict]], int]:
        """Raw-dict view of the stores plus the generation it corresponds
        to, captured atomically — the one extraction path checkpoints,
        rebase records, and recovery all share, so their view of "the
        store as raw dicts" can never diverge. ``fields=None`` returns
        every non-empty store; an explicit list returns exactly those
        stores (empty included: a rebase record for a now-empty resource
        is meaningful history)."""
        with self._lock:
            names = list(self._stores) if fields is None else list(fields)
            stores = {
                f: [getattr(o, "raw", None) or {} for o in self._stores[f].values()]
                for f in names
                if fields is not None or self._stores[f]
            }
            return stores, self.generation

    # -- event-side ----------------------------------------------------------

    def apply_event(self, field: str, ev_type: str, obj: dict) -> Optional[tuple]:
        """Apply one watch event; returns the *prep-cache delta* the change
        implies, or None for a no-op:

        - ``("pod_add", Pod)`` / ``("pod_del", (ns, name))`` — expressible
          as an O(changes) base-entry delta;
        - ``("node_add", Node)`` — expressible via ``extend_with_nodes``;
        - ``("rebuild", why)`` — the store changed in a way only a full
          re-prepare can express (modifications, node removals, workload
          object changes).
        """
        spec = RESOURCE_BY_FIELD[field]
        k = _obj_key(obj)
        rv = _obj_rv(obj)
        with self._lock:
            store = self._stores[field]
            rvs = self._rvs[field]
            tomb = self._tombstones[field]
            if rv is not None:
                dead_rv = tomb.get(k)
                if dead_rv is not None and rv <= dead_rv:
                    return None  # stale event for an already-deleted object
            if ev_type == "DELETED":
                if k not in store:
                    return None
                del store[k]
                rvs.pop(k, None)
                self._bury(field, k, rv)
                self.generation += 1
                if field == "pods":
                    return ("pod_del", k)
                return ("rebuild", f"{field} DELETED")
            if ev_type not in ("ADDED", "MODIFIED"):
                return None
            admissible = field != "pods" or _pod_admissible(obj)
            if not admissible:
                # a pod leaving the admissible set (Succeeded/Failed,
                # deletionTimestamp, DaemonSet adoption) IS a deletion as
                # far as the twin is concerned
                if k not in store:
                    return None
                del store[k]
                rvs.pop(k, None)
                self._bury(field, k, rv)
                self.generation += 1
                return ("pod_del", k)
            prev_rv = rvs.get(k)
            existed = k in store
            if existed and rv is not None and prev_rv is not None and rv <= prev_rv:
                return None  # duplicate or reordered stale delivery
            decoded = spec.wrap(obj)
            store[k] = decoded
            rvs[k] = rv
            tomb.pop(k, None)
            self.generation += 1
            if not existed:
                if field == "pods":
                    return ("pod_add", decoded)
                if field == "nodes":
                    return ("node_add", decoded)
                return ("rebuild", f"{field} ADDED")
            return ("rebuild", f"{field} MODIFIED")

    # -- serving-side --------------------------------------------------------

    def materialize(self) -> ResourceTypes:
        """The twin as a ResourceTypes, rebuilt per generation (lists are
        fresh objects per generation; the model objects are shared with the
        prepared stream, whose bind state is restored after every use)."""
        with self._lock:
            if self._mat is not None and self._mat_gen == self.generation:
                return self._mat
            rt = ResourceTypes()
            for spec in RESOURCES:
                getattr(rt, spec.field).extend(self._stores[spec.field].values())
            self._mat = rt
            self._mat_gen = self.generation
            return rt

    def fingerprint(self) -> str:
        """Content fingerprint of the materialized twin — the convergence
        check the tests and ``make twin-smoke`` compare against a fresh
        full relist. Not on the serving path (that keys on generation)."""
        from ..engine.prepcache import fingerprint_cluster

        return fingerprint_cluster(self.materialize())

    def reconcile(
        self,
        listing: Dict[str, Tuple[List[dict], str]],
        per_resource: Optional[Dict[str, int]] = None,
    ) -> int:
        """Anti-entropy: merge a fresh listing into the twin, returning the
        number of genuinely drifted objects repaired. The merge is
        **rv-aware** because the listing races the event streams — between
        the list fetch and this merge, reflectors may legitimately advance
        the twin past the listing. Twin-ahead is NOT drift and is never
        reverted (the stream would not redeliver what a wholesale rebase
        threw away):

        - fresh object unknown to the twin → drift (lost ADDED), unless a
          tombstone proves the twin deleted it at a newer rv;
        - fresh rv newer than the twin's → drift (lost MODIFIED), replace;
          fresh rv older → twin is ahead, keep ours;
        - twin object absent from the listing → drift (lost DELETED),
          remove — unless its rv is newer than the *list-level* rv, which
          means it was created after the list was taken.
        """
        drift = 0
        with self._lock:
            for field, (items, list_rv) in listing.items():
                field_drift0 = drift
                spec = RESOURCE_BY_FIELD[field]
                store = self._stores[field]
                rvs = self._rvs[field]
                tomb = self._tombstones[field]
                try:
                    list_rv_n: Optional[int] = int(list_rv)
                except (TypeError, ValueError):
                    list_rv_n = None
                fresh: Dict[Tuple[str, str], dict] = {}
                for d in items:
                    if field == "pods" and not _pod_admissible(d):
                        continue
                    fresh[_obj_key(d)] = d
                for k, d in fresh.items():
                    rv = _obj_rv(d)
                    if k not in store:
                        dead_rv = tomb.get(k)
                        if dead_rv is not None and rv is not None and rv <= dead_rv:
                            continue  # we deleted it after the list was taken
                        store[k] = spec.wrap(d)
                        rvs[k] = rv
                        tomb.pop(k, None)
                        drift += 1
                    else:
                        mine = rvs.get(k)
                        if rv is not None and (mine is None or rv > mine):
                            store[k] = spec.wrap(d)
                            rvs[k] = rv
                            drift += 1
                for k in [k for k in store if k not in fresh]:
                    mine = rvs.get(k)
                    if mine is not None and list_rv_n is not None and mine > list_rv_n:
                        continue  # created after the list snapshot: twin is ahead
                    del store[k]
                    self._bury(field, k, rvs.pop(k, None))
                    drift += 1
                if per_resource is not None and drift > field_drift0:
                    per_resource[field] = (
                        per_resource.get(field, 0) + drift - field_drift0
                    )
            if drift:
                self.generation += 1
        return drift


# ---------------------------------------------------------------------------
# event sources: real client / stdlib REST / (tests: any object with the
# same three methods)
# ---------------------------------------------------------------------------


class RestWatchSource:
    """Stdlib chunked-HTTP watch consumer — mirrors the snapshot's REST
    fallback: ``GET <path>?watch=1&allowWatchBookmarks=true&resourceVersion=<rv>``
    and one JSON watch event per line. The read timeout doubles as the
    transport half of the staleness deadline: a silent peer (no events, no
    bookmarks) surfaces as a TimeoutError → reconnect."""

    def __init__(
        self,
        kubeconfig: str,
        master: Optional[str] = None,
        read_timeout_s: float = 60.0,
    ) -> None:
        self._server, self._headers, self._ssl = _load_kubeconfig(kubeconfig, master)
        self.read_timeout_s = read_timeout_s

    def list(self, field: str) -> Tuple[List[dict], str]:
        got = list_resource(self._server, self._headers, self._ssl, RESOURCE_BY_FIELD[field])
        return got if got is not None else ([], "")

    def list_all(self) -> Dict[str, Tuple[List[dict], str]]:
        return {spec.field: self.list(spec.field) for spec in RESOURCES}

    def watch(self, field: str, rv: str) -> Iterator[Tuple[str, dict]]:
        spec = RESOURCE_BY_FIELD[field]
        sep = "&" if "?" in spec.path else "?"
        url = f"{self._server}{spec.path}{sep}watch=1&allowWatchBookmarks=true"
        if rv:
            url += f"&resourceVersion={rv}"
        req = urllib.request.Request(url, headers=self._headers)
        try:
            resp = urllib.request.urlopen(req, timeout=self.read_timeout_s, context=self._ssl)
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise GoneError(f"watch {spec.path} from rv {rv}: HTTP 410 Gone") from e
            if e.code >= 500:
                raise SnapshotFetchError(f"watch {spec.path} failed: HTTP {e.code}") from e
            raise RuntimeError(f"watch {spec.path} failed: HTTP {e.code}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise SnapshotFetchError(f"watch {spec.path} connect failed: {e}") from e
        return self._events(resp, spec.path)

    def _events(self, resp, path: str) -> Iterator[Tuple[str, dict]]:
        with resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError as e:
                    raise SnapshotFetchError(f"watch {path}: truncated frame") from e
                ev_type = str(ev.get("type") or "")
                obj = ev.get("object") or {}
                if ev_type == "ERROR":
                    code = obj.get("code")
                    if code == 410:
                        raise GoneError(f"watch {path}: resourceVersion expired (410)")
                    raise SnapshotFetchError(f"watch {path}: ERROR event {obj!r}")
                yield ev_type, obj
        # server closed the stream (apiservers time watches out routinely):
        # the reflector resumes from the last seen rv


class KubeWatchSource:
    """``kubernetes``-client-backed source, used when the package is
    importable (parity with ``cluster_from_kubeconfig``'s client branch).
    Decodes to the same raw-dict wire form the REST source yields."""

    def __init__(self, kubeconfig: str, master: Optional[str] = None) -> None:
        from kubernetes import client, config  # type: ignore

        config.load_kube_config(config_file=kubeconfig)
        core = client.CoreV1Api()
        apps = client.AppsV1Api()
        policy = client.PolicyV1Api() if hasattr(client, "PolicyV1Api") else client.PolicyV1beta1Api()
        storage = client.StorageV1Api()
        self._api = client.ApiClient()
        self._calls = {
            "nodes": core.list_node,
            "pods": core.list_pod_for_all_namespaces,
            "daemon_sets": apps.list_daemon_set_for_all_namespaces,
            "pdbs": policy.list_pod_disruption_budget_for_all_namespaces,
            "services": core.list_service_for_all_namespaces,
            "storage_classes": storage.list_storage_class,
            "pvcs": core.list_persistent_volume_claim_for_all_namespaces,
            "config_maps": core.list_config_map_for_all_namespaces,
        }

    def list(self, field: str) -> Tuple[List[dict], str]:
        resp = self._calls[field](resource_version="0")
        items = [self._api.sanitize_for_serialization(o) for o in resp.items]
        meta = getattr(resp, "metadata", None)
        rv = str(getattr(meta, "resource_version", "") or "")
        return items, rv

    def list_all(self) -> Dict[str, Tuple[List[dict], str]]:
        return {spec.field: self.list(spec.field) for spec in RESOURCES}

    def watch(self, field: str, rv: str) -> Iterator[Tuple[str, dict]]:
        from kubernetes import watch as kwatch  # type: ignore

        stream = kwatch.Watch().stream(
            self._calls[field],
            resource_version=rv or None,
            allow_watch_bookmarks=True,
        )
        try:
            for ev in stream:
                yield str(ev.get("type") or ""), dict(ev.get("raw_object") or {})
        except Exception as e:
            if getattr(e, "status", None) == 410:
                raise GoneError(f"watch {field}: resourceVersion expired (410)") from e
            raise


def source_from_kubeconfig(kubeconfig: str, master: Optional[str] = None, read_timeout_s: float = 60.0):
    """The same client-or-stdlib ladder ``cluster_from_kubeconfig`` walks."""
    try:
        import kubernetes  # type: ignore # noqa: F401
    except ImportError:
        return RestWatchSource(kubeconfig, master, read_timeout_s=read_timeout_s)
    return KubeWatchSource(kubeconfig, master)


# ---------------------------------------------------------------------------
# reflectors: one supervised list+watch lifecycle per watched resource
# ---------------------------------------------------------------------------


class _Reflector(threading.Thread):
    """client-go-reflector analogue: resume the watch from the last seen
    resourceVersion across reconnects; only a 410 (or a first start) pays a
    relist. Every (re)connect goes through ``retry_call`` — bounded
    attempts, full-jitter backoff — and an exhausted budget degrades the
    twin instead of crash-looping (the supervisor keeps a slow heartbeat
    that re-enters the cycle, and anti-entropy still converges the data)."""

    def __init__(self, sup: "WatchSupervisor", field: str) -> None:
        super().__init__(name=f"simon-watch-{field}", daemon=True)
        self.sup = sup
        self.field = field
        self.rv: str = ""  # "" → next cycle lists first
        self._delivered = 0  # items the current stream cycle yielded

    def run(self) -> None:
        connected_once = False
        last_cycle_delivered = True
        while not self.sup._stop.is_set():
            try:
                if not self.rv:
                    items, rv = retry_call(
                        lambda: self.sup.source.list(self.field),
                        attempts=self.sup.policy["reconnects"],
                        base_delay=self.sup.policy["backoff_s"],
                        retry_on=(SnapshotFetchError, TimeoutError),
                        trace_name="watch.relist.retry",
                    )
                    self.rv = rv
                    self.sup.on_relist(self.field, items, rv=rv)
                    last_cycle_delivered = True  # a relist IS fresh data
                stream = retry_call(
                    lambda: self.sup.source.watch(self.field, self.rv),
                    attempts=self.sup.policy["reconnects"],
                    base_delay=self.sup.policy["backoff_s"],
                    retry_on=(SnapshotFetchError, TimeoutError),
                    trace_name="watch.reconnect.retry",
                )
                if connected_once:
                    self.sup.note_reconnect(self.field)
                connected_once = True
                # a successful connect only resets the staleness deadline
                # when the PREVIOUS cycle actually delivered something: a
                # connectable-but-silent endpoint (half-dead LB that 200s
                # the watch and then sends nothing) must not stay "live"
                # by reconnecting once per read timeout
                if last_cycle_delivered:
                    self.sup.note_traffic(self.field)
                self._delivered = 0
                try:
                    self._consume(stream)
                    # clean EOF: apiservers time watches out routinely —
                    # resume immediately from the last seen rv
                except GoneError:
                    raise
                except Exception as e:
                    # mid-stream drop: resume from the last rv; the very
                    # next connect above is itself bounded via retry_call
                    log.info(
                        "watch[%s]: stream dropped (%s: %s); reconnecting",
                        self.field, type(e).__name__, e,
                    )
                last_cycle_delivered = self._delivered > 0
            except GoneError as e:
                log.warning("watch[%s]: %s; relisting and rebasing", self.field, e)
                self.sup.note_gone(self.field)
                self.rv = ""  # forces the relist+rebase on the next cycle
            except Exception as e:
                log.warning(
                    "watch[%s]: stream down after %d bounded attempt(s) (%s: %s)",
                    self.field, self.sup.policy["reconnects"], type(e).__name__, e,
                )
                self.sup.note_stream_down(self.field, e)
                # slow heartbeat before re-entering the bounded cycle: the
                # twin is already degraded; pace recovery at the staleness
                # deadline rather than hammering a down apiserver
                self.sup._stop.wait(self.sup.policy["stale_s"])

    def _consume(self, stream: Iterator[Tuple[str, dict]]) -> None:
        for ev_type, obj in stream:
            if self.sup._stop.is_set():
                return
            # chaos: a dropped connection mid-stream (exception ⇒ the
            # reconnect path), or an injected 410 (⇒ relist-and-rebase)
            faults.fault_point("watch.disconnect")
            try:
                faults.fault_point("watch.gone")
            except Exception as e:
                raise GoneError("injected resourceVersion expiry") from e
            self._delivered += 1
            self.sup.note_traffic(self.field)
            rv = _obj_rv(obj)
            if rv is not None:
                self.rv = str(rv)
            if ev_type == "BOOKMARK":
                # progress marker only: advances rv, feeds the staleness
                # deadline, carries no object payload
                self.sup.count_event("BOOKMARK", self.field)
                continue
            self.sup.dispatch(self.field, ev_type, obj)


# ---------------------------------------------------------------------------
# supervisor: state machine + prep maintenance + anti-entropy
# ---------------------------------------------------------------------------


class WatchSupervisor:
    """Owns the twin, the reflector threads, the state machine, and the
    always-warm prep-cache base entry. The REST server asks one question —
    :meth:`serving_snapshot` — and gets either the twin (with its staleness
    verdict) or None (not synced → caller falls back to polling)."""

    def __init__(
        self,
        source,
        prep_cache: Optional["PrepareCache"] = None,
        watched: Tuple[str, ...] = DEFAULT_WATCHED,
        policy: Optional[dict] = None,
        journal=None,
    ) -> None:
        unknown = [f for f in watched if f not in RESOURCE_BY_FIELD]
        if unknown:
            raise ValueError(f"unknown watch resource(s) {unknown}; known: {sorted(RESOURCE_BY_FIELD)}")
        self.source = source
        self.prep_cache = prep_cache
        # watch-event journal (ISSUE 11, server/journal.py): when attached,
        # every ACCEPTED event / list-shaped rebase is recorded off the
        # dispatch path, and start() restores the twin from the newest
        # checkpoint + suffix replay instead of a cold relist
        self.journal = None
        # capacity observatory (ISSUE 9, obs/capacity.py): when attached,
        # the supervisor bootstraps it at sync/rebase and feeds it every
        # ACCEPTED event — the O(1) aggregate update rides the same
        # dispatch the prep delta does
        self.capacity = None
        self.watched = tuple(watched)
        self.policy = policy or watch_policy()
        self.twin = ClusterTwin()
        self.key_prefix = f"twin|{next(_UID)}|"
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reflectors: List[_Reflector] = []
        self._state_lock = threading.Lock()
        self._state = "syncing"  # guarded-by: _state_lock
        # _down/_traffic are deliberately unguarded: set.add/discard and
        # per-key dict stores are atomic under the GIL, the readers
        # (_recompute_state, staleness checks) tolerate a stale view for
        # one tick, and taking a lock on every received event would put a
        # hot-path wait in front of twin application for a telemetry hint
        self._down: set = set()
        self._traffic: Dict[str, float] = {}
        self._maint_lock = threading.Lock()
        self._pending: List[tuple] = []  # guarded-by: _maint_lock
        self._prep_gen = -1  # guarded-by: _maint_lock
        # serializes flushers only (supervisor loop vs request threads) and
        # is held across the delta re-encode — a first JIT compile can take
        # seconds, and waiting here IS the warm-path contract (the request
        # wants the folded base); _maint_lock is never held that long, so
        # reflector dispatch keeps flowing
        self._flush_lock = threading.Lock()  # lockwatch: hold-exempt — holds across delta re-encode by design
        self._boot_rvs: Dict[str, str] = {}
        #: in-memory state to adopt at start() instead of journal recovery
        #: (the HA standby's pre-warmed twin; see preload_state)
        self._preloaded = None
        # serializes event application against the anti-entropy merge (the
        # reflector threads vs the supervisor thread) and guards the
        # per-field reorder-fault holding slots
        self._dispatch_lock = threading.Lock()
        self._held: Dict[str, Tuple[str, dict]] = {}  # guarded-by: _dispatch_lock
        self._trace_seq = itertools.count(1)
        # counters (rendered under the one metrics lock, RECORDER.lock).
        # events and drift carry a {resource=} label (ISSUE 7 satellite) so
        # drift is attributable — pods churn and nodes churn are different
        # operational stories; the unlabeled totals stay as attributes for
        # programmatic callers
        # (kind, resource)
        self.events_total: Dict[Tuple[str, str], int] = {}  # guarded-by: RECORDER.lock
        self.reconnects_total = 0  # guarded-by: RECORDER.lock
        self.relists_total = 0  # guarded-by: RECORDER.lock
        self.gone_total = 0  # guarded-by: RECORDER.lock
        self.drift_total = 0  # guarded-by: RECORDER.lock
        self.drift_by_resource: Dict[str, int] = {}  # guarded-by: RECORDER.lock
        self.resyncs_total = 0  # guarded-by: RECORDER.lock
        if journal is not None:
            self.attach_journal(journal)

    # -- journal (ISSUE 11, server/journal.py) -------------------------------

    def attach_journal(self, journal) -> None:
        """Wire a :class:`~.journal.Journal`: the supervisor records every
        accepted event/rebase into it and hands it the checkpoint source
        (twin object references captured under the twin lock; the journal's
        writer thread serializes them outside it)."""
        self.journal = journal
        journal.checkpoint_source = self._journal_snapshot

    def _journal_snapshot(self) -> Optional[tuple]:
        """(stores objrefs, generation, timeline dicts) for a cadence
        checkpoint — called from the journal writer thread only."""
        if not self._synced.is_set():
            return None
        with self.twin._lock:
            stores = {
                field: list(store.values())
                for field, store in self.twin._stores.items()
                if store
            }
            gen = self.twin.generation
        timeline = []
        if self.capacity is not None:
            timeline = [s.to_dict() for s in self.capacity.timeline.snapshot()]
        return stores, gen, timeline

    def _checkpoint_now(self, why: str) -> None:
        """Journal an explicit full-snapshot checkpoint of the twin — the
        bootstrap anchor and the post-recovery re-anchor. Raw dict
        references are captured under the twin lock; the journal's writer
        thread serializes them off this path."""
        if self.journal is None:
            return
        stores, gen = self.twin.snapshot_raw()
        timeline = []
        if self.capacity is not None:
            timeline = [s.to_dict() for s in self.capacity.timeline.snapshot()]
        self.journal.record_checkpoint(
            stores, gen, resume_rvs=self._boot_rvs, timeline=timeline, why=why
        )

    def preload_state(self, state) -> None:
        """Hand the supervisor an in-memory :class:`~.journal.RecoveredState`
        to adopt INSTEAD of recovering from its journal at start() — the HA
        standby's takeover path (server/fleet.py): the standby tailed the
        old owner's journal onto its own twin, and the new supervisor must
        start from that pre-warmed state (zero relists, reflectors resuming
        at the recorded rvs), not from a disk replay of history it already
        holds."""
        self._preloaded = state

    def _restore_from_journal(self) -> bool:
        """Rebuild the twin from the journal's newest checkpoint + suffix
        replay, then resume serving WITHOUT a relist: the reflectors pick
        up from the restored per-resource rvs (a too-old rv heals through
        the normal 410 relist-and-rebase path), and anti-entropy repairs —
        journaled as rebase records — cover whatever the crash lost."""
        state = self.journal.recover()
        if state is None:
            return False
        return self._adopt_state(state, "journal-restore", "recovered")

    def _adopt_state(self, state, span: str, why: str) -> bool:
        """Seed the twin/capacity/resume-rvs from a recovered (or
        standby-tailed) state and go live — shared by journal recovery and
        the HA takeover."""
        with self._traced(span):
            with self._maint_lock:
                for field, items in state.stores.items():
                    if field in RESOURCE_BY_FIELD:
                        self.twin.rebase(field, items)
                with self.twin._lock:
                    self.twin.generation = max(self.twin.generation, state.generation)
                self._pending.clear()
                self._prep_gen = self.twin.generation
            if self.capacity is not None and state.timeline:
                try:
                    from ..obs.timeline import Sample

                    self.capacity.timeline.restore(
                        [Sample.from_dict(d) for d in state.timeline]
                    )
                except Exception as e:
                    log.warning(
                        "capacity timeline restore failed: %s: %s",
                        type(e).__name__, e,
                    )
            self._capacity_rebase()
            self._boot_rvs = {
                f: rv for f, rv in state.resume_rvs.items() if f in RESOURCE_BY_FIELD
            }
            for field in self.watched:
                self.note_traffic(field)
            self._set_state("live")
            self._synced.set()
            # re-anchor: the next crash must not have to replay this
            # suffix again (and a restore-time drift repair now has a
            # checkpoint to be a suffix OF)
            self._checkpoint_now(why)
            log.info(
                "live twin %s: generation %d "
                "(checkpoint %d + %d replayed record(s))",
                why, state.generation, state.checkpoint_generation,
                state.records_replayed,
            )
            return True

    # -- lifecycle -----------------------------------------------------------

    def start(self, wait_s: Optional[float] = None) -> bool:
        """Spawn the supervisor thread. With ``wait_s``, block up to that
        long for the first sync and return whether it completed (the CLI's
        ``--watch on`` uses this to fail loudly)."""
        self._thread = threading.Thread(target=self._run, name="simon-watch-supervisor", daemon=True)
        self._thread.start()
        if wait_s is not None:
            return self._synced.wait(wait_s)
        return True

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self.journal is not None:
            # clean stop: the accepted-event history on disk is complete up
            # to the last dispatched event (graceful shutdown's fsync
            # barrier; the journal object itself is closed by its owner)
            self.journal.flush(timeout=10.0)

    def _run(self) -> None:
        if self._preloaded is not None and not self._synced.is_set():
            state, self._preloaded = self._preloaded, None
            try:
                self._adopt_state(state, "takeover-adopt", "takeover")
            except Exception as e:
                # a failed adopt degrades to the journal/bootstrap ladder
                # below — the takeover gets slower, never stuck
                log.warning(
                    "takeover state adopt failed (%s: %s); falling back to "
                    "journal recovery / relist", type(e).__name__, e,
                )
        if self.journal is not None and not self._synced.is_set():
            try:
                self._restore_from_journal()  # sets _synced on success
            except Exception as e:
                # recovery is best-effort by contract: ANY failure here
                # degrades to the cold bootstrap below, never a crash loop
                log.warning(
                    "journal restore failed (%s: %s); falling back to a "
                    "full relist", type(e).__name__, e,
                )
        while not self._stop.is_set() and not self._synced.is_set():
            if self._bootstrap():
                break
            # bootstrap keeps failing: the server is already serving via
            # the polling fallback; re-attempt at the resync cadence
            self._stop.wait(self.policy["resync_s"] or self.policy["stale_s"])
        if self._stop.is_set():
            return
        for field in self.watched:
            r = _Reflector(self, field)
            # resume each stream from the bootstrap list's resourceVersion:
            # the whole point of capturing it is that the first watch cycle
            # needs no second relist
            r.rv = self._boot_rvs.get(field, "")
            self._reflectors.append(r)
            r.start()
        tick = min(0.5, self.policy["stale_s"] / 4.0)
        next_resync = time.monotonic() + (self.policy["resync_s"] or float("inf"))
        while not self._stop.is_set():
            self._stop.wait(tick)
            if self._stop.is_set():
                return
            self._recompute_state()
            try:
                self.flush_pending()
            except Exception as e:
                # maintenance must never kill the supervisor; the request
                # path rebuilds from scratch when the warm entry is missing
                log.warning("twin prep maintenance failed: %s: %s", type(e).__name__, e)
            if self.capacity is not None:
                try:
                    # generation-keyed and memoized: an idle tick is a dict
                    # lookup, a busy one is one O(nodes) fold feeding the
                    # capacity timeline (obs/timeline.py)
                    self.capacity.sample()
                except Exception as e:
                    log.warning("capacity sampling failed: %s: %s", type(e).__name__, e)
            if time.monotonic() >= next_resync:
                next_resync = time.monotonic() + self.policy["resync_s"]
                try:
                    self.anti_entropy()
                except Exception as e:
                    log.warning("anti-entropy pass failed: %s: %s", type(e).__name__, e)

    def _bootstrap(self) -> bool:
        with self._traced("bootstrap"):
            try:
                listing = retry_call(
                    self.source.list_all,
                    attempts=self.policy["reconnects"],
                    base_delay=self.policy["backoff_s"],
                    retry_on=(SnapshotFetchError, TimeoutError),
                    trace_name="watch.bootstrap.retry",
                )
            except Exception as e:
                log.warning(
                    "watch bootstrap failed (%s: %s); serving stays on the "
                    "polling snapshot path until the twin syncs",
                    type(e).__name__, e,
                )
                return False
            with self._maint_lock:
                self.twin.rebase_all(listing)
                self._pending.clear()
                self._prep_gen = self.twin.generation
            self._capacity_rebase()
            self._boot_rvs = {f: rv for f, (_items, rv) in listing.items()}
            for field in self.watched:
                self.note_traffic(field)
            self._set_state("live")
            self._synced.set()
            # the journal's first record is a complete history prefix: a
            # crash at ANY later point recovers from this checkpoint plus
            # the accepted-event suffix
            self._checkpoint_now("bootstrap")
            log.info(
                "live twin synced: %s",
                ", ".join(f"{len(items)} {f}" for f, (items, _rv) in listing.items() if items),
            )
            return True

    # -- event path (reflector threads) --------------------------------------

    def count_event(self, kind: str, resource: str = "") -> None:
        with RECORDER.lock:
            key = (kind, resource)
            self.events_total[key] = self.events_total.get(key, 0) + 1

    def dispatch(self, field: str, ev_type: str, obj: dict) -> None:
        t0 = time.monotonic()  # event receipt: the watch-apply clock starts
        self.count_event(
            ev_type if ev_type in ("ADDED", "MODIFIED", "DELETED") else "OTHER", field
        )
        try:
            faults.fault_point("watch.drop_event")
        except Exception as e:
            # the event is LOST — precisely the failure only the
            # anti-entropy pass can repair (the twin drifts silently)
            log.debug("watch[%s]: injected event loss (%s): %s dropped", field, e, ev_type)
            return
        with self._dispatch_lock:
            try:
                faults.fault_point("watch.reorder")
            except Exception as e:
                # hold this event back; it is delivered AFTER the stream's
                # next event (per-field slot: streams must not cross)
                log.debug("watch[%s]: injected reorder (%s): %s held back", field, e, ev_type)
                self._held[field] = (ev_type, obj)
                return
            held = self._held.pop(field, None)
            self._apply(field, ev_type, obj)
            if held is not None:
                self._apply(field, *held)
        # watch-pipeline latency (ISSUE 9 satellite): receipt → twin
        # applied, for every event that reached application (dropped/held
        # events never complete the pipeline on this call)
        RECORDER.observe_watch_apply(time.monotonic() - t0)

    def _apply(self, field: str, ev_type: str, obj: dict) -> None:
        # generation is captured atomically with the apply (the twin lock
        # is reentrant): a rebase racing in from another reflector's 410
        # recovery must not mislabel this event's journal record — replay's
        # --at-generation cut points depend on the label being the
        # generation the event actually produced
        with self.twin._lock:
            change = self.twin.apply_event(field, ev_type, obj)
            gen = self.twin.generation
        if change is None:
            return
        # acceptance stamp (ISSUE 20): the event id rides the journal
        # record and, once gen is published, the control-block payload —
        # the anchor of the stitched fleet trace and the t=0 of every
        # simon_fleet_freshness_seconds stage
        eid, ts = new_event_id(), time.time()
        FRESHNESS.event_accepted(eid, gen, ts)
        if self.journal is not None:
            # ACCEPTED events only (rv-monotonic no-ops never reach here):
            # an O(1) bounded-queue enqueue, never I/O — the journal's
            # writer thread drains it off this path, so dispatch hold
            # times stay tsan-clean
            self.journal.record_event(field, ev_type, obj, gen, eid=eid, ts=ts)
        if self.capacity is not None:
            try:
                self.capacity.on_twin_change(field, ev_type, obj, change, gen)
            except Exception as e:
                # observability must never break event application; the
                # next bootstrap (rebase/anti-entropy) self-heals the view
                log.warning(
                    "capacity accounting failed (%s: %s); view may lag until "
                    "the next rebase", type(e).__name__, e,
                )
        with self._maint_lock:
            self._pending.append(change)

    # -- freshness / state ---------------------------------------------------

    def note_traffic(self, field: str) -> None:
        self._traffic[field] = time.monotonic()

    def note_reconnect(self, field: str) -> None:
        with RECORDER.lock:
            self.reconnects_total += 1
        self._down.discard(field)
        self._recompute_state()

    def note_stream_down(self, field: str, exc: BaseException) -> None:
        self._down.add(field)
        self._recompute_state()

    def note_gone(self, field: str) -> None:
        with RECORDER.lock:
            self.gone_total += 1

    def on_relist(self, field: str, items: List[dict], rv: str = "") -> None:
        """A reflector relisted (first start or 410 recovery): rebase that
        resource and drop the warm prep lineage — the jump is unbounded."""
        with RECORDER.lock:
            self.relists_total += 1
        with self._traced("rebase"):
            with self._maint_lock:
                gen = self.twin.rebase(field, items)
                self._pending.clear()
                self._invalidate_prep()
                self._prep_gen = gen
            if self.journal is not None:
                # the list-shaped jump is part of the history: replay
                # applies it as the same wholesale store replacement. The
                # rebase's own generation labels the record — re-reading
                # ``twin.generation`` here would race concurrent event
                # applies on other reflector threads
                self.journal.record_rebase(field, items, gen, rv=rv, why="relist")
            self._capacity_rebase()
        self.note_traffic(field)  # a fresh list is proof of liveness
        self._down.discard(field)
        self._recompute_state()

    def _recompute_state(self) -> None:
        if not self._synced.is_set():
            self._set_state("syncing")
            return
        now = time.monotonic()
        stale = [
            f
            for f in self.watched
            if now - self._traffic.get(f, 0.0) > self.policy["stale_s"]
        ]
        if self._down or stale:
            self._set_state("degraded")
        elif self.state() != "resyncing":
            self._set_state("live")

    def _set_state(self, new: str) -> None:
        assert new in STATES, new
        with self._state_lock:
            old, self._state = self._state, new
        if old != new:
            log.info("live twin: %s -> %s", old, new)
            tracing.event("watch.state", frm=old, to=new)

    def state(self) -> str:
        with self._state_lock:
            return self._state

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def is_stale(self) -> bool:
        """Should a request served from the twin be tagged stale? True in
        every state but ``live`` — degraded (streams down/silent) and
        resyncing (mid-rebase) both mean 'possibly behind the cluster'."""
        return self.state() != "live"

    # -- serving interface (request threads) ---------------------------------

    def serving_snapshot(self) -> Optional[Tuple[ResourceTypes, str, bool]]:
        """(cluster, cache key, stale?) from the twin, or None before the
        first sync (caller falls back to the polling snapshot path)."""
        if not self._synced.is_set():
            return None
        self.flush_pending()
        with self.twin._lock:
            # cluster and key must be read atomically: a concurrent event
            # bumping the generation between the two reads would cache this
            # cluster's prepare under the NEXT generation's key
            cluster = self.twin.materialize()
            key = f"{self.key_prefix}{self.twin.generation}"
        return cluster, key, self.is_stale()

    # -- prep-cache maintenance ---------------------------------------------

    def _invalidate_prep(self) -> None:
        if self.prep_cache is not None:
            self.prep_cache.invalidate(self.key_prefix)

    def _capacity_rebase(self) -> None:
        """Rebuild the capacity view from the twin after a list-shaped jump
        (bootstrap, 410 rebase, anti-entropy repair) — the same moments the
        prep lineage is dropped, and already O(cluster) paths."""
        if self.capacity is None:
            return
        try:
            with self.twin._lock:
                cluster = self.twin.materialize()
                gen = self.twin.generation
            self.capacity.claim_event_fed()  # the supervisor owns the view now
            self.capacity.bootstrap(cluster, gen)
        except Exception as e:
            log.warning("capacity rebase failed: %s: %s", type(e).__name__, e)

    def flush_pending(self) -> None:
        """Fold buffered twin changes into the warm prep-cache base entry —
        the O(changes) hand-off that makes the next request skip the
        O(cluster) prepare. Pod ADDED → arena-fork insert at the bare-region
        end; pod DELETED → valid-mask flip; node ADDED → node-arena extend
        with DaemonSet splice; anything else → drop the lineage (next
        request re-prepares once)."""
        if self.prep_cache is None:
            with self._maint_lock:
                self._pending.clear()
                self._prep_gen = self.twin.generation
            return
        from ..engine import prepcache

        # the re-encode must NOT run under _maint_lock: reflector dispatch
        # appends under it (while holding the dispatch lock), so holding it
        # across a multi-second first compile stalls the whole event
        # pipeline — `make tsan` catches exactly that as a hold outlier.
        # Flushers serialize on _flush_lock; the pending swap and the
        # publish are each a short _maint_lock critical section, and the
        # publish re-checks the lineage generation so a concurrent
        # relist/drift/bootstrap reset wins over a stale delta.
        with self._flush_lock:
            with self._maint_lock:
                gen_now = self.twin.generation
                old_gen = self._prep_gen
                if gen_now == old_gen and not self._pending:
                    return
                changes, self._pending = self._pending, []
            added: List[object] = []
            removed: set = set()
            nodes_added: List[object] = []
            rebuild: Optional[str] = None
            for change in changes:
                kind = change[0]
                if kind == "pod_add":
                    added.append(change[1])
                elif kind == "pod_del":
                    k = change[1]
                    before = len(added)
                    added = [
                        p
                        for p in added
                        if (p.metadata.namespace, p.metadata.name) != k
                    ]
                    if len(added) == before:
                        removed.add(k)
                elif kind == "node_add":
                    nodes_added.append(change[1])
                else:
                    rebuild = change[1]
            old_key = f"{self.key_prefix}{old_gen}|base"
            new_key = f"{self.key_prefix}{gen_now}|base"
            base = self.prep_cache.get(old_key)
            entry = None
            if rebuild is None and base is not None and base.prep is not None:
                cluster = self.twin.materialize()
                watch = prepcache.watch_snapshot(cluster, [])
                with base.lock:
                    base.restore()
                    # a mixed pod+node batch used to drop the lineage
                    # wholesale (NOTES round-14). It decomposes instead:
                    # node wave first (arena extend + DS splice), then the
                    # pod wave on top (bare-region insert + mask flips) —
                    # exactly the stream a fresh prepare of the post-batch
                    # twin produces (new bare pods at the bare-region end,
                    # new nodes' DS pods appended per group), gated
                    # bit-equal in tests/test_watch.py
                    mid = base
                    if nodes_added:
                        new_prep = prepcache.extend_with_nodes(
                            base.prep, nodes_added, cluster, [], base_entry=base
                        )
                        mid = None
                        if new_prep is not None:
                            mid = prepcache.CacheEntry(new_key, new_prep, base=base, watch=watch)
                            mid.base_drop = prepcache.pad_drop_mask(
                                base.base_drop, len(new_prep.ordered)
                            )
                    if mid is base:
                        entry = prepcache.twin_pod_delta(
                            base, new_key, added, removed, watch=watch
                        )
                    elif mid is not None:
                        if added or removed:
                            # mid was created above and is not yet published:
                            # its lock is uncontended, held only for the
                            # twin_pod_delta caller contract
                            with mid.lock:
                                entry = prepcache.twin_pod_delta(
                                    mid, new_key, added, removed, watch=watch
                                )
                        else:
                            entry = mid
            with self._maint_lock:
                if self._prep_gen != old_gen:
                    # a relist/drift repair/bootstrap reset the lineage
                    # while the delta was encoding; its verdict supersedes
                    # ours — the swapped changes belong to the dead lineage
                    return
                if entry is not None:
                    self.prep_cache.put(new_key, entry)
                    # trailing "|" so gen 5 cannot prefix-match gen 50's keys
                    self.prep_cache.invalidate(f"{self.key_prefix}{old_gen}|")
                    tracing.event(
                        "twin.delta",
                        added=len(added), removed=len(removed), nodes=len(nodes_added),
                    )
                else:
                    self._invalidate_prep()
                    if rebuild is not None:
                        log.debug("twin prep lineage dropped: %s", rebuild)
                self._prep_gen = gen_now

    # -- anti-entropy --------------------------------------------------------

    def anti_entropy(self) -> int:
        """Relist, then rv-aware-merge the listing into the twin
        (``ClusterTwin.reconcile``), counting and repairing genuinely
        drifted objects. Returns the drift count (0 = converged, -1 = the
        relist itself failed). The merge runs under the dispatch lock so it
        cannot interleave with reflector event application, and twin-ahead
        objects (events applied after the list was taken) are never
        reverted. Public: tests and ``make twin-smoke`` call it
        synchronously instead of waiting out ``OPENSIM_WATCH_RESYNC_S``."""
        with self._traced("anti-entropy") as tr:
            try:
                # fetched OUTSIDE the dispatch lock: a slow apiserver must
                # not stall event application for the whole list round-trip
                listing = retry_call(
                    self.source.list_all,
                    attempts=self.policy["reconnects"],
                    base_delay=self.policy["backoff_s"],
                    retry_on=(SnapshotFetchError, TimeoutError),
                    trace_name="watch.antientropy.retry",
                )
            except Exception as e:
                log.warning("anti-entropy relist failed: %s: %s", type(e).__name__, e)
                tracing.event("twin.antientropy", status="error", error=str(e))
                return -1
            with self._dispatch_lock:
                per: Dict[str, int] = {}
                drift = self.twin.reconcile(listing, per_resource=per)
                if drift:
                    with RECORDER.lock:
                        self.drift_total += drift
                        for res, n in per.items():
                            self.drift_by_resource[res] = (
                                self.drift_by_resource.get(res, 0) + n
                            )
                        self.resyncs_total += 1
                    self._set_state("resyncing")
                    log.warning(
                        "anti-entropy: repaired %d drifted object(s)", drift
                    )
                    tracing.event("twin.drift", status="error", drift=drift)
                    if self.journal is not None:
                        # drift repair against a journal-restored (or live)
                        # twin is journaled as a rebase record: without it a
                        # replay would faithfully re-create the drift the
                        # pass just fixed. The POST-reconcile store is the
                        # truth (the merge is rv-aware; the raw listing is
                        # not), recorded per drifted resource.
                        repaired, gen = self.twin.snapshot_raw(sorted(per))
                        for res, items in repaired.items():
                            self.journal.record_rebase(
                                res, items, gen,
                                rv=listing.get(res, ([], ""))[1],
                                why="anti-entropy",
                            )
                    with self._maint_lock:
                        self._pending.clear()
                        self._invalidate_prep()
                        self._prep_gen = self.twin.generation
                    self._capacity_rebase()
                    self._set_state("live")
                    self._recompute_state()
            if tr is not None:
                tr.root.set(drift=drift)
            return drift

    # -- telemetry -----------------------------------------------------------

    @contextlib.contextmanager
    def _traced(self, op: str):
        """Run one supervisor operation under its own recorded trace (ids
        ``watch-<op>-<n>`` in the flight recorder) when tracing is on."""
        tr = tracing.start_trace(f"watch-{op}", request_id=f"watch-{op}-{next(self._trace_seq)}")
        if tr is None:
            yield None
            return
        status = "ok"
        try:
            with tracing.trace_scope(tr):
                yield tr
        except BaseException:
            status = "error"
            raise
        finally:
            tr.finish(status=status)
            FLIGHT_RECORDER.record(tr)

    def metrics_lines(self) -> List[str]:
        """Prometheus lines for /metrics (rendered by the REST layer under
        the one recorder lock)."""
        esc = escape_label_value
        state = self.state()
        hdr = family_header  # headers come from the obs/metrics.py registry

        with RECORDER.lock:
            lines = hdr("simon_watch_state")
            lines += [
                f'simon_watch_state{{state="{esc(s)}"}} {int(s == state)}'
                for s in STATES
            ]
            lines += hdr("simon_watch_events_total")
            lines += [
                f'simon_watch_events_total{{kind="{esc(k)}",resource="{esc(res)}"}} {n}'
                for (k, res), n in sorted(self.events_total.items())
            ]
            lines += [
                *hdr("simon_watch_reconnects_total"),
                f"simon_watch_reconnects_total {self.reconnects_total}",
                *hdr("simon_watch_relists_total"),
                f"simon_watch_relists_total {self.relists_total}",
                *hdr("simon_watch_gone_total"),
                f"simon_watch_gone_total {self.gone_total}",
                *hdr("simon_twin_drift_total"),
            ]
            # stable per-resource series from the first scrape: every
            # watched resource renders (0 until drift is attributed to it)
            drift_res = {res: 0 for res in self.watched}
            drift_res.update(self.drift_by_resource)
            lines += [
                f'simon_twin_drift_total{{resource="{esc(res)}"}} {n}'
                for res, n in sorted(drift_res.items())
            ]
            lines += [
                *hdr("simon_twin_resyncs_total"),
                f"simon_twin_resyncs_total {self.resyncs_total}",
                # the generation gauge (ISSUE 9 satellite): every applied
                # event bumps it — a flatlined generation under traffic is
                # the "watch died" smoke signal dashboards alert on
                *hdr("simon_twin_generation"),
                f"simon_twin_generation {self.twin.generation}",
            ]
        # owner-side freshness stages (journaled/published); worker-side
        # processes render the same family from their own tracker
        lines += FRESHNESS.metrics_lines()
        return lines
