"""Multi-process serving fleet: shared-memory twin publication + worker
processes past the GIL (ISSUE 15, docs/serving.md "Scaling past one
process").

PR 8's admission/batching core multiplied throughput inside ONE Python
process; this module multiplies processes over ONE warm twin. The roles:

- **twin owner** (the parent, :func:`serve_fleet`): runs the watch
  supervisor + journal exactly like the single-process server, and after
  every twin generation change publishes the warm base prep's arenas over
  POSIX shared memory (``multiprocessing.shared_memory``):

    * one **content-keyed segment per numpy buffer** — the
      ``EncodedCluster``/``ScanState`` field buffers, template ids, masks.
      Segment names are derived from the buffer's content hash, so a
      generation that changed 2 of 75 arrays re-publishes 2 segments and
      the workers re-attach 2 (the arenas are already content-keyed and
      immutable-once-built, which is what makes this delta publication
      sound);
    * one **blob segment** holding the pickled host-side state (twin
      cluster objects, pod stream, encoder provenance, decode tables);
      its pickler externalizes every numpy leaf into the segments above,
      so arrays cross the process boundary exactly once, by name;
    * a small **control block** with a seqlock: ``seq`` goes odd, the
      generation/fingerprint/segment-directory payload is swapped, ``seq``
      goes even. Readers retry on an odd or changed ``seq`` — a worker can
      NEVER observe a torn generation (gated by tests/test_fleet.py).

- **N server workers** (:func:`run_worker`, spawned as fresh ``simon
  server`` subprocesses with ``OPENSIM_FLEET_ATTACH`` set): attach the
  segments read-only, reconstruct the numpy views zero-copy via
  ``np.frombuffer``, rebuild a warm base ``CacheEntry`` through
  ``prepcache.entry_from_publication`` (the one device upload per
  generation per worker), and serve the FULL admission → reqbatch →
  simulate ladder independently — placements are bit-identical to the
  single-process server (gated). Workers share the public port via
  ``SO_REUSEPORT`` (the kernel load-balances accepted connections) and
  each binds a loopback listener the owner scrapes for aggregation.

- **supervision**: a crashed worker is respawned with the resilience
  layer's full-jitter backoff (``resilience.retry.backoff_delay``) and
  reattaches at the CURRENT generation. SIGTERM drains the fleet in
  order: workers first (each drains its admission queue), owner last
  (reflectors stopped, journal flushed + fsynced, segments unlinked).

Shared-memory discipline (opensim-lint OSL1701): segments are created,
attached and unlinked ONLY in this module. Leak story: the owner unlinks
everything on close/atexit, and the stdlib resource tracker — a separate
process that survives even SIGKILL of the owner — unlinks whatever an
owner crash leaves behind, so ``/dev/shm`` never accumulates garbage.
Workers deliberately unregister their attachments from their own tracker:
an exiting worker must never destroy the owner's live segments.
"""

from __future__ import annotations

import atexit
import contextlib
import hashlib
import io
import json
import logging
import os
import pickle
import secrets
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.metrics import (
    FAMILIES,
    RECORDER,
    escape_label_value,
    family_header,
    make_histogram,
)
from ..resilience.retry import backoff_delay
from ..utils import envknobs

log = logging.getLogger("opensim_tpu.server")

__all__ = [
    "ControlBlock",
    "FleetReader",
    "FleetTwinClient",
    "TornGeneration",
    "TwinPublisher",
    "run_worker",
    "serve_fleet",
]

# control-block layout (little-endian):
#   0..8    magic
#   8..16   seq        — seqlock: odd while a publish is in flight
#   16..24  payload len
#   24..32  generation
#   32..    payload    — json: fingerprint, state, stale, blob segment,
#                        array-segment directory (accounting + GC)
_MAGIC = b"SIMFLT01"
_HEADER = struct.Struct("<8sQQQ")
_CONTROL_SIZE = 256 * 1024

#: arrays smaller than this ride inside the pickled blob (a dedicated
#: segment per 8-byte scalar array would be pure overhead, and zero-size
#: arrays cannot be shm segments at all)
_INLINE_BYTES = 64


class TornGeneration(RuntimeError):
    """A reader exhausted its seqlock retries without observing one stable
    publication — the owner is either republishing faster than the reader
    can attach or has died mid-publish. Counted in
    ``simon_fleet_attach_retries_exhausted_total``; the caller keeps
    serving its previously attached generation."""


_SHM_CLS = None


def _shm_cls():
    """The one construction point for stdlib shm segments (OSL1701 keeps
    every create/attach/unlink inside this file). The subclass makes
    ``close()`` tolerate live buffer exports: at interpreter shutdown the
    stdlib ``__del__`` closes segments in GC order, and a zero-copy numpy
    view that outlives its segment object would otherwise spray
    ``BufferError`` tracebacks over every worker exit (the mmap itself is
    freed safely once the last view dies — suppressing the eager close is
    correct, not cosmetic)."""
    global _SHM_CLS
    if _SHM_CLS is None:
        from multiprocessing import shared_memory

        class _Segment(shared_memory.SharedMemory):
            def close(self) -> None:
                try:
                    super().close()
                except BufferError:
                    pass

        _SHM_CLS = _Segment
    return _SHM_CLS


#: segment names THIS process created (it owns their tracker registration
#: and their unlink); in-process readers — tests, the owner's own attach
#: fallback — must not unregister them out from under the owner
_OWNED_NAMES: set = set()


def _attach(name: str):
    """Attach an existing segment WITHOUT adopting ownership: Python's
    resource tracker would otherwise unlink the owner's segment when this
    (reader) process exits — exactly the destruction the owner/reader
    split exists to prevent. Segments created by this very process keep
    their registration (the owner's crash-cleanup backstop)."""
    shm = _shm_cls()(name=name)
    if name not in _OWNED_NAMES:
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
        except Exception as e:  # pragma: no cover - tracker layout off-CPython
            log.debug("resource-tracker unregister failed: %s: %s", type(e).__name__, e)
    return shm


class ControlBlock:
    """The seqlock-guarded publication header.

    One writer (the twin owner), many readers (workers). ``write`` bumps
    ``seq`` to odd, swaps the payload, bumps to even; ``read`` snapshots
    ``seq`` before and after and retries unless both are the same even
    value. 8-byte aligned header writes and bounded retries make torn
    reads impossible to observe, not merely unlikely."""

    def __init__(self, name: Optional[str] = None, create: bool = False,
                 size: int = _CONTROL_SIZE) -> None:
        self.create = create
        if create:
            self.name = name or f"simon-fleet-{os.getpid()}-{secrets.token_hex(4)}"
            self._shm = _shm_cls()(
                name=self.name, create=True, size=size
            )
            _OWNED_NAMES.add(self.name)
            self._seq = 0
            _HEADER.pack_into(self._shm.buf, 0, _MAGIC, 0, 0, 0)
        else:
            if not name:
                raise ValueError("attaching a ControlBlock requires its name")
            self.name = name
            self._shm = _attach(name)
            magic = bytes(self._shm.buf[:8])
            if magic != _MAGIC:
                raise ValueError(
                    f"shared-memory segment {name!r} is not a fleet control block"
                )

    # -- writer side ---------------------------------------------------------

    def write(self, generation: int, payload: dict) -> None:
        data = json.dumps(payload, sort_keys=True).encode()
        if _HEADER.size + len(data) > self._shm.size:
            raise ValueError(
                f"fleet control payload ({len(data)}B) exceeds the control "
                f"block ({self._shm.size}B); raise the control size"
            )
        buf = self._shm.buf
        self._seq += 1  # odd: publication in flight
        struct.pack_into("<Q", buf, 8, self._seq)
        struct.pack_into("<Q", buf, 16, len(data))
        struct.pack_into("<Q", buf, 24, generation)
        buf[_HEADER.size : _HEADER.size + len(data)] = data
        self._seq += 1  # even: stable
        struct.pack_into("<Q", buf, 8, self._seq)

    # -- reader side ---------------------------------------------------------

    def seq(self) -> int:
        return struct.unpack_from("<Q", self._shm.buf, 8)[0]

    def poll(self) -> Optional[int]:
        """(generation) of the current stable publication, or None before
        the first publish / while a swap is in flight."""
        got = self.poll_state()
        return got[0] if got is not None else None

    def poll_state(self) -> Optional[Tuple[int, int]]:
        """(generation, seq) of the current stable publication. The seq
        is the change detector: a republish at the SAME generation (a
        staleness/state flip on a quiet twin) bumps it, and readers must
        refresh their payload on any bump, not only on generation
        moves."""
        s1 = self.seq()
        if s1 == 0 or s1 % 2:
            return None
        gen = struct.unpack_from("<Q", self._shm.buf, 24)[0]
        if self.seq() != s1:
            return None
        return int(gen), s1

    def read(self) -> Optional[Tuple[int, dict, int]]:
        """One seqlock read attempt: ``(generation, payload, seq)`` or
        None on a torn/absent publication (caller retries). The json
        parse is inside the torn-read net on purpose: the pure-Python
        seqlock carries no memory fences, so on a weakly-ordered CPU a
        stable-looking seq pair can still cover torn payload bytes — a
        parse failure IS a torn read, never an exception on the serving
        path."""
        s1 = self.seq()
        if s1 == 0 or s1 % 2:
            return None
        _magic, _seq, n, gen = _HEADER.unpack_from(self._shm.buf, 0)
        data = bytes(self._shm.buf[_HEADER.size : _HEADER.size + n])
        if self.seq() != s1:
            return None
        try:
            return int(gen), json.loads(data.decode()), s1
        except ValueError:
            return None

    def close(self) -> None:
        with contextlib.suppress(BufferError, OSError):
            self._shm.close()

    def unlink(self) -> None:
        with contextlib.suppress(FileNotFoundError, OSError):
            self._shm.unlink()
        _OWNED_NAMES.discard(self.name)


# ---------------------------------------------------------------------------
# pickling with externalized arrays
# ---------------------------------------------------------------------------


class _ShmPickler(pickle.Pickler):
    """Pickles the publication blob with every material numpy buffer
    externalized into a content-keyed segment: the blob carries
    ``("shmarr", segment, dtype, shape)`` stubs, the publisher writes each
    distinct buffer exactly once, and the reader rebuilds zero-copy
    ``np.frombuffer`` views. Pickle's memo keeps aliased arrays (the
    encoder's arenas ARE the encoded cluster's node tensors) aliased."""

    def __init__(self, file, put_array) -> None:
        super().__init__(file, protocol=pickle.HIGHEST_PROTOCOL)
        self._put_array = put_array

    def persistent_id(self, obj):
        if (
            type(obj) is np.ndarray
            and obj.dtype != object
            and obj.nbytes >= _INLINE_BYTES
        ):
            name = self._put_array(obj)
            return ("shmarr", name, obj.dtype.str, obj.shape)
        return None


class _ShmUnpickler(pickle.Unpickler):
    def __init__(self, file, get_segment) -> None:
        super().__init__(file)
        self._get_segment = get_segment

    def persistent_load(self, pid):
        tag, name, dtype, shape = pid
        if tag != "shmarr":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        shm = self._get_segment(name)
        count = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(shm.buf, dtype=np.dtype(dtype), count=count)
        arr = arr.reshape(shape)
        arr.flags.writeable = False
        return arr


# ---------------------------------------------------------------------------
# owner side: the publisher
# ---------------------------------------------------------------------------


class TwinPublisher:
    """Publishes generation-stamped arena deltas over shared memory.

    Owned by the twin-owner process. ``publish`` is called with the warm
    base entry's :func:`engine.prepcache.publication_parts` (under the
    entry lock — the shared pod objects must be quiescent while they
    pickle); unchanged buffers keep their content-keyed segments, so a
    steady twin republishes only the blob and the control block.

    Lifecycle: ``close()`` unlinks everything; it is also registered via
    ``atexit``, and the stdlib resource tracker unlinks whatever a crash
    leaves behind — ``/dev/shm`` hygiene is tested, not hoped for."""

    def __init__(self, token: Optional[str] = None,
                 control_size: int = _CONTROL_SIZE, keep_generations: int = 2) -> None:
        self.token = token or f"{os.getpid()}-{secrets.token_hex(4)}"
        self.control = ControlBlock(
            name=f"simon-fleet-{self.token}", create=True, size=control_size
        )
        self.keep_generations = keep_generations
        self._segments: Dict[str, object] = {}  # name -> SharedMemory
        self._seg_bytes: Dict[str, int] = {}
        self._gen_segments: "Dict[int, set]" = {}
        self._lock = threading.Lock()
        self.publishes_total = 0
        self.last_generation = -1
        self.publish_seconds = make_histogram("simon_fleet_publish_seconds", ())
        self._closed = False
        atexit.register(self.close)

    # -- segments ------------------------------------------------------------

    def _segment_name(self, data: bytes) -> str:
        digest = hashlib.blake2b(data, digest_size=12).hexdigest()
        return f"simon-fleet-{self.token}-{digest}"

    def _put_bytes(self, data: bytes, current: set) -> str:
        name = self._segment_name(data)
        current.add(name)
        if name in self._segments:
            return name
        try:
            shm = _shm_cls()(name=name, create=True, size=len(data))
            _OWNED_NAMES.add(name)
        except FileExistsError:
            # content-keyed: an existing same-name segment holds the same
            # bytes (it was published by US under this run token)
            shm = _attach(name)
        shm.buf[: len(data)] = data
        self._segments[name] = shm
        self._seg_bytes[name] = len(data)
        return name

    # -- publish -------------------------------------------------------------

    def publish(self, generation: int, cluster, parts: Optional[dict],
                state: str = "live", stale: bool = False) -> dict:
        """Write one publication: array segments, blob segment, control
        swap (seqlock), then garbage-collect segments no generation within
        the keep window references."""
        t0 = time.monotonic()
        with self._lock:
            current: set = set()
            arrays: List[Tuple[str, str, List[int]]] = []

            def put_array(arr: np.ndarray) -> str:
                a = np.ascontiguousarray(arr)
                name = self._put_bytes(a.tobytes(), current)
                arrays.append((name, a.dtype.str, list(a.shape)))
                return name

            buf = io.BytesIO()
            _ShmPickler(buf, put_array).dump({"cluster": cluster, "parts": parts})
            blob = self._put_bytes(buf.getvalue(), current)
            fingerprint = hashlib.blake2b(
                ("|".join(sorted(current)) + f"|{blob}").encode(), digest_size=16
            ).hexdigest()
            payload = {
                "fingerprint": fingerprint,
                "state": state,
                "stale": bool(stale),
                "blob": blob,
                "arrays": arrays,
                "token": self.token,
            }
            self.control.write(generation, payload)
            self._gen_segments[generation] = current
            self.publishes_total += 1
            self.last_generation = generation
            self._gc_segments()
        seconds = time.monotonic() - t0
        with RECORDER.lock:
            self.publish_seconds.observe(seconds, ())
        return payload

    def _gc_segments(self) -> None:
        """Unlink segments referenced by no generation in the keep window.
        A reader attaching the PREVIOUS directory mid-swap may race an
        unlink — its attach fails with FileNotFoundError and the seqlock
        retry picks up the new directory; keeping one extra generation
        makes that race rare instead of per-publish."""
        gens = sorted(self._gen_segments)
        keep = gens[-self.keep_generations :]
        live: set = set()
        for g in keep:
            live |= self._gen_segments[g]
        for g in gens:
            if g not in keep:
                del self._gen_segments[g]
        for name in list(self._segments):
            if name not in live:
                shm = self._segments.pop(name)
                self._seg_bytes.pop(name, None)
                with contextlib.suppress(FileNotFoundError, OSError, BufferError):
                    shm.unlink()
                _OWNED_NAMES.discard(name)
                with contextlib.suppress(BufferError, OSError):
                    shm.close()

    # -- accounting / teardown ----------------------------------------------

    def footprint(self) -> dict:
        with self._lock:
            return {
                "segments": len(self._segments) + 1,  # + control block
                "bytes": sum(self._seg_bytes.values()) + _CONTROL_SIZE,
                "publishes": self.publishes_total,
                "generation": self.last_generation,
            }

    def close(self) -> None:
        """Unlink every owned segment (idempotent; atexit-registered)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for name, shm in self._segments.items():
                with contextlib.suppress(FileNotFoundError, OSError, BufferError):
                    shm.unlink()
                _OWNED_NAMES.discard(name)
                with contextlib.suppress(BufferError, OSError):
                    shm.close()
            self._segments.clear()
            self._seg_bytes.clear()
            self.control.unlink()
            self.control.close()


# ---------------------------------------------------------------------------
# worker side: the reader
# ---------------------------------------------------------------------------


def attach_retries() -> int:
    # the registered validator owns the parse/clamp and the warn-and-
    # fall-back policy (utils/envknobs.py)
    return int(envknobs.value("OPENSIM_FLEET_ATTACH_RETRIES"))


class FleetReader:
    """Attaches a publication and rebuilds the host-side view.

    Attached segments are cached by (content-keyed) name, so a generation
    that changed 2 arrays re-attaches 2 segments and reuses the rest —
    the reader half of delta publication. Dropped cache references are
    NOT closed eagerly: live numpy views pin the mmap via the buffer
    protocol, and Python frees it only after the last view dies, which is
    what makes handing zero-copy views to long-lived cache entries safe."""

    def __init__(self, control_name: str, retries: Optional[int] = None) -> None:
        self.control = ControlBlock(name=control_name, create=False)
        self.retries = retries if retries is not None else attach_retries()
        self._cache: Dict[str, object] = {}  # segment name -> SharedMemory
        self.attaches_total = 0
        self.retries_total = 0
        self.retries_exhausted_total = 0
        self.segment_reuse_total = 0
        self.last_seq: Optional[int] = None  # seq validated by the last attach()

    def poll(self) -> Optional[int]:
        return self.control.poll()

    def poll_state(self) -> Optional[Tuple[int, int]]:
        return self.control.poll_state()

    def _segment(self, name: str):
        shm = self._cache.get(name)
        if shm is None:
            shm = _attach(name)
            self._cache[name] = shm
        else:
            self.segment_reuse_total += 1
        return shm

    def attach(self) -> Tuple[int, dict, dict]:
        """(generation, payload, blob object) for the current stable
        publication. Retries the whole read on any torn observation — an
        odd/changed seqlock, or a segment unlinked between the directory
        read and the attach. Raises :class:`TornGeneration` when the
        retry budget is exhausted."""
        last_err: Optional[BaseException] = None
        for attempt in range(self.retries):
            if attempt:
                self.retries_total += 1
                time.sleep(min(0.05, 0.002 * attempt))
            got = self.control.read()
            if got is None:
                last_err = None
                continue
            gen, payload, seq = got
            try:
                blob_shm = self._segment(payload["blob"])
                data = bytes(blob_shm.buf[:])
                obj = _ShmUnpickler(io.BytesIO(data), self._segment).load()
            except FileNotFoundError as e:
                last_err = e  # segment GC'd mid-swap: re-read the directory
                continue
            if self.control.seq() != seq:
                last_err = None
                continue  # a publish landed while we attached
            # drop cache references no longer named by this publication
            # (the mmaps stay alive until the last numpy view dies)
            live = {payload["blob"]} | {name for name, _, _ in payload["arrays"]}
            for name in [n for n in self._cache if n not in live]:
                del self._cache[name]
            self.attaches_total += 1
            self.last_seq = seq
            return gen, payload, obj
        self.retries_exhausted_total += 1
        raise TornGeneration(
            f"no stable fleet publication after {self.retries} attempts"
            + (f" (last error: {last_err})" if last_err else "")
        )

    def close(self) -> None:
        self.control.close()
        self._cache.clear()


class FleetTwinClient:
    """The worker's stand-in for the watch supervisor: same serving
    interface (``serving_snapshot``/``state``/``metrics_lines``), backed
    by the owner's shared-memory publication instead of a private watch
    pipeline. On a generation change it attaches the new view, rebuilds
    the warm base entry (``prepcache.entry_from_publication``) and swaps
    it into the server's prep cache under the new generation key — the
    request path then behaves exactly as with a live twin."""

    key_prefix = "fleet|"

    def __init__(self, control_name: str, prep_cache=None) -> None:
        self.control_name = control_name
        self.prep_cache = prep_cache
        self.capacity = None  # assigned by SimonServer; bootstrap is per key
        self.journal = None
        self._reader: Optional[FleetReader] = None
        self._lock = threading.Lock()
        self._gen: Optional[int] = None
        self._seq: Optional[int] = None  # guarded-by: _lock
        self._cluster = None
        self._payload: Optional[dict] = None
        self._synced = threading.Event()

    # -- lifecycle (the serve() supervisor contract) -------------------------

    def start(self, wait_s: Optional[float] = None) -> bool:
        deadline = time.monotonic() + (wait_s if wait_s is not None else 120.0)
        attempt = 0
        while time.monotonic() < deadline:
            try:
                if self._reader is None:
                    self._reader = FleetReader(self.control_name)
                if self._reader.poll() is not None:
                    self._synced.set()
                    return True
            except (FileNotFoundError, ValueError):
                self._reader = None  # owner not up yet
            attempt += 1
            time.sleep(min(0.25, 0.01 * attempt))
        return False

    def stop(self) -> None:
        if self._reader is not None:
            self._reader.close()

    def attach_journal(self, journal) -> None:  # pragma: no cover - owner-only
        raise RuntimeError("fleet workers do not own a journal (the twin owner does)")

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- serving interface ---------------------------------------------------

    def state(self) -> str:
        p = self._payload
        return f"fleet-{p['state']}" if p else "fleet-attaching"

    def is_stale(self) -> bool:
        p = self._payload
        return bool(p.get("stale")) if p else True

    def serving_snapshot(self):
        """(cluster, cache key, stale?) — None before the first attach.
        Steady state is one seqlock poll; ANY new publication re-attaches
        — a generation move swaps the warm base entry under the new key,
        and a same-generation republish (the owner flipping
        staleness/state on a quiet twin) refreshes the payload so
        degraded responses keep their stale tag."""
        if self._reader is None:
            return None
        state = self._reader.poll_state()
        with self._lock:
            if state is not None and state[1] != self._seq:
                try:
                    self._attach_locked()
                except TornGeneration as e:
                    log.warning("fleet attach failed (%s); serving previous generation", e)
            if self._gen is None:
                return None
            return self._cluster, f"{self.key_prefix}{self._gen}", self.is_stale()

    def _attach_locked(self) -> None:
        from ..engine import prepcache
        from ..obs import trace as tracing

        gen, payload, obj = self._reader.attach()
        if gen != self._gen:
            key = f"{self.key_prefix}{gen}"
            if self.prep_cache is not None and obj.get("parts") is not None:
                entry = prepcache.entry_from_publication(f"{key}|base", obj["parts"])
                old_gen = self._gen
                self.prep_cache.put(f"{key}|base", entry)
                if old_gen is not None:
                    # trailing "|" so gen 5 cannot prefix-match gen 50's keys
                    self.prep_cache.invalidate(f"{self.key_prefix}{old_gen}|")
            self._cluster = obj["cluster"]
        self._gen = gen
        # the seq attach() VALIDATED, not the live one: a publish landing
        # after the attach must leave this behind so the next poll
        # re-attaches instead of silently serving the older payload
        self._seq = self._reader.last_seq
        self._payload = payload
        self._synced.set()
        tracing.event(
            "fleet.attach", generation=gen, fingerprint=payload["fingerprint"],
            state=payload.get("state"), stale=payload.get("stale"),
        )

    # -- telemetry -----------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        r = self._reader
        lines: List[str] = []
        pairs = (
            ("simon_fleet_attaches_total", r.attaches_total if r else 0),
            ("simon_fleet_attach_retries_total", r.retries_total if r else 0),
            (
                "simon_fleet_attach_retries_exhausted_total",
                r.retries_exhausted_total if r else 0,
            ),
            ("simon_fleet_segment_reuse_total", r.segment_reuse_total if r else 0),
            ("simon_fleet_attach_generation", self._gen if self._gen is not None else -1),
        )
        for name, value in pairs:
            lines += family_header(name)
            lines.append(f"{name} {value}")
        return lines


# ---------------------------------------------------------------------------
# worker process entry
# ---------------------------------------------------------------------------


def _http_base():
    from .rest import SimonHTTPServer

    return SimonHTTPServer

class _ReusePortHTTPServer(_http_base()):
    """Public listener shared across worker processes: every worker binds
    the same port with SO_REUSEPORT and the kernel load-balances accepted
    connections — no fd passing, and a respawned worker just binds again."""

    # the stdlib default backlog of 5 RESETS the connect storm of a
    # hundreds-of-clients closed loop before a single request is read;
    # keep-alive means the storm is one-time, but it must survive it
    request_queue_size = 512

    def server_bind(self):
        if not hasattr(socket, "SO_REUSEPORT"):  # pragma: no cover - linux CI
            raise OSError(
                "SO_REUSEPORT is unavailable on this platform; "
                "simon server --workers needs it (docs/serving.md)"
            )
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


def run_worker(port: int) -> int:
    """One fleet worker: attach the owner's publication, serve the full
    REST surface on the shared public port plus a loopback listener for
    the owner's aggregation scrapes. Invoked by ``simon server`` when
    ``OPENSIM_FLEET_ATTACH`` names a control block (the supervisor sets
    it; operators never do)."""
    from .rest import SimonServer, make_handler

    control = envknobs.raw("OPENSIM_FLEET_ATTACH")
    internal_raw = envknobs.raw("OPENSIM_FLEET_INTERNAL_PORT")
    client = FleetTwinClient(control)
    if not client.start(wait_s=120.0):
        print(
            f"simon server[worker]: no fleet publication at {control!r} "
            "within 120s", flush=True,
        )
        return 1
    server = SimonServer(watch=client)
    client.prep_cache = server.prep_cache
    server.memory.start_ticker()
    handler = make_handler(server)
    httpd = _ReusePortHTTPServer(("0.0.0.0", port), handler)
    internal_httpd = None
    if internal_raw:
        internal_httpd = ThreadingHTTPServer(("127.0.0.1", int(internal_raw)), handler)
        threading.Thread(
            target=internal_httpd.serve_forever, name="simon-fleet-internal",
            daemon=True,
        ).start()

    def _graceful(signum, frame):
        log.info("worker received %s; draining", signal.Signals(signum).name)
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _graceful)
        except ValueError:  # pragma: no cover - embedded use
            break
    print(
        f"simon server[worker {os.getpid()}] attached to fleet "
        f"(generation {client._gen if client._gen is not None else '?'}) "
        f"on :{port}",
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        # same drain order as the single-process server: stop admitting
        # (queued tickets shed typed 503s, the in-flight batch completes),
        # then detach from the publication
        if internal_httpd is not None:
            internal_httpd.shutdown()
        server.close()
        client.stop()
        print(f"simon server[worker {os.getpid()}]: shutdown complete", flush=True)
    return 0


# ---------------------------------------------------------------------------
# owner process: publisher loop + worker supervision + admin endpoint
# ---------------------------------------------------------------------------


def publish_interval_s() -> float:
    # the registered validator owns the parse/clamp and the warn-and-
    # fall-back policy (utils/envknobs.py)
    return float(envknobs.value("OPENSIM_FLEET_PUBLISH_MS")) / 1000.0


class _Worker:
    def __init__(self, index: int, internal_port: int) -> None:
        self.index = index
        self.internal_port = internal_port
        self.proc: Optional[subprocess.Popen] = None
        self.spawned_at = 0.0
        self.crashes = 0


#: gauges whose fleet aggregate is a max, not a sum (a summed generation
#: number is meaningless; everything else — counters, histogram buckets,
#: queue depths — sums correctly across workers)
_AGG_MAX = {"simon_fleet_attach_generation"}


class FleetSupervisor:
    """The twin-owner process: watch supervisor + journal + publisher +
    worker supervision + the aggregated admin endpoint."""

    def __init__(self, supervisor, journal, port: int, workers: int,
                 admin_port: Optional[int] = None) -> None:
        from ..engine.prepcache import PrepareCache

        self.supervisor = supervisor
        self.journal = journal
        self.port = port
        self.n_workers = workers
        raw_admin = envknobs.raw("OPENSIM_FLEET_ADMIN_PORT")
        self.admin_port = admin_port or (int(raw_admin) if raw_admin else port + 1)
        self.prep_cache = PrepareCache()
        supervisor.prep_cache = self.prep_cache
        self.publisher = TwinPublisher()
        self.workers = [
            _Worker(i, self.admin_port + 1 + i) for i in range(workers)
        ]
        self.respawns_total = 0
        self._published_gen: Optional[int] = None
        self._published_stale: Optional[bool] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- publication ---------------------------------------------------------

    def publish_once(self) -> bool:
        """Publish the twin's current generation if it moved (or its
        staleness flipped). Returns True when a publication was written."""
        from ..engine import prepcache
        from ..engine.simulator import prepare

        sup = self.supervisor
        if not sup.has_synced():
            return False
        got = sup.serving_snapshot()  # folds pending deltas into the base entry
        if got is None:
            return False
        cluster, key, stale = got
        gen = int(key.rsplit("|", 1)[-1])
        if gen == self._published_gen and stale == self._published_stale:
            return False
        base_key = f"{key}|base"
        base = self.prep_cache.get(base_key)
        if base is None:
            watch = prepcache.watch_snapshot(cluster, [])  # before the build
            base = self.prep_cache.put(
                base_key,
                prepcache.CacheEntry(base_key, prepare(cluster, []), watch=watch),
            )
        state = sup.state()
        if base.prep is None:
            self.publisher.publish(gen, cluster, None, state=state, stale=stale)
        else:
            with base.lock:
                # the pickle walks the shared pod objects: bind state must
                # be pristine and stay quiescent for the walk
                base.restore()
                parts = prepcache.publication_parts(base)
                self.publisher.publish(gen, cluster, parts, state=state, stale=stale)
        self._published_gen = gen
        self._published_stale = stale
        return True

    def _publish_loop(self) -> None:
        interval = publish_interval_s()
        while not self._stop.is_set():
            try:
                self.publish_once()
            except Exception as e:
                log.warning("fleet publish failed: %s: %s", type(e).__name__, e)
            self._stop.wait(interval)

    # -- workers -------------------------------------------------------------

    def _spawn(self, w: _Worker) -> None:
        env = dict(os.environ)
        env["OPENSIM_FLEET_ATTACH"] = self.publisher.control.name
        env["OPENSIM_FLEET_INTERNAL_PORT"] = str(w.internal_port)
        # a worker must never recurse into fleet mode
        env.pop("OPENSIM_WORKERS_FLEET", None)
        w.proc = subprocess.Popen(
            [
                sys.executable, "-m", "opensim_tpu", "server",
                "--port", str(self.port), "--watch", "off",
            ],
            env=env,
        )
        w.spawned_at = time.monotonic()
        log.info("fleet worker %d spawned (pid %d)", w.index, w.proc.pid)

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            for w in self.workers:
                if self._stop.is_set():
                    return
                if w.proc is not None and w.proc.poll() is None:
                    if time.monotonic() - w.spawned_at > 30.0:
                        w.crashes = 0  # stable long enough: reset the backoff
                    continue
                rc = w.proc.returncode if w.proc is not None else None
                log.warning(
                    "fleet worker %d exited (rc=%s); respawning", w.index, rc
                )
                self.respawns_total += 1
                delay = backoff_delay(w.crashes, base_delay=0.25, max_delay=5.0)
                w.crashes += 1
                if self._stop.wait(delay):
                    return
                self._spawn(w)
            self._stop.wait(0.5)

    # -- aggregation ---------------------------------------------------------

    def _scrape_worker(self, w: _Worker) -> Optional[str]:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{w.internal_port}/metrics", timeout=2.0
            ) as resp:
                return resp.read().decode()
        except OSError:
            return None

    def aggregate_metrics(self) -> str:
        """The fleet /metrics body: per-worker expositions summed per
        series (bucket ladders are shared, so histogram sums stay valid
        histograms), plus the owner's twin/journal families and the fleet
        families themselves."""
        from .loadgen import parse_metrics

        sums: Dict[tuple, float] = {}
        live = 0
        for w in self.workers:
            text = self._scrape_worker(w)
            if text is None:
                continue
            live += 1
            for key, v in parse_metrics(text).items():
                if key[0] in _AGG_MAX:
                    sums[key] = max(sums.get(key, float("-inf")), v)
                else:
                    sums[key] = sums.get(key, 0.0) + v
        lines: List[str] = []
        fp = self.publisher.footprint()
        own = [
            ("simon_fleet_workers", live),
            ("simon_fleet_workers_target", self.n_workers),
            ("simon_fleet_respawns_total", self.respawns_total),
            ("simon_fleet_publishes_total", fp["publishes"]),
            ("simon_fleet_generation", fp["generation"]),
            ("simon_fleet_shm_segments", fp["segments"]),
            ("simon_fleet_shm_bytes", fp["bytes"]),
        ]
        for name, value in own:
            lines += family_header(name)
            lines.append(f"{name} {value}")
        with RECORDER.lock:
            lines += self.publisher.publish_seconds.render_lines()
        if self.supervisor is not None:
            lines += self.supervisor.metrics_lines()
        if self.journal is not None:
            lines += self.journal.metrics_lines()
        emitted: set = set()
        for (name, labels) in sorted(sums):
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                if family.endswith(suffix):
                    family = family[: -len(suffix)]
                    break
            if family in FAMILIES and family not in emitted:
                lines += family_header(family)
                emitted.add(family)
            body = ",".join(
                f'{k}="{escape_label_value(v)}"' for k, v in labels
            )
            value = sums[(name, labels)]
            rendered = f"{value:.10g}"
            lines.append(f"{name}{{{body}}} {rendered}" if body else f"{name} {rendered}")
        return "\n".join(lines) + "\n"

    def status(self) -> dict:
        fp = self.publisher.footprint()
        return {
            "workers": [
                {
                    "index": w.index,
                    "pid": w.proc.pid if w.proc is not None else None,
                    "alive": w.proc is not None and w.proc.poll() is None,
                    "internal_port": w.internal_port,
                    "crashes": w.crashes,
                }
                for w in self.workers
            ],
            "target_workers": self.n_workers,
            "respawns_total": self.respawns_total,
            "twin_state": self.supervisor.state() if self.supervisor else "none",
            "shm": fp,
            "control": self.publisher.control.name,
            "port": self.port,
            "admin_port": self.admin_port,
        }

    def alive_workers(self) -> int:
        return sum(
            1 for w in self.workers if w.proc is not None and w.proc.poll() is None
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for w in self.workers:
            self._spawn(w)
        for target, name in (
            (self._publish_loop, "simon-fleet-publish"),
            (self._monitor_loop, "simon-fleet-monitor"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, drain_s: float = 30.0) -> None:
        """SIGTERM drain order: workers first (each drains its admission
        queue and completes in-flight work), then the reflectors, then the
        journal flush, then the shared-memory unlink."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        for w in self.workers:
            if w.proc is not None and w.proc.poll() is None:
                with contextlib.suppress(OSError):
                    w.proc.terminate()
        deadline = time.monotonic() + drain_s
        for w in self.workers:
            if w.proc is None:
                continue
            with contextlib.suppress(subprocess.TimeoutExpired):
                w.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            if w.proc.poll() is None:
                log.warning("fleet worker %d did not drain; killing", w.index)
                with contextlib.suppress(OSError):
                    w.proc.kill()
                    w.proc.wait(timeout=5.0)
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.journal is not None:
            self.journal.close()
        self.publisher.close()


def _make_admin_handler(fleet: FleetSupervisor):
    class AdminHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet, like the REST handler
            pass

        def _send(self, code: int, data: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                alive = fleet.alive_workers()
                body = {
                    "status": "ok" if alive == fleet.n_workers else "degraded",
                    "role": "fleet-owner",
                    "workers": alive,
                    "target": fleet.n_workers,
                    "generation": fleet.publisher.last_generation,
                }
                self._send(200, json.dumps(body).encode(), "application/json")
            elif path == "/metrics":
                try:
                    text = fleet.aggregate_metrics()
                except Exception as e:  # a worker roll mid-scrape
                    log.warning("fleet aggregation failed: %s: %s", type(e).__name__, e)
                    self._send(
                        500, json.dumps({"error": str(e)}).encode(), "application/json"
                    )
                    return
                self._send(200, text.encode(), "text/plain; version=0.0.4")
            elif path == "/api/fleet/status":
                self._send(200, json.dumps(fleet.status()).encode(), "application/json")
            else:
                self._send(404, b'{"error": "not found"}', "application/json")

    return AdminHandler


def serve_fleet(kubeconfig: str, master: str, port: int, watch: str,
                journal: str, workers: int) -> int:
    """``simon server --workers N``: the multi-process serving fleet.
    Called by ``rest.serve`` with already-validated paths. The owner
    process never serves simulate traffic — workers own the public port
    via SO_REUSEPORT; the owner serves the aggregated fleet endpoint on
    the admin port (default: public port + 1)."""
    from .rest import build_twin

    if not kubeconfig or watch == "off":
        print(
            "simon server: --workers needs the live twin "
            "(--kubeconfig and --watch auto|on) — the twin owner is what "
            "the workers attach to", flush=True,
        )
        return 1
    try:
        supervisor, jrnl = build_twin(kubeconfig, master, watch, journal)
    except ValueError as e:
        print(f"simon server: {e}", flush=True)
        return 1
    if jrnl is not None:
        # attached BEFORE start(): the twin restores from the newest
        # checkpoint + suffix replay during startup, like the
        # single-process server (SimonServer wires this in its ctor)
        supervisor.attach_journal(jrnl)
    fleet = FleetSupervisor(supervisor, jrnl, port, workers)
    if watch == "on":
        if not supervisor.start(wait_s=60.0):
            print("simon server: --watch on but the twin could not sync", flush=True)
            supervisor.stop()
            fleet.publisher.close()
            return 1
    else:
        supervisor.start()
    httpd = ThreadingHTTPServer(("0.0.0.0", fleet.admin_port), _make_admin_handler(fleet))

    def _graceful(signum, frame):
        log.info(
            "fleet received %s; draining workers then owner",
            signal.Signals(signum).name,
        )
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _graceful)
        except ValueError:  # pragma: no cover - embedded use
            break
    fleet.start()
    print(
        f"simon fleet listening on :{port} [{workers} workers, "
        f"admin :{fleet.admin_port}]"
        + (f" [journal {journal}]" if jrnl is not None else ""),
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        fleet.stop()
        print("simon fleet: shutdown complete", flush=True)
    return 0
