"""Multi-process serving fleet: shared-memory twin publication + worker
processes past the GIL (ISSUE 15, docs/serving.md "Scaling past one
process").

PR 8's admission/batching core multiplied throughput inside ONE Python
process; this module multiplies processes over ONE warm twin. The roles:

- **twin owner** (the parent, :func:`serve_fleet`): runs the watch
  supervisor + journal exactly like the single-process server, and after
  every twin generation change publishes the warm base prep's arenas over
  POSIX shared memory (``multiprocessing.shared_memory``):

    * one **content-keyed segment per numpy buffer** — the
      ``EncodedCluster``/``ScanState`` field buffers, template ids, masks.
      Segment names are derived from the buffer's content hash, so a
      generation that changed 2 of 75 arrays re-publishes 2 segments and
      the workers re-attach 2 (the arenas are already content-keyed and
      immutable-once-built, which is what makes this delta publication
      sound);
    * one **blob segment** holding the pickled host-side state (twin
      cluster objects, pod stream, encoder provenance, decode tables);
      its pickler externalizes every numpy leaf into the segments above,
      so arrays cross the process boundary exactly once, by name;
    * a small **control block** with a seqlock: ``seq`` goes odd, the
      generation/fingerprint/segment-directory payload is swapped, ``seq``
      goes even. Readers retry on an odd or changed ``seq`` — a worker can
      NEVER observe a torn generation (gated by tests/test_fleet.py).

- **N server workers** (:func:`run_worker`, spawned as fresh ``simon
  server`` subprocesses with ``OPENSIM_FLEET_ATTACH`` set): attach the
  segments read-only, reconstruct the numpy views zero-copy via
  ``np.frombuffer``, rebuild a warm base ``CacheEntry`` through
  ``prepcache.entry_from_publication`` (the one device upload per
  generation per worker), and serve the FULL admission → reqbatch →
  simulate ladder independently — placements are bit-identical to the
  single-process server (gated). Workers share the public port via
  ``SO_REUSEPORT`` (the kernel load-balances accepted connections) and
  each binds a loopback listener the owner scrapes for aggregation.

- **supervision**: a crashed worker is respawned with the resilience
  layer's full-jitter backoff (``resilience.retry.backoff_delay``) and
  reattaches at the CURRENT generation. SIGTERM drains the fleet in
  order: workers first (each drains its admission queue), owner last
  (reflectors stopped, journal flushed + fsynced, segments unlinked).

Shared-memory discipline (opensim-lint OSL1701): segments are created,
attached and unlinked ONLY in this module. Leak story: the owner unlinks
everything on close/atexit, and the stdlib resource tracker — a separate
process that survives even SIGKILL of the owner — unlinks whatever an
owner crash leaves behind, so ``/dev/shm`` never accumulates garbage.
Workers deliberately unregister their attachments from their own tracker:
an exiting worker must never destroy the owner's live segments.

**HA control plane** (ISSUE 18, docs/serving.md "Surviving owner loss &
rolling upgrades"): with ``OPENSIM_HA=1`` the owner holds a **fenced
lease** (:class:`FleetLease` — a JSON file beside the journal carrying a
monotonic ``epoch``), renewed at a third of ``OPENSIM_HA_LEASE_S``. The
epoch is woven into every shared-memory name (publisher token
``e<epoch>-<pid>-<hex>``) and into the publication payload, and
:meth:`TwinPublisher.publish` re-validates the lease immediately before
the seqlock control swap — a deposed owner's late publish raises
:class:`FencedWrite` (counted in ``simon_fleet_fenced_writes_total``)
instead of ever becoming attachable. A hot standby (``simon server
--standby``, :func:`serve_standby`) tails the journal live
(:class:`~.journal.JournalTailer`) onto its own twin and takes over on
lease expiry or explicit release (``POST /api/fleet/handover`` — the
rolling-upgrade path): it bumps the epoch, starts a fresh
:class:`~.watch.WatchSupervisor` from the tailed state (zero relists,
reflectors resuming at the recorded rvs), **adopts** the surviving worker
processes recorded in the lease, and republishes at a continuous
generation — workers follow the lease file to the new control block
without dropping a request.
"""

from __future__ import annotations

import atexit
import contextlib
import hashlib
import io
import json
import logging
import os
import pickle
import re
import secrets
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.fleetobs import FRESHNESS
from ..obs.metrics import (
    FAMILIES,
    RECORDER,
    escape_label_value,
    family_header,
    make_counter,
    make_histogram,
    parse_metrics,
)
from ..resilience import faults
from ..resilience.retry import backoff_delay
from ..utils import envknobs

log = logging.getLogger("opensim_tpu.server")

__all__ = [
    "ControlBlock",
    "FencedWrite",
    "FleetLease",
    "FleetReader",
    "FleetTwinClient",
    "StandbyOwner",
    "TornGeneration",
    "TwinPublisher",
    "lease_path",
    "run_worker",
    "serve_fleet",
    "serve_standby",
]

# control-block layout (little-endian):
#   0..8    magic
#   8..16   seq        — seqlock: odd while a publish is in flight
#   16..24  payload len
#   24..32  generation
#   32..    payload    — json: fingerprint, state, stale, blob segment,
#                        array-segment directory (accounting + GC)
_MAGIC = b"SIMFLT01"
_HEADER = struct.Struct("<8sQQQ")
_CONTROL_SIZE = 256 * 1024

#: arrays smaller than this ride inside the pickled blob (a dedicated
#: segment per 8-byte scalar array would be pure overhead, and zero-size
#: arrays cannot be shm segments at all)
_INLINE_BYTES = 64


class TornGeneration(RuntimeError):
    """A reader exhausted its seqlock retries without observing one stable
    publication — the owner is either republishing faster than the reader
    can attach or has died mid-publish. Counted in
    ``simon_fleet_attach_retries_exhausted_total``; the caller keeps
    serving its previously attached generation."""


class FencedWrite(RuntimeError):
    """A publish was refused because the HA lease moved past this owner's
    epoch — the process has been deposed and must demote instead of
    split-braining. Counted in ``simon_fleet_fenced_writes_total``; the
    seqlock control block is left untouched, so no worker can ever attach
    a stale-epoch generation."""


#: the HA lease file, created beside the journal segments (the journal
#: directory is the one piece of shared durable state the owner and the
#: standby already agree on)
HA_LEASE_FILENAME = "ha-lease.json"


def lease_path(state_dir: str) -> str:
    return os.path.join(state_dir, HA_LEASE_FILENAME)


def ha_enabled() -> bool:
    return bool(envknobs.value("OPENSIM_HA"))


def ha_lease_s() -> float:
    # the registered validator owns the parse and the raise-on-typo policy
    return float(envknobs.value("OPENSIM_HA_LEASE_S"))


def ha_tail_poll_s() -> float:
    return float(envknobs.value("OPENSIM_HA_TAIL_POLL_MS")) / 1000.0


def ha_handover_timeout_s() -> float:
    return float(envknobs.value("OPENSIM_HA_HANDOVER_TIMEOUT_S"))


def _pid_alive(pid: int) -> bool:
    if not pid or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # e.g. EPERM: it exists, it just isn't ours
    return True


class FleetLease:
    """Fenced ownership of the fleet: one JSON file, one monotonic epoch.

    The file carries ``{epoch, holder, pid, renewed_at, released, ...}``
    plus owner metadata (control-block name, ports, worker pids) that the
    standby needs for takeover and the workers need to re-resolve the
    owner. Writes are atomic (temp file + ``os.replace``); there is
    deliberately no fsync — the lease is a liveness signal, not durable
    history, and a machine crash takes owner and lease down together.

    Correctness story: ``acquire`` only steals a lease that is absent,
    explicitly released, or older than ``lease_s``; it writes epoch+1 and
    then **confirms after a settle window** — of two racing acquirers the
    later write wins the file, the loser observes a foreign holder on
    re-read and stands down. ``check``/``renew`` observe the file every
    time: the moment another epoch appears, the holder is fenced and every
    subsequent :meth:`TwinPublisher.publish` refuses with
    :class:`FencedWrite`. Chaos point ``fleet.lease_steal`` forces the
    fenced verdict deterministically.
    """

    #: settle window between the acquire write and its confirming re-read
    ACQUIRE_CONFIRM_S = 0.05

    def __init__(self, path: str, lease_s: Optional[float] = None,
                 holder: Optional[str] = None) -> None:
        self.path = path
        self.lease_s = float(lease_s) if lease_s is not None else ha_lease_s()
        self.holder = holder or f"{os.getpid()}-{secrets.token_hex(4)}"
        self.epoch = 0  # 0 = not holding

    # -- file I/O ------------------------------------------------------------

    def read(self) -> Optional[dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError):
            return None

    def _write(self, doc: dict) -> None:
        # the lease may be the journal directory's FIRST file (the owner
        # acquires before opening the journal for append)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True)
        os.replace(tmp, self.path)

    # -- verdicts ------------------------------------------------------------

    @staticmethod
    def age_s(doc: Optional[dict]) -> float:
        if doc is None:
            return float("inf")
        try:
            return max(0.0, time.time() - float(doc.get("renewed_at") or 0.0))
        except (TypeError, ValueError):
            return float("inf")

    def claimable(self, doc: Optional[dict]) -> bool:
        """Absent, explicitly released, or expired — stealable."""
        return doc is None or bool(doc.get("released")) or self.age_s(doc) > self.lease_s

    # -- lifecycle -----------------------------------------------------------

    def acquire(self, meta: Optional[dict] = None) -> Optional[int]:
        """Take the lease (epoch+1) if it is claimable (or already ours).
        Returns the new epoch, or None when a live foreign holder owns it
        or a racing acquirer won the settle window."""
        doc = self.read()
        if doc is not None and doc.get("holder") != self.holder and not self.claimable(doc):
            return None
        epoch = int((doc or {}).get("epoch") or 0) + 1
        body = {
            "epoch": epoch, "holder": self.holder, "pid": os.getpid(),
            "renewed_at": time.time(), "released": False,
        }
        body.update(meta or {})
        self._write(body)
        time.sleep(self.ACQUIRE_CONFIRM_S)
        cur = self.read()
        if (
            cur is None
            or cur.get("holder") != self.holder
            or int(cur.get("epoch") or -1) != epoch
        ):
            return None  # lost the race: the later writer owns the file
        self.epoch = epoch
        return epoch

    def check(self) -> bool:
        """True while this process still holds the lease at its epoch.
        False IS the fencing verdict — the caller must stop publishing."""
        try:
            faults.fault_point("fleet.lease_steal")
        except Exception as e:
            log.warning("fleet lease: injected steal (%s); fencing", e)
            return False
        doc = self.read()
        return (
            doc is not None
            and doc.get("holder") == self.holder
            and int(doc.get("epoch") or -1) == self.epoch
            and not doc.get("released")
        )

    def renew(self, **updates) -> bool:
        """Re-stamp ``renewed_at`` (merging ``updates`` into the metadata)
        under our epoch. False = fenced; the caller demotes."""
        if not self.check():
            return False
        doc = self.read()
        if doc is None:
            return False
        doc["renewed_at"] = time.time()
        doc.update(updates)
        self._write(doc)
        return True

    def release(self, handover: bool = False) -> None:
        """Mark the lease released (the graceful-handover signal: the
        standby may take over immediately instead of waiting out the
        expiry window). No-op when the lease is no longer ours."""
        doc = self.read()
        if doc is None or doc.get("holder") != self.holder:
            return
        doc["released"] = True
        doc["handover"] = bool(handover)
        doc["renewed_at"] = time.time()
        self._write(doc)


_SHM_CLS = None


def _shm_cls():
    """The one construction point for stdlib shm segments (OSL1701 keeps
    every create/attach/unlink inside this file). The subclass makes
    ``close()`` tolerate live buffer exports: at interpreter shutdown the
    stdlib ``__del__`` closes segments in GC order, and a zero-copy numpy
    view that outlives its segment object would otherwise spray
    ``BufferError`` tracebacks over every worker exit (the mmap itself is
    freed safely once the last view dies — suppressing the eager close is
    correct, not cosmetic)."""
    global _SHM_CLS
    if _SHM_CLS is None:
        from multiprocessing import shared_memory

        class _Segment(shared_memory.SharedMemory):
            def close(self) -> None:
                try:
                    super().close()
                except BufferError:
                    pass

        _SHM_CLS = _Segment
    return _SHM_CLS


#: segment names THIS process created (it owns their tracker registration
#: and their unlink); in-process readers — tests, the owner's own attach
#: fallback — must not unregister them out from under the owner
_OWNED_NAMES: set = set()


def _attach(name: str):
    """Attach an existing segment WITHOUT adopting ownership: Python's
    resource tracker would otherwise unlink the owner's segment when this
    (reader) process exits — exactly the destruction the owner/reader
    split exists to prevent. Segments created by this very process keep
    their registration (the owner's crash-cleanup backstop)."""
    shm = _shm_cls()(name=name)
    if name not in _OWNED_NAMES:
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
        except Exception as e:  # pragma: no cover - tracker layout off-CPython
            log.debug("resource-tracker unregister failed: %s: %s", type(e).__name__, e)
    return shm


class ControlBlock:
    """The seqlock-guarded publication header.

    One writer (the twin owner), many readers (workers). ``write`` bumps
    ``seq`` to odd, swaps the payload, bumps to even; ``read`` snapshots
    ``seq`` before and after and retries unless both are the same even
    value. 8-byte aligned header writes and bounded retries make torn
    reads impossible to observe, not merely unlikely."""

    def __init__(self, name: Optional[str] = None, create: bool = False,
                 size: int = _CONTROL_SIZE) -> None:
        self.create = create
        if create:
            self.name = name or f"simon-fleet-{os.getpid()}-{secrets.token_hex(4)}"
            self._shm = _shm_cls()(
                name=self.name, create=True, size=size
            )
            _OWNED_NAMES.add(self.name)
            self._seq = 0
            _HEADER.pack_into(self._shm.buf, 0, _MAGIC, 0, 0, 0)
        else:
            if not name:
                raise ValueError("attaching a ControlBlock requires its name")
            self.name = name
            self._shm = _attach(name)
            magic = bytes(self._shm.buf[:8])
            if magic != _MAGIC:
                raise ValueError(
                    f"shared-memory segment {name!r} is not a fleet control block"
                )

    # -- writer side ---------------------------------------------------------

    def write(self, generation: int, payload: dict) -> None:
        data = json.dumps(payload, sort_keys=True).encode()
        if _HEADER.size + len(data) > self._shm.size:
            raise ValueError(
                f"fleet control payload ({len(data)}B) exceeds the control "
                f"block ({self._shm.size}B); raise the control size"
            )
        buf = self._shm.buf
        self._seq += 1  # odd: publication in flight
        struct.pack_into("<Q", buf, 8, self._seq)
        struct.pack_into("<Q", buf, 16, len(data))
        struct.pack_into("<Q", buf, 24, generation)
        buf[_HEADER.size : _HEADER.size + len(data)] = data
        self._seq += 1  # even: stable
        struct.pack_into("<Q", buf, 8, self._seq)

    # -- reader side ---------------------------------------------------------

    def seq(self) -> int:
        return struct.unpack_from("<Q", self._shm.buf, 8)[0]

    def poll(self) -> Optional[int]:
        """(generation) of the current stable publication, or None before
        the first publish / while a swap is in flight."""
        got = self.poll_state()
        return got[0] if got is not None else None

    def poll_state(self) -> Optional[Tuple[int, int]]:
        """(generation, seq) of the current stable publication. The seq
        is the change detector: a republish at the SAME generation (a
        staleness/state flip on a quiet twin) bumps it, and readers must
        refresh their payload on any bump, not only on generation
        moves."""
        s1 = self.seq()
        if s1 == 0 or s1 % 2:
            return None
        gen = struct.unpack_from("<Q", self._shm.buf, 24)[0]
        if self.seq() != s1:
            return None
        return int(gen), s1

    def read(self) -> Optional[Tuple[int, dict, int]]:
        """One seqlock read attempt: ``(generation, payload, seq)`` or
        None on a torn/absent publication (caller retries). The json
        parse is inside the torn-read net on purpose: the pure-Python
        seqlock carries no memory fences, so on a weakly-ordered CPU a
        stable-looking seq pair can still cover torn payload bytes — a
        parse failure IS a torn read, never an exception on the serving
        path."""
        s1 = self.seq()
        if s1 == 0 or s1 % 2:
            return None
        _magic, _seq, n, gen = _HEADER.unpack_from(self._shm.buf, 0)
        data = bytes(self._shm.buf[_HEADER.size : _HEADER.size + n])
        if self.seq() != s1:
            return None
        try:
            return int(gen), json.loads(data.decode()), s1
        except ValueError:
            return None

    def close(self) -> None:
        with contextlib.suppress(BufferError, OSError):
            self._shm.close()

    def unlink(self) -> None:
        with contextlib.suppress(FileNotFoundError, OSError):
            self._shm.unlink()
        _OWNED_NAMES.discard(self.name)


# ---------------------------------------------------------------------------
# pickling with externalized arrays
# ---------------------------------------------------------------------------


class _ShmPickler(pickle.Pickler):
    """Pickles the publication blob with every material numpy buffer
    externalized into a content-keyed segment: the blob carries
    ``("shmarr", segment, dtype, shape)`` stubs, the publisher writes each
    distinct buffer exactly once, and the reader rebuilds zero-copy
    ``np.frombuffer`` views. Pickle's memo keeps aliased arrays (the
    encoder's arenas ARE the encoded cluster's node tensors) aliased."""

    def __init__(self, file, put_array) -> None:
        super().__init__(file, protocol=pickle.HIGHEST_PROTOCOL)
        self._put_array = put_array

    def persistent_id(self, obj):
        if (
            type(obj) is np.ndarray
            and obj.dtype != object
            and obj.nbytes >= _INLINE_BYTES
        ):
            name = self._put_array(obj)
            return ("shmarr", name, obj.dtype.str, obj.shape)
        return None


class _ShmUnpickler(pickle.Unpickler):
    def __init__(self, file, get_segment) -> None:
        super().__init__(file)
        self._get_segment = get_segment

    def persistent_load(self, pid):
        tag, name, dtype, shape = pid
        if tag != "shmarr":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        shm = self._get_segment(name)
        count = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(shm.buf, dtype=np.dtype(dtype), count=count)
        arr = arr.reshape(shape)
        arr.flags.writeable = False
        return arr


# ---------------------------------------------------------------------------
# owner side: the publisher
# ---------------------------------------------------------------------------


class TwinPublisher:
    """Publishes generation-stamped arena deltas over shared memory.

    Owned by the twin-owner process. ``publish`` is called with the warm
    base entry's :func:`engine.prepcache.publication_parts` (under the
    entry lock — the shared pod objects must be quiescent while they
    pickle); unchanged buffers keep their content-keyed segments, so a
    steady twin republishes only the blob and the control block.

    Lifecycle: ``close()`` unlinks everything; it is also registered via
    ``atexit``, and the stdlib resource tracker unlinks whatever a crash
    leaves behind — ``/dev/shm`` hygiene is tested, not hoped for."""

    def __init__(self, token: Optional[str] = None,
                 control_size: int = _CONTROL_SIZE, keep_generations: int = 2,
                 epoch: int = 0, lease: Optional[FleetLease] = None) -> None:
        # the epoch is woven into the token, hence into EVERY segment name
        # and the control-block name: two owners can never collide on a
        # shared-memory name, and a worker can see at a glance (and the
        # payload check below can enforce) which fencing epoch published it
        self.epoch = int(epoch)
        self.lease = lease
        default = f"{os.getpid()}-{secrets.token_hex(4)}"
        self.token = token or (f"e{self.epoch}-{default}" if self.epoch else default)
        self.control = ControlBlock(
            name=f"simon-fleet-{self.token}", create=True, size=control_size
        )
        self.keep_generations = keep_generations
        self._segments: Dict[str, object] = {}  # name -> SharedMemory
        self._seg_bytes: Dict[str, int] = {}
        self._gen_segments: "Dict[int, set]" = {}
        self._lock = threading.Lock()
        self.publishes_total = 0
        self.fenced_writes_total = 0  # guarded-by: _lock
        self.last_generation = -1
        self.publish_seconds = make_histogram("simon_fleet_publish_seconds", ())
        self._closed = False
        atexit.register(self.close)

    # -- segments ------------------------------------------------------------

    def _segment_name(self, data: bytes) -> str:
        digest = hashlib.blake2b(data, digest_size=12).hexdigest()
        return f"simon-fleet-{self.token}-{digest}"

    def _put_bytes(self, data: bytes, current: set) -> str:
        name = self._segment_name(data)
        current.add(name)
        if name in self._segments:
            return name
        try:
            shm = _shm_cls()(name=name, create=True, size=len(data))
            _OWNED_NAMES.add(name)
        except FileExistsError:
            # content-keyed: an existing same-name segment holds the same
            # bytes (it was published by US under this run token)
            shm = _attach(name)
        shm.buf[: len(data)] = data
        self._segments[name] = shm
        self._seg_bytes[name] = len(data)
        return name

    # -- publish -------------------------------------------------------------

    def publish(self, generation: int, cluster, parts: Optional[dict],
                state: str = "live", stale: bool = False) -> dict:
        """Write one publication: array segments, blob segment, control
        swap (seqlock), then garbage-collect segments no generation within
        the keep window references."""
        t0 = time.monotonic()
        # publication stamp (ISSUE 20): fold pending accepted-event ids
        # into a trace dict BEFORE taking self._lock — FRESHNESS takes
        # RECORDER.lock, and this publisher deliberately never nests the
        # two (see publish_seconds below)
        trace_info = FRESHNESS.publication(generation)
        with self._lock:
            self._check_fence()  # refuse before wasting segment writes
            current: set = set()
            arrays: List[Tuple[str, str, List[int]]] = []

            def put_array(arr: np.ndarray) -> str:
                a = np.ascontiguousarray(arr)
                name = self._put_bytes(a.tobytes(), current)
                arrays.append((name, a.dtype.str, list(a.shape)))
                return name

            buf = io.BytesIO()
            _ShmPickler(buf, put_array).dump({"cluster": cluster, "parts": parts})
            blob = self._put_bytes(buf.getvalue(), current)
            fingerprint = hashlib.blake2b(
                ("|".join(sorted(current)) + f"|{blob}").encode(), digest_size=16
            ).hexdigest()
            payload = {
                "fingerprint": fingerprint,
                "state": state,
                "stale": bool(stale),
                "blob": blob,
                "arrays": arrays,
                "token": self.token,
                "epoch": self.epoch,
                # cross-process stitching: publication span id + carried
                # event ids (bounded, PUB_EVENTS_MAX) ride the control
                # block to every attaching worker
                "trace": trace_info,
            }
            # chaos shm.republish: a publish dying HERE leaves the seqlock
            # even and the directory untouched — readers keep the previous
            # stable generation (the segments written above are garbage
            # until a control swap names them; close() unlinks them)
            faults.fault_point("shm.republish")
            # the authoritative fencing gate: nothing a worker can attach
            # is ever swapped in under a stale epoch. Re-checked HERE (not
            # only at entry) because the segment writes above take real
            # time — a lease stolen mid-publish must still fence the swap.
            self._check_fence()
            self.control.write(generation, payload)
            self._gen_segments[generation] = current
            self.publishes_total += 1
            self.last_generation = generation
            self._gc_segments()
        seconds = time.monotonic() - t0
        with RECORDER.lock:
            self.publish_seconds.observe(seconds, ())
        return payload

    def _gc_segments(self) -> None:
        """Unlink segments referenced by no generation in the keep window.
        A reader attaching the PREVIOUS directory mid-swap may race an
        unlink — its attach fails with FileNotFoundError and the seqlock
        retry picks up the new directory; keeping one extra generation
        makes that race rare instead of per-publish."""
        gens = sorted(self._gen_segments)
        keep = gens[-self.keep_generations :]
        live: set = set()
        for g in keep:
            live |= self._gen_segments[g]
        for g in gens:
            if g not in keep:
                del self._gen_segments[g]
        for name in list(self._segments):
            if name not in live:
                shm = self._segments.pop(name)
                self._seg_bytes.pop(name, None)
                with contextlib.suppress(FileNotFoundError, OSError, BufferError):
                    shm.unlink()
                _OWNED_NAMES.discard(name)
                with contextlib.suppress(BufferError, OSError):
                    shm.close()

    def _check_fence(self) -> None:
        """Raise :class:`FencedWrite` (and count it) when the HA lease no
        longer names this owner's epoch. No-op outside HA mode."""
        if self.lease is None:
            return
        if not self.lease.check():
            self.fenced_writes_total += 1
            raise FencedWrite(
                f"lease epoch moved past {self.epoch}; publish refused "
                "(this owner is deposed and must demote)"
            )

    # -- accounting / teardown ----------------------------------------------

    def footprint(self) -> dict:
        with self._lock:
            return {
                "segments": len(self._segments) + 1,  # + control block
                "bytes": sum(self._seg_bytes.values()) + _CONTROL_SIZE,
                "publishes": self.publishes_total,
                "generation": self.last_generation,
                "fenced_writes": self.fenced_writes_total,
                "epoch": self.epoch,
            }

    def close(self) -> None:
        """Unlink every owned segment (idempotent; atexit-registered)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for name, shm in self._segments.items():
                with contextlib.suppress(FileNotFoundError, OSError, BufferError):
                    shm.unlink()
                _OWNED_NAMES.discard(name)
                with contextlib.suppress(BufferError, OSError):
                    shm.close()
            self._segments.clear()
            self._seg_bytes.clear()
            self.control.unlink()
            self.control.close()


# ---------------------------------------------------------------------------
# worker side: the reader
# ---------------------------------------------------------------------------


def attach_retries() -> int:
    # the registered validator owns the parse/clamp and the warn-and-
    # fall-back policy (utils/envknobs.py)
    return int(envknobs.value("OPENSIM_FLEET_ATTACH_RETRIES"))


class FleetReader:
    """Attaches a publication and rebuilds the host-side view.

    Attached segments are cached by (content-keyed) name, so a generation
    that changed 2 arrays re-attaches 2 segments and reuses the rest —
    the reader half of delta publication. Dropped cache references are
    NOT closed eagerly: live numpy views pin the mmap via the buffer
    protocol, and Python frees it only after the last view dies, which is
    what makes handing zero-copy views to long-lived cache entries safe."""

    def __init__(self, control_name: str, retries: Optional[int] = None) -> None:
        self.control = ControlBlock(name=control_name, create=False)
        self.retries = retries if retries is not None else attach_retries()
        self._cache: Dict[str, object] = {}  # segment name -> SharedMemory
        self.attaches_total = 0
        self.retries_total = 0
        self.retries_exhausted_total = 0
        self.segment_reuse_total = 0
        self.last_seq: Optional[int] = None  # seq validated by the last attach()

    def poll(self) -> Optional[int]:
        return self.control.poll()

    def poll_state(self) -> Optional[Tuple[int, int]]:
        return self.control.poll_state()

    def _segment(self, name: str):
        shm = self._cache.get(name)
        if shm is None:
            shm = _attach(name)
            self._cache[name] = shm
        else:
            self.segment_reuse_total += 1
        return shm

    def attach(self) -> Tuple[int, dict, dict]:
        """(generation, payload, blob object) for the current stable
        publication. Retries the whole read on any torn observation — an
        odd/changed seqlock, or a segment unlinked between the directory
        read and the attach. Raises :class:`TornGeneration` when the
        retry budget is exhausted."""
        last_err: Optional[BaseException] = None
        for attempt in range(self.retries):
            if attempt:
                self.retries_total += 1
                time.sleep(min(0.05, 0.002 * attempt))
            got = self.control.read()
            if got is None:
                last_err = None
                continue
            gen, payload, seq = got
            try:
                blob_shm = self._segment(payload["blob"])
                data = bytes(blob_shm.buf[:])
                obj = _ShmUnpickler(io.BytesIO(data), self._segment).load()
            except FileNotFoundError as e:
                last_err = e  # segment GC'd mid-swap: re-read the directory
                continue
            if self.control.seq() != seq:
                last_err = None
                continue  # a publish landed while we attached
            # drop cache references no longer named by this publication
            # (the mmaps stay alive until the last numpy view dies)
            live = {payload["blob"]} | {name for name, _, _ in payload["arrays"]}
            for name in [n for n in self._cache if n not in live]:
                del self._cache[name]
            self.attaches_total += 1
            self.last_seq = seq
            return gen, payload, obj
        self.retries_exhausted_total += 1
        raise TornGeneration(
            f"no stable fleet publication after {self.retries} attempts"
            + (f" (last error: {last_err})" if last_err else "")
        )

    def close(self) -> None:
        self.control.close()
        self._cache.clear()


class FleetTwinClient:
    """The worker's stand-in for the watch supervisor: same serving
    interface (``serving_snapshot``/``state``/``metrics_lines``), backed
    by the owner's shared-memory publication instead of a private watch
    pipeline. On a generation change it attaches the new view, rebuilds
    the warm base entry (``prepcache.entry_from_publication``) and swaps
    it into the server's prep cache under the new generation key — the
    request path then behaves exactly as with a live twin."""

    key_prefix = "fleet|"

    #: how often a worker re-reads the HA lease file for an owner change
    LEASE_CHECK_S = 0.25

    def __init__(self, control_name: str, prep_cache=None,
                 lease_file: str = "") -> None:
        self.control_name = control_name
        self.prep_cache = prep_cache
        self.capacity = None  # assigned by SimonServer; bootstrap is per key
        self.journal = None
        # HA (docs/serving.md "Surviving owner loss"): when the supervisor
        # hands us the lease path, the worker follows it — a failover
        # republishes under a NEW control block (the epoch is in the name),
        # and the lease file is how the worker finds it without restarting
        self.lease_file = lease_file
        self._lease_epoch = 0
        self._next_lease_check = 0.0
        self.owner_switches_total = 0
        self._reader: Optional[FleetReader] = None
        self._lock = threading.Lock()
        self._gen: Optional[int] = None
        self._seq: Optional[int] = None  # guarded-by: _lock
        self._cluster = None
        self._payload: Optional[dict] = None
        self._synced = threading.Event()

    # -- lifecycle (the serve() supervisor contract) -------------------------

    def start(self, wait_s: Optional[float] = None) -> bool:
        deadline = time.monotonic() + (wait_s if wait_s is not None else 120.0)
        attempt = 0
        while time.monotonic() < deadline:
            try:
                if self._reader is None:
                    self._reader = FleetReader(self.control_name)
                if self._reader.poll() is not None:
                    self._synced.set()
                    return True
            except (FileNotFoundError, ValueError):
                self._reader = None  # owner not up yet
            attempt += 1
            time.sleep(min(0.25, 0.01 * attempt))
        return False

    def stop(self) -> None:
        if self._reader is not None:
            self._reader.close()

    def attach_journal(self, journal) -> None:  # pragma: no cover - owner-only
        raise RuntimeError("fleet workers do not own a journal (the twin owner does)")

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- serving interface ---------------------------------------------------

    def state(self) -> str:
        p = self._payload
        return f"fleet-{p['state']}" if p else "fleet-attaching"

    def is_stale(self) -> bool:
        p = self._payload
        return bool(p.get("stale")) if p else True

    def serving_snapshot(self):
        """(cluster, cache key, stale?) — None before the first attach.
        Steady state is one seqlock poll; ANY new publication re-attaches
        — a generation move swaps the warm base entry under the new key,
        and a same-generation republish (the owner flipping
        staleness/state on a quiet twin) refreshes the payload so
        degraded responses keep their stale tag."""
        self._follow_lease()
        reader = self._reader
        if reader is None:
            return None
        state = reader.poll_state()
        with self._lock:
            if state is not None and state[1] != self._seq:
                try:
                    self._attach_locked()
                except TornGeneration as e:
                    log.warning("fleet attach failed (%s); serving previous generation", e)
            if self._gen is None:
                return None
            return self._cluster, f"{self.key_prefix}{self._gen}", self.is_stale()

    def _follow_lease(self) -> None:
        """Failover discovery: when the HA lease names a DIFFERENT control
        block (a new owner took over at a higher epoch), swap readers and
        keep serving the old mmap'd generation until the new owner's first
        publication attaches — a worker never drops a request across a
        failover. Throttled to one file read per LEASE_CHECK_S."""
        if not self.lease_file:
            return
        now = time.monotonic()
        if now < self._next_lease_check:
            return
        self._next_lease_check = now + self.LEASE_CHECK_S
        try:
            with open(self.lease_file, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return  # lease unreadable mid-replace or gone: keep serving
        epoch = int(doc.get("epoch") or 0)
        if epoch > self._lease_epoch:
            self._lease_epoch = epoch
        control = str(doc.get("control") or "")
        if not control or control == self.control_name:
            return
        try:
            reader = FleetReader(control)
            if reader.poll() is None:
                # the new owner exists but has not published yet: stay on
                # the old (still mmap'd) generation and retry next check
                reader.close()
                return
        except (FileNotFoundError, ValueError):
            return
        with self._lock:
            # the old reader is dropped, NOT closed: request threads may be
            # mid-poll on it, and the live numpy views pin its mmaps anyway
            self._reader = reader
            self.control_name = control
            self._seq = None  # force a fresh attach on the next snapshot
            self.owner_switches_total += 1
        log.info(
            "fleet worker: followed the lease to new owner control %s "
            "(epoch %d)", control, epoch,
        )

    def _attach_locked(self) -> None:
        from ..engine import prepcache
        from ..obs import trace as tracing

        gen, payload, obj = self._reader.attach()
        ep = int(payload.get("epoch") or 0)
        if self._lease_epoch and ep and ep < self._lease_epoch:
            # fencing, reader side: a deposed owner raced one last publish
            # in. Refuse it — the caller keeps serving the previous
            # generation until the current-epoch owner publishes.
            raise TornGeneration(
                f"stale-epoch publication refused (epoch {ep} < lease "
                f"epoch {self._lease_epoch})"
            )
        if gen != self._gen:
            key = f"{self.key_prefix}{gen}"
            if self.prep_cache is not None and obj.get("parts") is not None:
                entry = prepcache.entry_from_publication(f"{key}|base", obj["parts"])
                old_gen = self._gen
                self.prep_cache.put(f"{key}|base", entry)
                if old_gen is not None:
                    # trailing "|" so gen 5 cannot prefix-match gen 50's keys
                    self.prep_cache.invalidate(f"{self.key_prefix}{old_gen}|")
            self._cluster = obj["cluster"]
        self._gen = gen
        # the seq attach() VALIDATED, not the live one: a publish landing
        # after the attach must leave this behind so the next poll
        # re-attaches instead of silently serving the older payload
        self._seq = self._reader.last_seq
        self._payload = payload
        self._synced.set()
        # worker-side freshness stage + the stitching handoff: remember
        # the owner's publication span/event ids for this generation
        trace_info = payload.get("trace")
        FRESHNESS.attached(gen, trace_info)
        pub = trace_info if isinstance(trace_info, dict) else {}
        tracing.event(
            "fleet.attach", generation=gen, fingerprint=payload["fingerprint"],
            state=payload.get("state"), stale=payload.get("stale"),
            publication_span=pub.get("span"),
            publication_age_s=(
                round(time.time() - float(pub["pub_ts"]), 6)
                if pub.get("pub_ts") else None
            ),
        )

    # -- telemetry -----------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        r = self._reader
        lines: List[str] = []
        pairs = (
            ("simon_fleet_attaches_total", r.attaches_total if r else 0),
            ("simon_fleet_attach_retries_total", r.retries_total if r else 0),
            (
                "simon_fleet_attach_retries_exhausted_total",
                r.retries_exhausted_total if r else 0,
            ),
            ("simon_fleet_segment_reuse_total", r.segment_reuse_total if r else 0),
            ("simon_fleet_attach_generation", self._gen if self._gen is not None else -1),
        )
        for name, value in pairs:
            lines += family_header(name)
            lines.append(f"{name} {value}")
        # worker-side freshness stages (attached/served)
        lines += FRESHNESS.metrics_lines()
        return lines

    def stitch_info(self) -> Tuple[Optional[int], Optional[dict]]:
        """(serving generation, owner publication trace dict) for the
        request being served RIGHT NOW — the REST layer stamps both onto
        the request trace so the flight recorder can graft the owner-side
        publication subtree under it. Also closes the freshness pipeline:
        the first request per generation observes the ``served`` stage."""
        with self._lock:
            gen = self._gen
        if gen is None:
            return None, None
        return gen, FRESHNESS.note_served(gen)


# ---------------------------------------------------------------------------
# worker process entry
# ---------------------------------------------------------------------------


def _http_base():
    from .rest import SimonHTTPServer

    return SimonHTTPServer

class _ReusePortHTTPServer(_http_base()):
    """Public listener shared across worker processes: every worker binds
    the same port with SO_REUSEPORT and the kernel load-balances accepted
    connections — no fd passing, and a respawned worker just binds again."""

    # the stdlib default backlog of 5 RESETS the connect storm of a
    # hundreds-of-clients closed loop before a single request is read;
    # keep-alive means the storm is one-time, but it must survive it
    request_queue_size = 512

    def server_bind(self):
        if not hasattr(socket, "SO_REUSEPORT"):  # pragma: no cover - linux CI
            raise OSError(
                "SO_REUSEPORT is unavailable on this platform; "
                "simon server --workers needs it (docs/serving.md)"
            )
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


def run_worker(port: int) -> int:
    """One fleet worker: attach the owner's publication, serve the full
    REST surface on the shared public port plus a loopback listener for
    the owner's aggregation scrapes. Invoked by ``simon server`` when
    ``OPENSIM_FLEET_ATTACH`` names a control block (the supervisor sets
    it; operators never do)."""
    from .rest import SimonServer, make_handler

    control = envknobs.raw("OPENSIM_FLEET_ATTACH")
    internal_raw = envknobs.raw("OPENSIM_FLEET_INTERNAL_PORT")
    client = FleetTwinClient(control, lease_file=envknobs.raw("OPENSIM_FLEET_LEASE"))
    if not client.start(wait_s=120.0):
        print(
            f"simon server[worker]: no fleet publication at {control!r} "
            "within 120s", flush=True,
        )
        return 1
    server = SimonServer(watch=client)
    client.prep_cache = server.prep_cache
    server.memory.start_ticker()
    handler = make_handler(server)
    httpd = _ReusePortHTTPServer(("0.0.0.0", port), handler)
    internal_httpd = None
    if internal_raw:
        internal_httpd = ThreadingHTTPServer(("127.0.0.1", int(internal_raw)), handler)
        threading.Thread(
            target=internal_httpd.serve_forever, name="simon-fleet-internal",
            daemon=True,
        ).start()

    def _graceful(signum, frame):
        log.info("worker received %s; draining", signal.Signals(signum).name)
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _graceful)
        except ValueError:  # pragma: no cover - embedded use
            break
    print(
        f"simon server[worker {os.getpid()}] attached to fleet "
        f"(generation {client._gen if client._gen is not None else '?'}) "
        f"on :{port}",
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        # same drain order as the single-process server: stop admitting
        # (queued tickets shed typed 503s, the in-flight batch completes),
        # then detach from the publication
        if internal_httpd is not None:
            internal_httpd.shutdown()
        server.close()
        client.stop()
        print(f"simon server[worker {os.getpid()}]: shutdown complete", flush=True)
    return 0


# ---------------------------------------------------------------------------
# owner process: publisher loop + worker supervision + admin endpoint
# ---------------------------------------------------------------------------


def publish_interval_s() -> float:
    # the registered validator owns the parse/clamp and the warn-and-
    # fall-back policy (utils/envknobs.py)
    return float(envknobs.value("OPENSIM_FLEET_PUBLISH_MS")) / 1000.0


class _Worker:
    def __init__(self, index: int, internal_port: int) -> None:
        self.index = index
        self.internal_port = internal_port
        self.proc: Optional[subprocess.Popen] = None
        self.spawned_at = 0.0
        self.crashes = 0
        # HA takeover: an adopted worker was spawned by the PREVIOUS owner
        # and survived it — we only hold its pid, not a Popen handle
        self.pid = 0
        self.adopted = False

    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        return self.adopted and self.pid > 0 and _pid_alive(self.pid)


#: gauges whose fleet aggregate is a max, not a sum (a summed generation
#: number is meaningless; everything else — counters, histogram buckets,
#: queue depths — sums correctly across workers)
_AGG_MAX = {"simon_fleet_attach_generation"}

#: families additionally exposed per worker as `{worker="<index>"}` series
#: next to the summed family (ISSUE 20 satellite). An allowlist, not
#: everything: per-worker copies of all ~100 families would multiply the
#: admin endpoint's cardinality by the fleet size for series nobody
#: breaks down per worker.
_PER_WORKER = {
    "simon_request_seconds",
    "simon_requests_total",
    "simon_lane_depth",
    "simon_fleet_attach_generation",
    "simon_fleet_attaches_total",
    "simon_fleet_freshness_seconds",
}

_TYPE_LINE = re.compile(r"^# TYPE (\S+) ", re.M)


def _family_of(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def render_aggregated(worker_texts: List[Optional[str]],
                      owner_text: str = "") -> str:
    """Merge per-worker /metrics expositions (index = worker id; None =
    scrape failed) with the owner's own exposition into ONE body:

    - every series summed across processes (bucket ladders are shared, so
      histogram sums stay valid histograms; ``_AGG_MAX`` families take the
      max — a summed generation number is meaningless);
    - ``_PER_WORKER`` families additionally rendered per worker with a
      ``worker="<index>"`` label next to the summed series (same family,
      same header — exposition-format conformant, zero duplicate series
      because the label set differs);
    - exactly one ``# HELP``/``# TYPE`` header per family, including
      sample-less families that appeared header-only in any input.

    Module-level and pure so the conformance test can drive it with
    canned texts — no shared memory, no live workers."""
    sums: Dict[tuple, float] = {}
    labeled: Dict[tuple, float] = {}
    header_only: set = set(_TYPE_LINE.findall(owner_text))
    for key, v in parse_metrics(owner_text).items():
        if key[0] in _AGG_MAX:
            sums[key] = max(sums.get(key, float("-inf")), v)
        else:
            sums[key] = sums.get(key, 0.0) + v
    for i, text in enumerate(worker_texts):
        if text is None:
            continue
        header_only |= set(_TYPE_LINE.findall(text))
        for (name, labels), v in parse_metrics(text).items():
            key = (name, labels)
            if name in _AGG_MAX:
                sums[key] = max(sums.get(key, float("-inf")), v)
            else:
                sums[key] = sums.get(key, 0.0) + v
            if _family_of(name) in _PER_WORKER:
                labeled[(name, labels + (("worker", str(i)),))] = v
    by_family: Dict[str, List[tuple]] = {}
    for store in (sums, labeled):
        for key in store:
            by_family.setdefault(_family_of(key[0]), [])
    lines: List[str] = []

    def _render(store: Dict[tuple, float], name: str, labels: tuple) -> None:
        body = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels)
        rendered = f"{store[(name, labels)]:.10g}"
        lines.append(f"{name}{{{body}}} {rendered}" if body else f"{name} {rendered}")

    for family in sorted(by_family):
        if family in FAMILIES:
            lines += family_header(family)
        header_only.discard(family)
        for name, labels in sorted(k for k in sums if _family_of(k[0]) == family):
            _render(sums, name, labels)
        for name, labels in sorted(k for k in labeled if _family_of(k[0]) == family):
            _render(labeled, name, labels)
    for family in sorted(header_only):
        if family in FAMILIES:
            lines += family_header(family)
    return "\n".join(lines) + "\n"


class FleetSupervisor:
    """The twin-owner process: watch supervisor + journal + publisher +
    worker supervision + the aggregated admin endpoint."""

    def __init__(self, supervisor, journal, port: int, workers: int,
                 admin_port: Optional[int] = None, lease: Optional[FleetLease] = None,
                 adopt: Optional[list] = None, takeover_reason: str = "") -> None:
        from ..engine.prepcache import PrepareCache

        self.supervisor = supervisor
        self.journal = journal
        self.port = port
        self.n_workers = workers
        raw_admin = envknobs.raw("OPENSIM_FLEET_ADMIN_PORT")
        self.admin_port = admin_port or (int(raw_admin) if raw_admin else port + 1)
        self.prep_cache = PrepareCache()
        supervisor.prep_cache = self.prep_cache
        self.lease = lease
        self.publisher = TwinPublisher(
            epoch=lease.epoch if lease is not None else 0, lease=lease
        )
        self.workers = []
        adopted_by_index = {
            int(row.get("index", -1)): row for row in (adopt or [])
        }
        for i in range(workers):
            row = adopted_by_index.get(i)
            pid = int(row.get("pid") or 0) if row else 0
            if row and pid > 0 and _pid_alive(pid):
                # a survivor from the deposed owner: keep its recorded
                # loopback port and pid; it follows the lease to us on its
                # own — adopting it is what makes takeover relist-free
                w = _Worker(i, int(row.get("internal_port") or self.admin_port + 1 + i))
                w.pid = pid
                w.adopted = True
                w.spawned_at = time.monotonic()
            else:
                w = _Worker(i, self.admin_port + 1 + i)
            self.workers.append(w)
        self.takeover_reason = takeover_reason
        self.takeovers = make_counter("simon_fleet_takeovers_total", ("reason",))
        if takeover_reason:
            with RECORDER.lock:
                self.takeovers.inc(labels=(takeover_reason,))
        self.respawns_total = 0
        # time-series ring + SLO engine (ISSUE 20): wired by
        # start_timeseries() — NOT the ctor, so tests can build a
        # supervisor without a sampler thread or disk ring
        self.timeseries = None
        self.slo = None
        self._sampler = None
        self.handed_over = False
        self._on_handover = None  # set by the serve loop: shut the admin server
        self._fenced = threading.Event()
        self._published_gen: Optional[int] = None
        self._published_stale: Optional[bool] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- publication ---------------------------------------------------------

    def publish_once(self) -> bool:
        """Publish the twin's current generation if it moved (or its
        staleness flipped). Returns True when a publication was written."""
        from ..engine import prepcache
        from ..engine.simulator import prepare

        if self._fenced.is_set():
            return False
        sup = self.supervisor
        if not sup.has_synced():
            return False
        got = sup.serving_snapshot()  # folds pending deltas into the base entry
        if got is None:
            return False
        cluster, key, stale = got
        gen = int(key.rsplit("|", 1)[-1])
        if gen == self._published_gen and stale == self._published_stale:
            return False
        base_key = f"{key}|base"
        base = self.prep_cache.get(base_key)
        if base is None:
            watch = prepcache.watch_snapshot(cluster, [])  # before the build
            base = self.prep_cache.put(
                base_key,
                prepcache.CacheEntry(base_key, prepare(cluster, []), watch=watch),
            )
        state = sup.state()
        if base.prep is None:
            self.publisher.publish(gen, cluster, None, state=state, stale=stale)
        else:
            with base.lock:
                # the pickle walks the shared pod objects: bind state must
                # be pristine and stay quiescent for the walk
                base.restore()
                parts = prepcache.publication_parts(base)
                self.publisher.publish(gen, cluster, parts, state=state, stale=stale)
        self._published_gen = gen
        self._published_stale = stale
        return True

    def _publish_loop(self) -> None:
        interval = publish_interval_s()
        while not self._stop.is_set():
            try:
                self.publish_once()
            except FencedWrite as e:
                log.warning("fleet publish fenced: %s", e)
                self._demote("fenced publish")
                return
            except Exception as e:
                log.warning("fleet publish failed: %s: %s", type(e).__name__, e)
            self._stop.wait(interval)

    # -- HA lease ------------------------------------------------------------

    def _lease_doc_meta(self) -> dict:
        return {
            "control": self.publisher.control.name,
            "port": self.port,
            "admin_port": self.admin_port,
            "n_workers": self.n_workers,
            "generation": self.publisher.last_generation,
            "workers": [
                {
                    "index": w.index,
                    "internal_port": w.internal_port,
                    "pid": w.proc.pid if w.proc is not None else w.pid,
                }
                for w in self.workers
            ],
        }

    def _lease_loop(self) -> None:
        assert self.lease is not None
        interval = max(0.2, self.lease.lease_s / 3.0)
        while not self._stop.is_set():
            try:
                ok = self.lease.renew(**self._lease_doc_meta())
            except OSError as e:  # transient fs hiccup: try again next beat
                log.warning("fleet lease renew I/O error: %s", e)
                ok = True
            if not ok:
                self._demote("lease lost (stolen or expired past another acquire)")
                return
            self._stop.wait(interval)

    def _demote(self, why: str) -> None:
        """The lease moved under us: stop publishing and journaling NOW.
        The epoch fence already guarantees no worker attaches anything we
        write from here on; demotion just stops us burning the disk."""
        if self._fenced.is_set():
            return
        self._fenced.set()
        log.warning("fleet owner fenced: %s; demoting", why)

        def _down():
            # keep_workers: they belong to the NEW owner now (it adopted
            # their pids from the lease doc); killing them would drop the
            # very requests failover exists to save
            self.stop(keep_workers=True)

        threading.Thread(target=_down, name="simon-fleet-demote", daemon=True).start()

    # -- handover (rolling upgrade) ------------------------------------------

    def handover(self) -> Tuple[int, dict]:
        """POST /api/fleet/handover: drain and release the lease with the
        handover flag so the tailing standby takes over without waiting
        for expiry. Returns (http_status, body)."""
        if self.lease is None:
            return 409, {"error": "not running in HA mode (OPENSIM_HA)"}
        if self._fenced.is_set() or self.handed_over:
            return 409, {"error": "already fenced or handed over"}
        threading.Thread(
            target=self._handover_drain, name="simon-fleet-handover", daemon=True
        ).start()
        return 200, {"status": "draining", "epoch": self.lease.epoch}

    def _handover_drain(self) -> None:
        log.info("fleet handover: draining owner, releasing lease")
        self._fenced.set()  # no further publishes or lease renewals
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.journal is not None:
            self.journal.close(timeout=ha_handover_timeout_s())
        if self.lease is not None:
            with contextlib.suppress(OSError):
                self.lease.release(handover=True)
        self.handed_over = True
        cb = self._on_handover
        if cb is not None:
            cb()

    # -- workers -------------------------------------------------------------

    def _spawn(self, w: _Worker) -> None:
        env = dict(os.environ)
        env["OPENSIM_FLEET_ATTACH"] = self.publisher.control.name
        env["OPENSIM_FLEET_INTERNAL_PORT"] = str(w.internal_port)
        if self.lease is not None:
            # the worker follows the lease file across owner changes
            env["OPENSIM_FLEET_LEASE"] = self.lease.path
        # a worker must never recurse into fleet mode
        env.pop("OPENSIM_WORKERS_FLEET", None)
        w.adopted = False
        w.pid = 0
        w.proc = subprocess.Popen(
            [
                sys.executable, "-m", "opensim_tpu", "server",
                "--port", str(self.port), "--watch", "off",
            ],
            env=env,
        )
        w.spawned_at = time.monotonic()
        w.pid = w.proc.pid
        log.info("fleet worker %d spawned (pid %d)", w.index, w.proc.pid)

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            for w in self.workers:
                if self._stop.is_set():
                    return
                if w.alive():
                    if time.monotonic() - w.spawned_at > 30.0:
                        w.crashes = 0  # stable long enough: reset the backoff
                    continue
                rc = w.proc.returncode if w.proc is not None else None
                log.warning(
                    "fleet worker %d exited (rc=%s); respawning", w.index, rc
                )
                self.respawns_total += 1
                delay = backoff_delay(w.crashes, base_delay=0.25, max_delay=5.0)
                w.crashes += 1
                if self._stop.wait(delay):
                    return
                self._spawn(w)
            self._stop.wait(0.5)

    # -- aggregation ---------------------------------------------------------

    def _scrape_worker(self, w: _Worker) -> Optional[str]:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{w.internal_port}/metrics", timeout=2.0
            ) as resp:
                return resp.read().decode()
        except OSError:
            return None

    def _owner_metrics_text(self, live: int) -> str:
        """The owner process's OWN exposition (fleet gauges, publisher
        histogram, twin/journal families, time-series ring + SLO engine).
        Fed through :func:`render_aggregated` like a worker text so every
        family renders exactly one header at the admin endpoint."""
        lines: List[str] = []
        fp = self.publisher.footprint()
        own = [
            ("simon_fleet_workers", live),
            ("simon_fleet_workers_target", self.n_workers),
            ("simon_fleet_respawns_total", self.respawns_total),
            ("simon_fleet_publishes_total", fp["publishes"]),
            ("simon_fleet_generation", fp["generation"]),
            ("simon_fleet_shm_segments", fp["segments"]),
            ("simon_fleet_shm_bytes", fp["bytes"]),
            ("simon_fleet_fenced_writes_total", fp["fenced_writes"]),
        ]
        if self.lease is not None:
            age = FleetLease.age_s(self.lease.read())
            if age != float("inf"):
                own.append(("simon_fleet_lease_age_seconds", f"{age:.3f}"))
        for name, value in own:
            lines += family_header(name)
            lines.append(f"{name} {value}")
        with RECORDER.lock:
            lines += self.publisher.publish_seconds.render_lines()
            takeover_lines = self.takeovers.render_lines()
        lines += takeover_lines or family_header("simon_fleet_takeovers_total")
        if self.supervisor is not None:
            lines += self.supervisor.metrics_lines()
        if self.journal is not None:
            lines += self.journal.metrics_lines()
        if self.timeseries is not None:
            lines += self.timeseries.metrics_lines()
        if self.slo is not None:
            lines += self.slo.metrics_lines()
        return "\n".join(lines) + "\n"

    def aggregate_metrics(self) -> str:
        """The fleet /metrics body: per-worker expositions merged with the
        owner's own families (:func:`render_aggregated` — summed series,
        ``worker=``-labeled per-worker copies, one header per family)."""
        texts = [self._scrape_worker(w) for w in self.workers]
        live = sum(1 for t in texts if t is not None)
        return render_aggregated(texts, self._owner_metrics_text(live))

    def status(self) -> dict:
        fp = self.publisher.footprint()
        fingerprint = None
        if self.supervisor is not None and self.supervisor.has_synced():
            try:
                fingerprint = self.supervisor.twin.fingerprint()
            except Exception as e:  # pragma: no cover - racing a rebase
                log.warning("twin fingerprint failed: %s: %s", type(e).__name__, e)
                fingerprint = None
        doc = self.lease.read() if self.lease is not None else None
        age = FleetLease.age_s(doc)
        return {
            "role": "fenced" if self._fenced.is_set() else "owner",
            "epoch": self.lease.epoch if self.lease is not None else 0,
            "lease_age_s": None if age == float("inf") else round(age, 3),
            "generation": self.publisher.last_generation,
            "fingerprint": fingerprint,
            "fenced_writes": fp["fenced_writes"],
            "workers": [
                {
                    "index": w.index,
                    "pid": w.proc.pid if w.proc is not None else (w.pid or None),
                    "alive": w.alive(),
                    "adopted": w.adopted,
                    "internal_port": w.internal_port,
                    "crashes": w.crashes,
                }
                for w in self.workers
            ],
            "target_workers": self.n_workers,
            "respawns_total": self.respawns_total,
            "twin_state": self.supervisor.state() if self.supervisor else "none",
            "shm": fp,
            "control": self.publisher.control.name,
            "port": self.port,
            "admin_port": self.admin_port,
        }

    def healthz(self) -> dict:
        alive = self.alive_workers()
        return {
            "status": "ok" if alive == self.n_workers else "degraded",
            "role": "fenced" if self._fenced.is_set() else "fleet-owner",
            "epoch": self.lease.epoch if self.lease is not None else 0,
            "workers": alive,
            "target": self.n_workers,
            "generation": self.publisher.last_generation,
        }

    def metrics_text(self) -> str:
        return self.aggregate_metrics()

    def timeseries_payload(self, family: str = "",
                           range_s: Optional[float] = None) -> Optional[dict]:
        """``GET /api/debug/timeseries`` body (None → the caller answers
        503: the ring is not running, e.g. a standby's admin surface)."""
        if self.timeseries is None:
            return None
        return {
            "stats": self.timeseries.stats(),
            "samples": self.timeseries.query(family=family, range_s=range_s),
        }

    def slo_payload(self) -> Optional[dict]:
        """``GET /api/fleet/slo`` body (None → 503, no engine)."""
        if self.slo is None:
            return None
        return self.slo.evaluate()

    def start_timeseries(self) -> None:
        """Boot the on-disk time-series ring, the sampler (scraping this
        supervisor's own aggregated exposition) and the SLO engine."""
        from ..obs.slo import SLOEngine
        from ..obs.timeseries import TimeSeriesRing, TimeSeriesSampler

        ts_dir = str(envknobs.value("OPENSIM_TS_DIR") or "") or None
        self.timeseries = TimeSeriesRing(directory=ts_dir)
        self.slo = SLOEngine(self.timeseries)
        self._sampler = TimeSeriesSampler(self.timeseries, self.aggregate_metrics)
        self._sampler.start()

    def alive_workers(self) -> int:
        return sum(1 for w in self.workers if w.alive())

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for w in self.workers:
            if w.adopted:
                log.info(
                    "fleet worker %d adopted from previous owner (pid %d)",
                    w.index, w.pid,
                )
                continue
            self._spawn(w)
        loops = [
            (self._publish_loop, "simon-fleet-publish"),
            (self._monitor_loop, "simon-fleet-monitor"),
        ]
        if self.lease is not None:
            loops.append((self._lease_loop, "simon-fleet-lease"))
        for target, name in loops:
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, drain_s: float = 30.0, keep_workers: bool = False) -> None:
        """SIGTERM drain order: workers first (each drains its admission
        queue and completes in-flight work), then the reflectors, then the
        journal flush, then the shared-memory unlink. ``keep_workers``
        (handover / fenced demotion) leaves them running — they belong to
        the new owner now."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        if not keep_workers:
            for w in self.workers:
                if w.proc is not None and w.proc.poll() is None:
                    with contextlib.suppress(OSError):
                        w.proc.terminate()
                elif w.adopted and w.pid > 0 and _pid_alive(w.pid):
                    with contextlib.suppress(OSError):
                        os.kill(w.pid, signal.SIGTERM)
            deadline = time.monotonic() + drain_s
            for w in self.workers:
                if w.proc is not None:
                    with contextlib.suppress(subprocess.TimeoutExpired):
                        w.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                    if w.proc.poll() is None:
                        log.warning("fleet worker %d did not drain; killing", w.index)
                        with contextlib.suppress(OSError):
                            w.proc.kill()
                            w.proc.wait(timeout=5.0)
                elif w.adopted and w.pid > 0:
                    attempt = 0
                    while _pid_alive(w.pid) and time.monotonic() < deadline:
                        time.sleep(backoff_delay(attempt, base_delay=0.05, max_delay=0.5))
                        attempt += 1
                    if _pid_alive(w.pid):
                        log.warning(
                            "adopted fleet worker %d did not drain; killing", w.index
                        )
                        with contextlib.suppress(OSError):
                            os.kill(w.pid, signal.SIGKILL)
        if self._sampler is not None:
            self._sampler.stop()
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.journal is not None:
            self.journal.close()
        if self.timeseries is not None:
            self.timeseries.close()
        self.publisher.close()


class _RoleBox:
    """Indirection for the admin endpoint across a promotion: the handler
    closes over the box, and serve_standby swaps ``current`` from the
    StandbyOwner to the promoted FleetSupervisor without rebinding the
    HTTP server. Both roles expose healthz()/metrics_text()/status()/
    handover()."""

    def __init__(self, current) -> None:
        self.current = current


def _make_admin_handler(box: _RoleBox):
    class AdminHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet, like the REST handler
            pass

        def _send(self, code: int, data: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            path, _, query = self.path.partition("?")
            role = box.current
            if path == "/api/debug/timeseries":
                from ..obs.timeseries import parse_duration_s

                q = urllib.parse.parse_qs(query)
                try:
                    range_s = parse_duration_s((q.get("range") or [""])[0])
                except ValueError as e:
                    self._send(
                        400, json.dumps({"error": str(e)}).encode(),
                        "application/json",
                    )
                    return
                payload = getattr(role, "timeseries_payload", lambda **kw: None)(
                    family=(q.get("family") or [""])[0], range_s=range_s
                )
                if payload is None:  # standby / ring not running
                    self._send(
                        503, b'{"error": "time-series ring not running"}',
                        "application/json",
                    )
                    return
                self._send(200, json.dumps(payload).encode(), "application/json")
                return
            if path == "/api/fleet/slo":
                payload = getattr(role, "slo_payload", lambda: None)()
                if payload is None:
                    self._send(
                        503, b'{"error": "SLO engine not running"}',
                        "application/json",
                    )
                    return
                self._send(200, json.dumps(payload).encode(), "application/json")
                return
            if path == "/healthz":
                self._send(
                    200, json.dumps(role.healthz()).encode(), "application/json"
                )
            elif path == "/metrics":
                try:
                    text = role.metrics_text()
                except Exception as e:  # a worker roll mid-scrape
                    log.warning("fleet aggregation failed: %s: %s", type(e).__name__, e)
                    self._send(
                        500, json.dumps({"error": str(e)}).encode(), "application/json"
                    )
                    return
                self._send(200, text.encode(), "text/plain; version=0.0.4")
            elif path == "/api/fleet/status":
                self._send(200, json.dumps(role.status()).encode(), "application/json")
            else:
                self._send(404, b'{"error": "not found"}', "application/json")

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            if path == "/api/fleet/handover":
                code, body = box.current.handover()
                self._send(code, json.dumps(body).encode(), "application/json")
            else:
                self._send(404, b'{"error": "not found"}', "application/json")

    return AdminHandler


def serve_fleet(kubeconfig: str, master: str, port: int, watch: str,
                journal: str, workers: int) -> int:
    """``simon server --workers N``: the multi-process serving fleet.
    Called by ``rest.serve`` with already-validated paths. The owner
    process never serves simulate traffic — workers own the public port
    via SO_REUSEPORT; the owner serves the aggregated fleet endpoint on
    the admin port (default: public port + 1)."""
    from .rest import build_twin

    if not kubeconfig or watch == "off":
        print(
            "simon server: --workers needs the live twin "
            "(--kubeconfig and --watch auto|on) — the twin owner is what "
            "the workers attach to", flush=True,
        )
        return 1
    lease: Optional[FleetLease] = None
    if ha_enabled():
        if not journal:
            print(
                "simon server: OPENSIM_HA=1 needs --journal — the standby "
                "tails it and the lease lives beside it (docs/serving.md)",
                flush=True,
            )
            return 1
        # acquire BEFORE build_twin: opening the journal for append
        # truncates a torn tail, which must never race a live owner's
        # writer — the lease is what proves there isn't one
        lease = FleetLease(lease_path(journal))
        if lease.acquire({"control": "", "port": port, "n_workers": workers}) is None:
            print(
                "simon server: HA lease is held by a live owner — start "
                "this process with --standby to tail it instead", flush=True,
            )
            return 1
    try:
        supervisor, jrnl = build_twin(kubeconfig, master, watch, journal)
    except ValueError as e:
        print(f"simon server: {e}", flush=True)
        return 1
    if jrnl is not None:
        # attached BEFORE start(): the twin restores from the newest
        # checkpoint + suffix replay during startup, like the
        # single-process server (SimonServer wires this in its ctor)
        supervisor.attach_journal(jrnl)
    fleet = FleetSupervisor(supervisor, jrnl, port, workers, lease=lease)
    if watch == "on":
        if not supervisor.start(wait_s=60.0):
            print("simon server: --watch on but the twin could not sync", flush=True)
            supervisor.stop()
            fleet.publisher.close()
            return 1
    else:
        supervisor.start()
    box = _RoleBox(fleet)
    httpd = ThreadingHTTPServer(("0.0.0.0", fleet.admin_port), _make_admin_handler(box))
    fleet._on_handover = lambda: threading.Thread(
        target=httpd.shutdown, daemon=True
    ).start()

    def _graceful(signum, frame):
        log.info(
            "fleet received %s; draining workers then owner",
            signal.Signals(signum).name,
        )
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _graceful)
        except ValueError:  # pragma: no cover - embedded use
            break
    fleet.start()
    fleet.start_timeseries()
    print(
        f"simon fleet listening on :{port} [{workers} workers, "
        f"admin :{fleet.admin_port}]"
        + (f" [journal {journal}]" if jrnl is not None else "")
        + (f" [HA epoch {lease.epoch}]" if lease is not None else ""),
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        fleet.stop(keep_workers=fleet.handed_over)
        print(
            "simon fleet: handed over" if fleet.handed_over
            else "simon fleet: shutdown complete",
            flush=True,
        )
    return 0


# ---------------------------------------------------------------------------
# hot standby: tail the journal, take over on lease expiry or handover
# ---------------------------------------------------------------------------


class StandbyOwner:
    """``simon server --standby``: tails the live owner's journal onto a
    private twin (rv-monotonic apply, checkpoint rebases) and watches the
    HA lease. When the lease expires (owner died) or is released with the
    handover flag (rolling upgrade), it acquires the lease at epoch+1,
    builds a real watch supervisor, preloads it with the tailed state
    (resume rvs and all — zero relists), adopts the surviving workers
    recorded in the lease doc, and starts publishing at a continuous
    generation. Exposes the same admin surface as the owner on
    ``port + 16`` (clear of the owner's admin at port+1 and the workers'
    loopback ports above it)."""

    def __init__(self, kubeconfig: str, master: str, port: int, watch: str,
                 journal_dir: str, workers: int,
                 auto_handover: bool = False) -> None:
        from .journal import JournalTailer, RecoveredState
        from .watch import ClusterTwin

        self.kubeconfig = kubeconfig
        self.master = master
        self.port = port
        self.watch = watch
        self.journal_dir = journal_dir
        self.n_workers = workers
        self.admin_port = port + 16
        self.lease = FleetLease(lease_path(journal_dir))
        self.tailer = JournalTailer(journal_dir)
        self.twin = ClusterTwin()
        self.state = RecoveredState()
        self.records_applied = 0
        self.seen_checkpoint = False
        self.seen_owner = False
        self.auto_handover = auto_handover
        self._handover_requested_at = 0.0
        self.fleet: Optional[FleetSupervisor] = None

    # -- tailing -------------------------------------------------------------

    def _drain(self) -> int:
        from .journal import apply_record

        recs = self.tailer.poll()
        for rec in recs:
            apply_record(self.twin, rec, self.state)
            if rec.get("t") == "ck":
                self.seen_checkpoint = True
        self.records_applied += len(recs)
        return len(recs)

    def at_parity(self) -> bool:
        """Caught up enough to take over without a relist: at least one
        checkpoint absorbed (the re-anchor that heals any tail gap) and
        the last poll drained to the journal's end."""
        return self.seen_checkpoint and self.tailer.last_lag_records == 0

    # -- the standby loop ----------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        """Tail until promoted or told to stop. Returns with ``self.fleet``
        set when this process became the owner."""
        poll_s = ha_tail_poll_s()
        while not stop.is_set():
            self._drain()
            doc = self.lease.read()
            if doc is not None:
                self.seen_owner = True
            if self.seen_owner and self.lease.claimable(doc):
                reason = (
                    "handover"
                    if doc is not None and doc.get("handover")
                    else "expired"
                )
                if self._takeover(doc, reason):
                    return
                # lost the acquire race (another standby won): keep tailing
            elif (
                self.auto_handover
                and doc is not None
                and not doc.get("released")
                and self.at_parity()
            ):
                self._maybe_request_handover(doc)
            stop.wait(poll_s)

    def _maybe_request_handover(self, doc: dict) -> None:
        now = time.monotonic()
        if (
            self._handover_requested_at
            and now - self._handover_requested_at < ha_handover_timeout_s()
        ):
            return  # request outstanding; lease-expiry watching is the fallback
        self._handover_requested_at = now
        admin = int(doc.get("admin_port") or 0)
        if not admin:
            return

        def _post():
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{admin}/api/fleet/handover",
                    data=b"", method="POST",
                )
                with urllib.request.urlopen(req, timeout=5.0) as resp:
                    resp.read()
                log.info("standby: requested handover from owner admin :%d", admin)
            except OSError as e:
                log.warning("standby: handover request failed (%s); will retry", e)
                self._handover_requested_at = 0.0

        threading.Thread(
            target=_post, name="simon-standby-handover", daemon=True
        ).start()

    # -- promotion -----------------------------------------------------------

    def _takeover(self, doc: Optional[dict], reason: str) -> bool:
        from .rest import build_twin

        doc = doc or {}
        port = int(doc.get("port") or self.port)
        n = int(doc.get("n_workers") or 0) or self.n_workers
        if self.lease.acquire(
            {"control": "", "port": port, "n_workers": n}
        ) is None:
            log.info("standby: lost the takeover race; remaining standby")
            return False
        log.warning(
            "standby: taking over as owner (reason=%s, epoch %d, "
            "%d tailed records, generation %d)",
            reason, self.lease.epoch, self.records_applied, self.twin.generation,
        )
        # one final drain: whatever the old owner flushed before it went.
        # Opening the journal for APPEND (inside build_twin) truncates any
        # torn tail, so this read must come first — and only runs now that
        # the lease proves no live writer remains.
        self._drain()
        try:
            supervisor, jrnl = build_twin(
                self.kubeconfig, self.master, self.watch, self.journal_dir
            )
        except ValueError as e:
            print(f"simon server[standby]: {e}", flush=True)
            with contextlib.suppress(OSError):
                self.lease.release()
            return False
        stores, gen = self.twin.snapshot_raw()
        self.state.stores = stores
        self.state.generation = max(self.state.generation, gen)
        supervisor.preload_state(self.state)
        if jrnl is not None:
            supervisor.attach_journal(jrnl)
        fleet = FleetSupervisor(
            supervisor, jrnl, port, n, admin_port=self.admin_port,
            lease=self.lease, adopt=list(doc.get("workers") or []),
            takeover_reason=reason,
        )
        if self.watch == "on":
            if not supervisor.start(wait_s=60.0):
                log.warning("standby: twin did not sync after takeover")
        else:
            supervisor.start()
        fleet.start()
        # a fresh ring (or, with OPENSIM_TS_DIR set, the previous owner's
        # re-adopted one) — takeover markers keep accumulating
        fleet.start_timeseries()
        self.fleet = fleet
        return True

    # -- admin surface (same shape as the owner's) ---------------------------

    def healthz(self) -> dict:
        return {
            "status": "ok" if self.at_parity() else "syncing",
            "role": "standby",
            "generation": self.twin.generation,
            "tail_lag_records": self.tailer.last_lag_records,
        }

    def status(self) -> dict:
        seq, offset = self.tailer.position()
        return {
            "role": "standby",
            "fingerprint": self.twin.fingerprint(),
            "generation": self.twin.generation,
            "records_applied": self.records_applied,
            "at_parity": self.at_parity(),
            "tail": {
                "segment": seq,
                "offset": offset,
                "gaps_total": self.tailer.gaps_total,
                "lag_records": self.tailer.last_lag_records,
            },
            "lease": self.lease.read(),
            "admin_port": self.admin_port,
        }

    def metrics_text(self) -> str:
        lines: List[str] = []
        lines += family_header("simon_fleet_standby_tail_lag_records")
        lines.append(
            f"simon_fleet_standby_tail_lag_records {self.tailer.last_lag_records}"
        )
        age = FleetLease.age_s(self.lease.read())
        if age != float("inf"):
            lines += family_header("simon_fleet_lease_age_seconds")
            lines.append(f"simon_fleet_lease_age_seconds {age:.3f}")
        lines += family_header("simon_fleet_takeovers_total")
        return "\n".join(lines) + "\n"

    def handover(self) -> Tuple[int, dict]:
        return 409, {
            "error": "standby does not hold the lease; POST to the owner's "
            "admin port"
        }


def serve_standby(kubeconfig: str, master: str, port: int, watch: str,
                  journal: str, workers: int, handover: bool = False) -> int:
    """``simon server --standby``: run the hot standby until it is
    promoted (then keep serving as the fleet owner) or stopped. With
    ``handover=True`` it asks the live owner to drain once the tail
    reaches parity — the zero-downtime rolling-upgrade path."""
    if not journal:
        print(
            "simon server: --standby needs --journal — the standby tails "
            "the owner's journal (docs/serving.md)", flush=True,
        )
        return 1
    if not kubeconfig or watch == "off":
        print(
            "simon server: --standby needs the live twin (--kubeconfig "
            "and --watch auto|on) to serve after takeover", flush=True,
        )
        return 1
    standby = StandbyOwner(
        kubeconfig, master, port, watch, journal, workers,
        auto_handover=handover,
    )
    box = _RoleBox(standby)
    httpd = ThreadingHTTPServer(
        ("0.0.0.0", standby.admin_port), _make_admin_handler(box)
    )
    threading.Thread(
        target=httpd.serve_forever, name="simon-standby-admin", daemon=True
    ).start()
    stop = threading.Event()

    def _graceful(signum, frame):
        log.info("standby received %s; stopping", signal.Signals(signum).name)
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _graceful)
        except ValueError:  # pragma: no cover - embedded use
            break
    print(
        f"simon standby tailing {journal} [admin :{standby.admin_port}]"
        + (" [auto-handover]" if handover else ""),
        flush=True,
    )
    try:
        standby.run(stop)
    except KeyboardInterrupt:  # pragma: no cover
        stop.set()
    fleet = standby.fleet
    if fleet is None:
        httpd.shutdown()
        print("simon standby: shutdown complete", flush=True)
        return 0
    box.current = fleet
    fleet._on_handover = stop.set
    print(
        f"simon fleet listening on :{fleet.port} [{fleet.n_workers} workers, "
        f"admin :{standby.admin_port}] [HA epoch {standby.lease.epoch}] "
        f"(took over: {fleet.takeover_reason})",
        flush=True,
    )
    try:
        while not stop.is_set():
            stop.wait(0.5)
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        httpd.shutdown()
        fleet.stop(keep_workers=fleet.handed_over)
        print(
            "simon fleet: handed over" if fleet.handed_over
            else "simon fleet: shutdown complete",
            flush=True,
        )
    return 0
