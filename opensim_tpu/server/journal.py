"""Twin time machine — crash-safe watch-event journal (ISSUE 11).

The live twin (server/watch.py) and the capacity history (obs/capacity.py,
obs/timeline.py) are event-sourced but volatile: a crash mid-storm loses the
accepted event stream, the generation-keyed timeline, and every recorded
trace, and the only recovery is a cold full relist. This module makes the
twin durable:

- **append-only segments** under one journal directory
  (``journal-<seq>.seg``), each record framed as ``length || crc32 ||
  payload`` so a torn tail — the normal shape of a crash mid-write — is
  detected by the frame, truncated at the first bad byte, and reported
  loudly instead of poisoning recovery;
- **record types**: ``ev`` (one ACCEPTED twin event — rv-ordered,
  tombstones included, exactly what ``ClusterTwin.apply_event`` took),
  ``rb`` (a list-shaped rebase: 410 recovery or anti-entropy drift repair —
  the store replacement that keeps the file a faithful history), and
  ``ck`` (a checkpoint: full twin snapshot + per-field resume rvs +
  capacity timeline + generation);
- **off-dispatch writer**: ``append()`` is a bounded-queue enqueue — O(1),
  never blocking, never doing I/O — and one writer thread drains it
  (framing, fsync policy, rotation, checkpoints). Journaling must never
  convoy reflector dispatch; the ``make tsan`` hold-time gate is the proof.
  A full queue DROPS the record (counted, logged) and flags the journal for
  re-anchoring: the next checkpoint restores faithfulness, because a
  checkpoint is by construction a complete history prefix;
- **checkpoints + pruning**: every ``OPENSIM_JOURNAL_CHECKPOINT_EVERY``
  event records (and at every size-triggered rotation) the writer thread
  pulls a consistent twin snapshot through ``checkpoint_source`` (object
  references captured under the twin lock, serialized OUTSIDE it), rotates
  to a fresh segment, and writes the checkpoint as that segment's first
  record — so every segment after the first starts with a checkpoint, and
  pruning is simply "delete segments older than the
  ``OPENSIM_JOURNAL_KEEP``-th newest checkpoint segment";
- **fsync policy** (``OPENSIM_JOURNAL_FSYNC``): ``always`` (fsync after
  every drained batch — the crash-test setting), ``interval`` (default;
  fsync at most every ``OPENSIM_JOURNAL_FSYNC_S`` seconds), ``off`` (let
  the OS decide).

Recovery (:meth:`Journal.recover`) finds the newest valid checkpoint,
replays the suffix records after it, and returns the reconstructed state —
resume rvs included, so the reflectors continue from where the stream
actually was. Replay safety is rv-monotonic: a record that raced the
checkpoint (applied before it, written after) re-applies as a no-op, so the
writer queue needs no barrier against the checkpoint snapshot.

Replay (:func:`iter_records` / :func:`rebuild_twin` / :func:`replay_events`)
drives ``simon replay <journal>`` and ``bench.py --config replay``: the twin
at any recorded generation, or the event storm streamed at N× speed into
the scheduler / capacity observatory / a benchmark row.

Chaos points (``OPENSIM_FAULTS``): ``journal.write`` and ``journal.fsync``
fire in the writer thread — the journal degrades loudly (counted, logged)
and the serving path never notices; ``journal.corrupt`` fires at recovery —
a corrupt journal degrades to a full relist with a typed warning, never a
crash.

Lint (OSL1301, docs/static-analysis.md): journal files are opened, written
and fsynced ONLY here, and every record write goes through the one framing
helper (:meth:`Journal._write_framed`) so nothing unchecksummed can enter a
segment.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..obs.fleetobs import FRESHNESS
from ..obs.metrics import RECORDER, family_header, make_counter, make_histogram
from ..resilience import faults
from ..utils import envknobs

log = logging.getLogger("opensim_tpu.server.journal")

__all__ = [
    "Journal",
    "JournalError",
    "JournalTailer",
    "RecoveredState",
    "apply_record",
    "iter_records",
    "journal_policy",
    "rebuild_twin",
    "replay_events",
]

#: segment header: identifies the file format and versions the framing
SEGMENT_MAGIC = b"OSJRNL01"

#: frame header: 4-byte LE payload length + 4-byte LE crc32 of the payload
_FRAME = 8
_LEN_MAX = 1 << 31  # an absurd length in a frame header = corruption


class JournalError(RuntimeError):
    """Typed journal failure: an unusable journal directory at startup, or
    a ``rebuild_twin`` target generation the retained history no longer
    reaches (checkpoint pruning). Recovery paths never raise this to the
    serving path — they degrade to a relist."""


def journal_policy() -> dict:
    """Env-tunable journal knobs, validated loudly like ``watch_policy``
    (an operator typo must surface at startup, not at the first crash):

    - ``OPENSIM_JOURNAL_FSYNC`` (``always|interval|off``, default
      ``interval``): when the writer fsyncs the segment;
    - ``OPENSIM_JOURNAL_FSYNC_S`` (default 1.0): the ``interval`` cadence;
    - ``OPENSIM_JOURNAL_SEGMENT_MB`` (default 64): rotation size bound;
    - ``OPENSIM_JOURNAL_CHECKPOINT_EVERY`` (default 4096): event records
      between checkpoints;
    - ``OPENSIM_JOURNAL_KEEP`` (default 2): checkpoint segments retained by
      pruning (history older than the KEEP-th newest checkpoint is
      unreplayable anyway once its segment is gone);
    - ``OPENSIM_JOURNAL_QUEUE`` (default 65536): writer queue bound — past
      it records are dropped (counted) and the next checkpoint re-anchors.
    """
    fsync = envknobs.raw("OPENSIM_JOURNAL_FSYNC", "interval").strip().lower()
    if fsync not in ("always", "interval", "off"):
        raise ValueError(
            "OPENSIM_JOURNAL_FSYNC must be always|interval|off, got "
            f"{fsync!r}"
        )
    out: dict = {"fsync": fsync}
    for key, env, default, cast in (
        ("fsync_s", "OPENSIM_JOURNAL_FSYNC_S", 1.0, float),
        ("segment_mb", "OPENSIM_JOURNAL_SEGMENT_MB", 64.0, float),
        ("checkpoint_every", "OPENSIM_JOURNAL_CHECKPOINT_EVERY", 4096, int),
        ("keep", "OPENSIM_JOURNAL_KEEP", 2, int),
        ("queue", "OPENSIM_JOURNAL_QUEUE", 65536, int),
    ):
        raw = envknobs.raw(env, str(default))
        try:
            out[key] = cast(raw)
        except ValueError:
            raise ValueError(
                f"{env} must be {'an integer' if cast is int else 'a number'}"
            ) from None
    if out["fsync_s"] <= 0:
        raise ValueError("OPENSIM_JOURNAL_FSYNC_S must be positive")
    if out["segment_mb"] <= 0:
        raise ValueError("OPENSIM_JOURNAL_SEGMENT_MB must be positive")
    if out["checkpoint_every"] < 1:
        raise ValueError("OPENSIM_JOURNAL_CHECKPOINT_EVERY must be >= 1")
    if out["keep"] < 1:
        raise ValueError("OPENSIM_JOURNAL_KEEP must be >= 1")
    if out["queue"] < 1:
        raise ValueError("OPENSIM_JOURNAL_QUEUE must be >= 1")
    return out


def _segment_name(seq: int) -> str:
    return f"journal-{seq:08d}.seg"


def _segment_seq(name: str) -> Optional[int]:
    if not (name.startswith("journal-") and name.endswith(".seg")):
        return None
    try:
        return int(name[len("journal-") : -len(".seg")])
    except ValueError:
        return None


def _encode(record: dict) -> bytes:
    return json.dumps(record, separators=(",", ":"), sort_keys=True).encode()


class RecoveredState:
    """What :meth:`Journal.recover` hands the watch supervisor: enough to
    rebuild the twin and resume the reflectors without a relist."""

    def __init__(self) -> None:
        self.generation: int = 0
        #: {resource field: [raw wire dicts]} — the twin's stores
        self.stores: Dict[str, List[dict]] = {}
        #: {resource field: stream resume rv (string)}
        self.resume_rvs: Dict[str, str] = {}
        #: capacity timeline samples (obs/timeline.Sample dicts, oldest first)
        self.timeline: List[dict] = []
        self.checkpoint_generation: int = 0
        self.records_replayed: int = 0
        self.truncated_bytes: int = 0
        self.outcome: str = "restored"  # restored | empty | corrupt


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------


class Journal:
    """One journal directory: segments, the bounded writer, checkpoints.

    ``readonly=True`` opens for :func:`iter_records`-style access only (the
    replay CLI, crash-recovery assertions from another process) — no writer
    thread, no truncation, no side effects on the files.
    """

    def __init__(
        self,
        path: str,
        policy: Optional[dict] = None,
        readonly: bool = False,
    ) -> None:
        self.path = os.path.abspath(path)
        self.policy = dict(journal_policy(), **(policy or {}))
        self.readonly = readonly
        # telemetry — all families registered in obs/metrics.py (OSL1101),
        # all mutations under the ONE recorder lock
        self.records_total = make_counter("simon_journal_records_total", ("type",))
        self.dropped_total = 0  # guarded-by: RECORDER.lock
        self.bytes_total = 0  # guarded-by: RECORDER.lock
        self.fsync_seconds = make_histogram("simon_journal_fsync_seconds", ())
        self.recoveries = make_counter("simon_journal_recoveries_total", ("outcome",))
        #: set by the supervisor: () -> (stores_by_field objrefs, generation,
        #: timeline sample dicts). Called ONLY from the writer thread; the
        #: provider captures references under the twin lock and this module
        #: serializes them outside it.
        self.checkpoint_source: Optional[Callable[[], tuple]] = None
        self._cond = threading.Condition()
        self._queue: "deque[dict]" = deque()  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cond
        self._flush_waiters = 0  # guarded-by: _cond
        self._degraded = False  # writer thread only
        self._need_reanchor = False  # guarded-by: _cond
        self._f = None  # writer/recovery thread only
        self._seg_seq = 0
        self._seg_bytes = 0
        self._events_since_ck = 0
        self._last_fsync = 0.0
        self._dirty = False
        #: per-field stream cursor the next checkpoint records (journal-side
        #: bookkeeping so checkpoints need nothing from the reflectors)
        self._last_rvs: Dict[str, str] = {}  # guarded-by: _cond
        if not readonly:
            try:
                os.makedirs(self.path, exist_ok=True)
                self._open_for_append()
            except OSError as e:
                # an unusable directory is an operator mistake that must
                # surface at startup, typed — not as a raw OSError mid-boot
                raise JournalError(
                    f"journal directory {self.path} is not usable: {e}"
                ) from e

    # -- segment bookkeeping (writer side) -----------------------------------

    def _segments(self) -> List[str]:
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        out = [(s, n) for n in names if (s := _segment_seq(n)) is not None]
        return [n for _s, n in sorted(out)]

    def _open_for_append(self) -> None:
        """Validate the newest segment's tail (truncating a torn frame,
        loudly) and position the writer after the last good record."""
        segs = self._segments()
        if not segs:
            self._start_segment(1)
            return
        last = segs[-1]
        path = os.path.join(self.path, last)
        good = self._scan_segment(path, collect=None)
        size = os.path.getsize(path)
        if good < size:
            log.warning(
                "journal %s: torn tail — truncating %d byte(s) after the "
                "last valid frame (crash mid-write is the expected cause)",
                last, size - good,
            )
            with open(path, "r+b") as f:
                f.truncate(good)
        self._seg_seq = _segment_seq(last) or 1
        self._f = open(path, "ab")
        if self._f.tell() == 0:
            # the whole file (magic included) was torn away: re-stamp it
            self._f.write(SEGMENT_MAGIC)
            self._f.flush()
        self._seg_bytes = self._f.tell()
        # a fresh process re-anchors with a checkpoint soon regardless of
        # the event cadence: recovery from this journal must not have to
        # replay an unbounded pre-crash suffix again next time
        self._events_since_ck = self.policy["checkpoint_every"]

    def _start_segment(self, seq: int) -> None:
        if self._f is not None:
            self._f.close()
        self._seg_seq = seq
        path = os.path.join(self.path, _segment_name(seq))
        self._f = open(path, "ab")
        if self._f.tell() == 0:
            # flush immediately: the file on disk must never be observable
            # magic-less (recovery scans the physical bytes, not this buffer)
            self._f.write(SEGMENT_MAGIC)
            self._f.flush()
        self._seg_bytes = self._f.tell()

    def _scan_segment(self, path: str, collect: Optional[list]) -> int:
        """Walk one segment's frames; append decoded records to ``collect``
        (when given) and return the byte offset after the last VALID frame.
        Every corruption mode — bad magic, short header, absurd length,
        crc mismatch, broken JSON — stops the walk at the last good byte."""
        try:
            with open(path, "rb") as f:
                magic = f.read(len(SEGMENT_MAGIC))
                if magic != SEGMENT_MAGIC:
                    if magic:  # an EMPTY file is merely unwritten, not corrupt
                        log.warning("journal segment %s: bad magic; ignoring file", path)
                    return 0
                good = f.tell()
                while True:
                    hdr = f.read(_FRAME)
                    if len(hdr) < _FRAME:
                        return good
                    length = int.from_bytes(hdr[:4], "little")
                    crc = int.from_bytes(hdr[4:8], "little")
                    if length <= 0 or length >= _LEN_MAX:
                        return good
                    payload = f.read(length)
                    if len(payload) < length or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                        return good
                    if collect is not None:
                        try:
                            collect.append(json.loads(payload))
                        except ValueError:
                            return good
                    good = f.tell()
        except OSError as e:
            log.warning("journal segment %s unreadable: %s", path, e)
            return 0

    # -- append side (any thread; O(1), no I/O) ------------------------------

    def _enqueue(self, record: dict) -> None:
        with self._cond:
            if self._closed:
                return
            if len(self._queue) >= self.policy["queue"]:
                # shedding is the honest failure: blocking here would convoy
                # reflector dispatch behind disk I/O. The drop is counted
                # and the next checkpoint re-anchors the history.
                self._need_reanchor = True
                with RECORDER.lock:
                    self.dropped_total += 1
                    dropped = self.dropped_total
                if dropped == 1 or dropped % 1000 == 0:
                    log.warning(
                        "journal writer queue full (%d dropped so far); "
                        "history re-anchors at the next checkpoint",
                        dropped,
                    )
                return
            self._queue.append(record)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="simon-journal", daemon=True
                )
                self._thread.start()
            self._cond.notify()

    def record_event(self, field: str, ev_type: str, obj: dict, generation: int,
                     eid: str = "", ts: Optional[float] = None) -> None:
        """One ACCEPTED twin event (``apply_event`` returned a change).
        ``eid``/``ts`` are the fleet-trace acceptance stamp (ISSUE 20):
        the id rides the record so replay and the flight recorder can
        correlate journal lines with stitched request traces."""
        rv = str(((obj.get("metadata") or {}).get("resourceVersion")) or "")
        rec = {"t": "ev", "ts": ts if ts is not None else time.time(),
               "gen": generation, "f": field, "k": ev_type, "o": obj}
        if eid:
            rec["eid"] = eid
        with self._cond:
            if rv:
                self._last_rvs[field] = rv
        self._enqueue(rec)

    def record_rebase(
        self, field: str, items: List[dict], generation: int,
        rv: str = "", why: str = "",
    ) -> None:
        """A list-shaped store replacement (410 relist, anti-entropy drift
        repair): replay applies it as ``ClusterTwin.rebase``."""
        rec = {"t": "rb", "ts": time.time(), "gen": generation, "f": field,
               "rv": rv, "why": why, "items": items}
        with self._cond:
            if rv:
                self._last_rvs[field] = rv
        self._enqueue(rec)

    def record_checkpoint(
        self,
        stores: Dict[str, List[dict]],
        generation: int,
        resume_rvs: Optional[Dict[str, str]] = None,
        timeline: Optional[List[dict]] = None,
        why: str = "",
    ) -> None:
        """An explicit checkpoint (bootstrap, post-recovery re-anchor). The
        periodic cadence checkpoints come from the writer thread via
        ``checkpoint_source`` instead."""
        rvs = dict(resume_rvs or {})
        with self._cond:
            # the per-event stream cursor wins over the caller's (listing /
            # restore-time) rvs: it only ever moves forward, and resuming a
            # touch early merely re-delivers events the rv-monotonic apply
            # no-ops. The merge then SEEDS the cursor map, so later cadence
            # checkpoints keep resume rvs for resources with no events
            self._last_rvs.update(
                {f: rv for f, rv in rvs.items() if f not in self._last_rvs}
            )
            rvs.update(self._last_rvs)
        rec = {"t": "ck", "ts": time.time(), "gen": generation, "why": why,
               "rvs": rvs, "timeline": list(timeline or []), "stores": stores}
        self._enqueue(rec)

    def flush(self, timeout: float = 30.0) -> bool:
        """Drain the queue and fsync — the graceful-shutdown barrier.
        Returns False when the writer could not finish in time. The waiter
        stays registered until the segment is SYNCED, not merely drained:
        the writer's wake predicate forces an fsync for a registered
        waiter regardless of the fsync policy (mode ``off`` would
        otherwise park forever with dirty bytes)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._flush_waiters += 1
            self._cond.notify_all()
            try:
                while (self._queue or self._dirty) and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._cond.wait(min(0.1, remaining))
            finally:
                self._flush_waiters -= 1
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Flush, fsync, stop the writer. Idempotent."""
        if self.readonly:
            return
        self.flush(timeout=timeout)
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    # -- writer thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while (
                    not self._queue
                    and not self._closed
                    # a flush() waiter with unsynced bytes must wake the
                    # writer regardless of fsync policy (mode "off" would
                    # otherwise park here forever and hang close())
                    and not (self._dirty and self._flush_waiters)
                ):
                    if self._dirty and self.policy["fsync"] == "interval":
                        # idle with unsynced bytes: wait at most the fsync
                        # cadence, then sync below
                        self._cond.wait(self.policy["fsync_s"])
                        break
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                batch = []
                while self._queue:
                    batch.append(self._queue.popleft())
                reanchor = self._need_reanchor
                self._need_reanchor = False
                flushing = self._flush_waiters > 0
            for rec in batch:
                try:
                    self._write_record(rec)
                    self._degraded = False
                except Exception as e:
                    # a lost record makes the suffix unfaithful: count it as
                    # a drop and flag re-anchoring — the next checkpoint is
                    # by construction a complete history prefix again
                    with self._cond:
                        self._need_reanchor = True
                    with RECORDER.lock:
                        self.dropped_total += 1
                    if not self._degraded:
                        self._degraded = True
                        log.warning(
                            "journal writer degraded (%s: %s): record "
                            "dropped; the twin keeps serving and history "
                            "re-anchors at the next checkpoint — recovery "
                            "falls back to a relist past this point",
                            type(e).__name__, e,
                        )
            try:
                if (
                    self.checkpoint_source is not None
                    and not self._degraded
                    and (
                        reanchor
                        or self._events_since_ck >= self.policy["checkpoint_every"]
                        or self._seg_bytes >= self.policy["segment_mb"] * 1024 * 1024
                    )
                ):
                    self._write_checkpoint()
                self._maybe_fsync(force=flushing or self.policy["fsync"] == "always")
            except Exception as e:
                if not self._degraded:
                    self._degraded = True
                    log.warning(
                        "journal writer degraded (%s: %s): checkpoint/fsync "
                        "failed; durability is behind until the next "
                        "successful sync", type(e).__name__, e,
                    )
            with self._cond:
                if not self._queue:
                    self._cond.notify_all()

    def _write_record(self, rec: dict) -> None:
        faults.fault_point("journal.write")
        payload = _encode(rec)
        self._write_framed(payload)
        self._dirty = True
        with RECORDER.lock:
            self.records_total.inc((rec["t"],))
            self.bytes_total += len(payload) + _FRAME
            if rec["t"] == "ev" and rec.get("eid"):
                # journaled stage of the freshness pipeline: the stamped
                # acceptance time is in the record itself (RECORDER.lock
                # is an RLock; FRESHNESS shares it)
                FRESHNESS.event_journaled(float(rec["ts"]))
        if rec["t"] == "ev":
            self._events_since_ck += 1
        elif rec["t"] == "ck":
            # ANY checkpoint (explicit bootstrap/recovered re-anchor or the
            # writer's own cadence) restarts the cadence clock — without
            # this, a restart's explicit checkpoint is immediately followed
            # by a duplicate O(cluster) cadence one
            self._events_since_ck = 0

    def _write_framed(self, payload: bytes) -> None:
        """THE one framing path (lint OSL1301): length + crc32 + payload.
        Nothing else in this repo writes journal bytes."""
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._f.write(
            len(payload).to_bytes(4, "little") + crc.to_bytes(4, "little") + payload
        )
        self._seg_bytes += len(payload) + _FRAME

    def _maybe_fsync(self, force: bool = False) -> None:
        mode = self.policy["fsync"]
        if not self._dirty or self._f is None:
            return
        now = time.monotonic()
        if not force and (
            mode == "off"
            or (mode == "interval" and now - self._last_fsync < self.policy["fsync_s"])
        ):
            self._f.flush()
            return
        t0 = time.monotonic()
        faults.fault_point("journal.fsync")
        self._f.flush()
        os.fsync(self._f.fileno())
        self._last_fsync = now
        self._dirty = False
        with RECORDER.lock:
            self.fsync_seconds.observe(time.monotonic() - t0, ())

    def _write_checkpoint(self) -> None:
        """Cadence checkpoint from the writer thread: pull a consistent
        snapshot, rotate, write it as the new segment's first record, prune.
        Raw dicts are serialized HERE — outside every supervisor lock."""
        got = self.checkpoint_source()
        if got is None:
            return
        stores_objs, generation, timeline = got
        stores = {
            field: [getattr(o, "raw", None) or {} for o in objs]
            for field, objs in stores_objs.items()
        }
        with self._cond:
            rvs = dict(self._last_rvs)
        rec = {"t": "ck", "ts": time.time(), "gen": generation, "why": "cadence",
               "rvs": rvs, "timeline": list(timeline or []), "stores": stores}
        self._rotate_and_checkpoint(rec)

    def _rotate_and_checkpoint(self, rec: dict) -> None:
        self._maybe_fsync(force=self.policy["fsync"] != "off")
        self._start_segment(self._seg_seq + 1)
        self._write_record(rec)
        self._events_since_ck = 0
        self._maybe_fsync(force=self.policy["fsync"] != "off")
        self._prune()

    def _prune(self) -> None:
        """Delete segments older than the KEEP-th newest checkpoint segment.
        Every segment after the first starts with a checkpoint (rotation
        happens exactly at checkpoint time), so 'the newest K checkpoint
        segments and everything after the oldest of them' is a complete,
        self-contained history."""
        segs = self._segments()
        ck_segs = []
        for name in segs:
            first = self._first_record_type(os.path.join(self.path, name))
            if first == "ck":
                ck_segs.append(name)
        if len(ck_segs) <= self.policy["keep"]:
            return
        floor = ck_segs[-self.policy["keep"]]
        floor_seq = _segment_seq(floor) or 0
        for name in segs:
            seq = _segment_seq(name) or 0
            if seq < floor_seq:
                try:
                    os.unlink(os.path.join(self.path, name))
                    log.info("journal: pruned segment %s (checkpointed past it)", name)
                except OSError as e:
                    log.warning("journal: failed to prune %s: %s", name, e)

    def _first_record_type(self, path: str) -> str:
        try:
            with open(path, "rb") as f:
                if f.read(len(SEGMENT_MAGIC)) != SEGMENT_MAGIC:
                    return ""
                hdr = f.read(_FRAME)
                if len(hdr) < _FRAME:
                    return ""
                length = int.from_bytes(hdr[:4], "little")
                crc = int.from_bytes(hdr[4:8], "little")
                if length <= 0 or length >= _LEN_MAX:
                    return ""
                payload = f.read(length)
                if len(payload) < length or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    return ""
                return str(json.loads(payload).get("t") or "")
        except (OSError, ValueError):
            return ""

    # -- read side -----------------------------------------------------------

    def iter_records(self) -> Iterator[dict]:
        """All valid records across all segments, in order. The walk stops
        at the first bad frame (torn tail / corruption): records past a
        corrupt point are unreachable history and are never yielded."""
        for name in self._segments():
            path = os.path.join(self.path, name)
            collected: List[dict] = []
            good = self._scan_segment(path, collect=collected)
            for rec in collected:
                yield rec
            try:
                if good < os.path.getsize(path):
                    # corruption mid-stream: everything after is suspect
                    log.warning(
                        "journal %s: stopping replay at a bad frame "
                        "(%d valid byte(s))", name, good,
                    )
                    return
            except OSError:
                return

    def recover(self) -> Optional[RecoveredState]:
        """Reconstruct the newest twin state: the newest valid checkpoint
        plus every record after it. Returns None when the journal holds no
        usable state (empty, or corrupt before the first checkpoint) — the
        caller falls back to a cold relist. NEVER raises for data-shaped
        problems; corruption degrades, loudly."""
        try:
            faults.fault_point("journal.corrupt")
            state = self._recover_inner()
        except Exception as e:
            log.warning(
                "journal recovery failed (%s: %s); degrading to a full "
                "relist — the journal stays in place for post-mortem",
                type(e).__name__, e,
            )
            with RECORDER.lock:
                self.recoveries.inc(("corrupt",))
            return None
        with RECORDER.lock:
            self.recoveries.inc((state.outcome if state else "empty",))
        return state

    def _recover_inner(self) -> Optional[RecoveredState]:
        ck: Optional[dict] = None
        suffix: List[dict] = []
        n = 0
        for rec in self.iter_records():
            n += 1
            if rec.get("t") == "ck":
                ck = rec
                suffix = []
            else:
                suffix.append(rec)
        if ck is None and not suffix:
            return None
        state = RecoveredState()
        if ck is None:
            # events with no checkpoint: the history has no complete prefix
            # (the bootstrap checkpoint was lost) — a relist is the only
            # faithful recovery
            log.warning(
                "journal holds %d record(s) but no checkpoint; a full "
                "relist is the only faithful recovery", n,
            )
            return None
        state.checkpoint_generation = int(ck.get("gen") or 0)
        state.generation = state.checkpoint_generation
        state.stores = {f: list(items) for f, items in (ck.get("stores") or {}).items()}
        state.resume_rvs = {str(k): str(v) for k, v in (ck.get("rvs") or {}).items()}
        state.timeline = list(ck.get("timeline") or [])
        if suffix:
            # replay the suffix through a real twin: rv-monotonic apply
            # makes records that raced the checkpoint no-ops
            twin = _new_twin()
            for field, items in state.stores.items():
                twin.rebase(field, items)
            for rec in suffix:
                _apply_record(twin, rec, state)
            state.generation = max(state.generation, twin.generation)
            state.stores = _twin_stores_raw(twin)
            state.records_replayed = len(suffix)
        return state

    def queue_occupancy(self) -> Tuple[int, int]:
        """``(depth, bound)`` of the bounded writer queue — the memory
        observatory's ring-occupancy view (obs/footprint.py)."""
        with self._cond:
            return len(self._queue), int(self.policy["queue"])

    # -- /metrics ------------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        with RECORDER.lock:
            lines = self.records_total.render_lines()
            if not lines:
                lines = family_header("simon_journal_records_total")
            lines += [
                *family_header("simon_journal_bytes_total"),
                f"simon_journal_bytes_total {self.bytes_total}",
                *family_header("simon_journal_dropped_total"),
                f"simon_journal_dropped_total {self.dropped_total}",
            ]
            lines += self.fsync_seconds.render_lines()
            rec = self.recoveries.render_lines()
            if not rec:
                rec = family_header("simon_journal_recoveries_total")
            lines += rec
        return lines


# ---------------------------------------------------------------------------
# replay helpers (the CLI, bench.py --config replay, recovery)
# ---------------------------------------------------------------------------


def _new_twin():
    # local import: watch.py imports this module at top level
    from .watch import ClusterTwin

    return ClusterTwin()


def _twin_stores_raw(twin) -> Dict[str, List[dict]]:
    return twin.snapshot_raw()[0]


def _apply_record(twin, rec: dict, state: Optional[RecoveredState] = None):
    """Apply one record to a replay twin; returns the ``apply_event``
    change verdict for ``ev`` records (None otherwise) so replay consumers
    (the capacity feed) can ride the same O(1) delta path the live
    dispatch does."""
    t = rec.get("t")
    change = None
    if t == "ev":
        change = twin.apply_event(
            str(rec.get("f") or ""), str(rec.get("k") or ""), rec.get("o") or {}
        )
        rv = str(((rec.get("o") or {}).get("metadata") or {}).get("resourceVersion") or "")
        if state is not None and rv:
            state.resume_rvs[str(rec.get("f") or "")] = rv
    elif t == "rb":
        twin.rebase(str(rec.get("f") or ""), list(rec.get("items") or []))
        if state is not None and rec.get("rv"):
            state.resume_rvs[str(rec.get("f") or "")] = str(rec["rv"])
    # the journal's generation numbering is authoritative on replay: the
    # twin's own increments (one per store surgery) can differ from the live
    # sequence around list-shaped records
    gen = rec.get("gen")
    if isinstance(gen, int) and gen >= twin.generation:
        twin.generation = gen
    if state is not None:
        ts = rec.get("timeline")
        if ts:
            state.timeline.extend(ts)
    return change


def apply_record(twin, rec: dict, state: Optional[RecoveredState] = None):
    """Apply ANY record type to a consumer twin — the standby tailer's
    apply primitive (server/fleet.py). ``ev``/``rb`` ride
    :func:`_apply_record` (rv-monotonic, generation-overlaid); a ``ck``
    rebases the twin wholesale, exactly like :func:`replay_events` does —
    a checkpoint is an authoritative full snapshot, and applying it is
    what heals a tailer that lost records to a pruned gap."""
    if rec.get("t") != "ck":
        return _apply_record(twin, rec, state)
    for field, items in (rec.get("stores") or {}).items():
        twin.rebase(field, list(items))
    gen = rec.get("gen")
    if isinstance(gen, int) and gen >= twin.generation:
        twin.generation = gen
    if state is not None:
        for f, rv in (rec.get("rvs") or {}).items():
            state.resume_rvs[str(f)] = str(rv)
        ts = rec.get("timeline")
        if ts:
            state.timeline = list(ts)
        state.checkpoint_generation = int(gen or 0)
    return None


class JournalTailer:
    """Live segment-follow reader over a journal directory ANOTHER process
    is appending to — the HA standby's feed (docs/serving.md "Surviving
    owner loss & rolling upgrades"). Strictly read-only: never truncates,
    never writes, never takes the writer's locks.

    Follow semantics per :meth:`poll`:

    - complete CRC-framed records after the remembered offset are drained
      in order; the offset advances only past VALID frames;
    - an **incomplete tail frame** (short header or short payload — the
      writer is mid-append, or crashed there) is left unconsumed: the next
      poll re-reads from the same offset once the bytes land;
    - **rotation**: when a newer segment exists, the current one is
      finished history — whatever valid frames remain are drained, then
      the tailer moves on. A torn/corrupt tail abandoned by a crashed
      writer is skipped the same way, which is safe because every segment
      after the first STARTS with a checkpoint and :func:`apply_record`
      rebases on checkpoints (the overlap re-applies as rv-monotonic
      no-ops);
    - **pruning**: when the tailer's segment vanished underneath it (the
      writer pruned past it) or shrank below the offset (a takeover
      truncated a torn tail), it re-anchors — oldest surviving segment,
      offset 0 — and counts the gap; the first record there is a
      checkpoint, so the consumer's twin snaps back to truth.

    Chaos point ``journal.tail_gap`` drops one drained batch on the floor
    (counted in ``gaps_total``): the deterministic stand-in for a tailer
    that fell off the pruned end of history, proving the
    checkpoint-rebase healing path in ``make chaos``.
    """

    def __init__(self, path: str) -> None:
        self.path = os.path.abspath(path)
        self._seq: Optional[int] = None  # segment being followed
        self._offset = 0  # byte offset after the last valid frame
        self.records_total = 0
        self.gaps_total = 0
        #: records drained by the last poll — how far the consumer had
        #: fallen behind (simon_fleet_standby_tail_lag_records)
        self.last_lag_records = 0
        self.last_stop = ""  # incomplete | invalid | "" (clean EOF)

    def _seg_seqs(self) -> List[int]:
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        return sorted(s for n in names if (s := _segment_seq(n)) is not None)

    def _read_from(self, seq: int, offset: int) -> Tuple[List[dict], int, str]:
        """Drain valid frames from segment ``seq`` starting at ``offset``.
        Returns ``(records, new_offset, stop)`` where stop is
        ``"incomplete"`` (short tail — wait for the writer), ``"invalid"``
        (corruption — only a newer segment can unblock), or ``""`` (clean
        EOF). A magic-less prefix is ``"incomplete"`` too: the writer
        stamps the magic on segment creation, so its absence means the
        file is younger than its own header flush."""
        path = os.path.join(self.path, _segment_name(seq))
        out: List[dict] = []
        try:
            with open(path, "rb") as f:
                if offset < len(SEGMENT_MAGIC):
                    magic = f.read(len(SEGMENT_MAGIC))
                    if len(magic) < len(SEGMENT_MAGIC):
                        return out, offset, "incomplete"
                    if magic != SEGMENT_MAGIC:
                        return out, offset, "invalid"
                    offset = f.tell()
                else:
                    f.seek(offset)
                while True:
                    hdr = f.read(_FRAME)
                    if len(hdr) < _FRAME:
                        return out, offset, "incomplete" if hdr else ""
                    length = int.from_bytes(hdr[:4], "little")
                    crc = int.from_bytes(hdr[4:8], "little")
                    if length <= 0 or length >= _LEN_MAX:
                        return out, offset, "invalid"
                    payload = f.read(length)
                    if len(payload) < length:
                        return out, offset, "incomplete"
                    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                        return out, offset, "invalid"
                    try:
                        out.append(json.loads(payload))
                    except ValueError:
                        return out, offset, "invalid"
                    offset = f.tell()
        except FileNotFoundError:
            return out, offset, "invalid"
        except OSError as e:
            log.warning("journal tail: segment %s unreadable: %s", path, e)
            return out, offset, "invalid"

    def poll(self) -> List[dict]:
        """All records that became readable since the last poll, in order.
        Empty when the writer is idle (or mid-frame). Never raises for
        data-shaped problems — gaps are counted and healed by the next
        checkpoint the stream carries."""
        batch: List[dict] = []
        for _hop in range(64):  # bound: segments crossed per poll
            seqs = self._seg_seqs()
            if not seqs:
                break
            if self._seq is None:
                self._seq, self._offset = seqs[0], 0
            elif self._seq not in seqs:
                # pruned out from under us: re-anchor at the oldest
                # survivor — its first record is a checkpoint
                self.gaps_total += 1
                log.warning(
                    "journal tail: segment %d pruned underneath the tailer; "
                    "re-anchoring at segment %d (the checkpoint there heals "
                    "the gap)", self._seq, seqs[0] if self._seq < seqs[0] else seqs[-1],
                )
                newer = [s for s in seqs if s > self._seq]
                self._seq, self._offset = (newer[0] if newer else seqs[0]), 0
            else:
                # a takeover's torn-tail truncation can shrink the file
                # below our offset: re-read the whole segment (checkpoint
                # first records + rv-monotonic apply make the re-read safe)
                try:
                    size = os.path.getsize(
                        os.path.join(self.path, _segment_name(self._seq))
                    )
                except OSError:
                    size = 0
                if size < self._offset:
                    self.gaps_total += 1
                    self._offset = 0
            recs, self._offset, stop = self._read_from(self._seq, self._offset)
            batch.extend(recs)
            self.last_stop = stop
            newer = [s for s in seqs if s > self._seq]
            if newer:
                # rotation (or an abandoned torn tail): this segment is
                # finished history — move on; a skipped bad tail is healed
                # by the next segment's leading checkpoint
                if stop == "invalid" or not recs:
                    if stop == "invalid":
                        self.gaps_total += 1
                    self._seq, self._offset = newer[0], 0
                continue  # drain again: more may have landed meanwhile
            break
        if batch:
            try:
                faults.fault_point("journal.tail_gap")
            except Exception as e:
                self.gaps_total += 1
                log.warning(
                    "journal tail: injected gap (%s): %d record(s) dropped; "
                    "the next checkpoint rebases the consumer back to truth",
                    e, len(batch),
                )
                self.last_lag_records = 0
                return []
        self.last_lag_records = len(batch)
        self.records_total += len(batch)
        return batch

    def position(self) -> Tuple[Optional[int], int]:
        """(segment seq, byte offset) after the last drained frame."""
        return self._seq, self._offset


def iter_records(path: str) -> Iterator[dict]:
    """Read-only record iteration over a journal directory."""
    return Journal(path, readonly=True).iter_records()


def rebuild_twin(path: str, at_generation: Optional[int] = None):
    """Reconstruct the twin at ``at_generation`` (or the newest state):
    start from the newest checkpoint at-or-before the target and replay the
    suffix up to it. Returns ``(twin, meta)`` where meta summarizes the
    replayed window."""
    # two streaming passes so a multi-segment journal (every checkpoint a
    # full twin snapshot) is never held in memory at once: pass 1 indexes
    # the newest qualifying checkpoint and counts, pass 2 applies from it
    ck_idx = None
    oldest_ck_gen: Optional[int] = None
    meta = {"records": 0, "events": 0, "rebases": 0, "checkpoints": 0, "replayed": 0}
    for i, rec in enumerate(iter_records(path)):
        meta["records"] += 1
        t = rec.get("t")
        if t == "ev":
            meta["events"] += 1
        elif t == "rb":
            meta["rebases"] += 1
        elif t == "ck":
            meta["checkpoints"] += 1
            gen = int(rec.get("gen") or 0)
            if oldest_ck_gen is None:
                oldest_ck_gen = gen
            if at_generation is None or gen <= at_generation:
                ck_idx = i
    twin = _new_twin()
    start = 0 if ck_idx is None else ck_idx
    for i, rec in enumerate(iter_records(path)):
        if i < start:
            continue
        if i == ck_idx:
            for field, items in (rec.get("stores") or {}).items():
                twin.rebase(field, list(items))
            gen = rec.get("gen")
            if isinstance(gen, int):
                twin.generation = gen
            continue
        if rec.get("t") == "ck":
            continue
        gen = rec.get("gen")
        if at_generation is not None and isinstance(gen, int) and gen > at_generation:
            break
        _apply_record(twin, rec)
        meta["replayed"] += 1
    if at_generation is not None and ck_idx is None and meta["records"] and not meta["replayed"]:
        # checkpoint pruning dropped the prefix the target lives in: an
        # empty twin here would be valid-shaped but wrong — fail loudly
        raise JournalError(
            f"{path}: generation {at_generation} predates the retained "
            f"history (oldest surviving checkpoint is generation "
            f"{oldest_ck_gen}; older segments were pruned)"
        )
    meta["generation"] = twin.generation
    return twin, meta


def replay_events(
    path: str,
    speed: float = 0.0,
    at_generation: Optional[int] = None,
) -> Iterator[Tuple[dict, "object", Optional[tuple]]]:
    """Stream ``(record, twin, change)`` triples, applying each record to a
    live twin as it goes — the engine behind ``simon replay`` and the
    event-storm benchmark (``change`` is the ``apply_event`` verdict for
    event records, None for list-shaped ones; the capacity feed rides it).
    ``speed`` > 0 paces the stream at N× the recorded inter-record gaps; 0
    replays as fast as possible. Pacing gaps are clamped to 30s so a
    journal spanning an idle night replays in bounded time."""
    twin = _new_twin()
    prev_ts: Optional[float] = None
    for rec in iter_records(path):
        gen = rec.get("gen")
        if at_generation is not None and isinstance(gen, int) and gen > at_generation:
            return
        if rec.get("t") == "ck":
            # EVERY checkpoint rebases the replay twin: a checkpoint is an
            # authoritative full snapshot, and a mid-history re-anchor (the
            # repair written after a writer-queue drop) is exactly the
            # record that restores faithfulness — skipping it would replay
            # the gap the journal already healed
            for field, items in (rec.get("stores") or {}).items():
                twin.rebase(field, list(items))
            if isinstance(gen, int) and gen >= twin.generation:
                twin.generation = gen
            yield rec, twin, None
            continue
        if speed > 0 and prev_ts is not None:
            ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                gap = min(30.0, max(0.0, float(ts) - prev_ts)) / speed
                if gap > 0:
                    time.sleep(gap)
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            prev_ts = float(ts)
        change = _apply_record(twin, rec)
        yield rec, twin, change
