"""REST server — parity with ``pkg/server/server.go``: ``GET /healthz``,
``POST /api/deploy-apps``, ``POST /api/scale-apps`` with the exact request/
response DTOs (``server.go:48-93``) so existing clients can switch backends.

Implementation notes vs the reference:
- stdlib ``http.server`` replaces gin (no third-party web framework in the
  image); single-flight busy rejection mirrors the TryLock 503 behavior
  (``server.go:167,:234``).
- The live-cluster informer snapshot is taken per request via the
  Kubernetes Python client when a kubeconfig is configured; without one, the
  server can still serve simulations whose requests carry their own nodes
  (useful for testing and air-gapped use — a divergence the reference
  doesn't offer).
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:
    from ..engine.prepcache import PrepareCache

from ..engine.simulator import AppResource, SimulateResult, simulate
from ..models.objects import LABEL_APP_NAME, Node, ResourceTypes, object_from_dict
from ..obs import trace as tracing
from ..obs.metrics import RECORDER, escape_label_value, family_header
from ..obs.recorder import FLIGHT_RECORDER
from ..resilience import breaker as breaker_mod
from ..resilience import faults
from ..resilience.deadline import Deadline, DeadlineExceeded, check_deadline, deadline_scope
from ..resilience.retry import retry_call
from ..utils import envknobs
from .snapshot import (
    SnapshotFetchError,
    SnapshotUnavailable,
    cluster_from_kubeconfig,
    snapshot_retry_policy,
)

log = logging.getLogger("opensim_tpu.server")
# structured access log (OPENSIM_ACCESS_LOG=1): one JSON object per line
_ACCESS_LOG = logging.getLogger("opensim_tpu.access")

_deploy_lock = threading.Lock()  # lockwatch: hold-exempt — single-flight, spans engine work incl. first XLA compile
_scale_lock = threading.Lock()  # lockwatch: hold-exempt — single-flight, spans engine work incl. first XLA compile

# per-request state (one HTTP request = one handler thread): whether THIS
# request's result was computed from a stale snapshot. Reading the shared
# SimonServer flag at send time would mis-tag responses when a concurrent
# request's refresh flips it mid-flight.
_REQUEST_STATE = threading.local()


def _mark_request_snapshot(stale: bool) -> None:
    _REQUEST_STATE.snapshot_stale = stale


def request_served_stale() -> bool:
    """Did the current thread's request get served from a stale snapshot?"""
    return getattr(_REQUEST_STATE, "snapshot_stale", False)


def response_extra_headers() -> dict:
    """Extra response headers the current thread's request accumulated
    (e.g. ``Retry-After`` on an admission shed) — reset per request by the
    handler, merged into ``_send``."""
    return getattr(_REQUEST_STATE, "extra_headers", {}) or {}


def last_request_id() -> str:
    """The request id assigned to the current thread's request (honored from
    ``X-Simon-Request-Id`` if the client sent one, generated otherwise) —
    echoed back in the response header by the handler."""
    return getattr(_REQUEST_STATE, "request_id", "")


class _Metrics:
    """Process-local counters exposed at /metrics in Prometheus text format
    (the reference's vendored scheduler metrics exist but are never exposed;
    SURVEY.md §5 — this closes that gap).

    Locking (ISSUE 5 bugfix): every mutation routes through the ONE
    recorder RLock shared with the span sink and latency histograms
    (``obs.metrics.RECORDER``) — counters are bumped both from ``_handle``
    and from snapshot-retry callbacks on other code paths, and the old
    per-object lock left render() assembling a scrape interleaved with
    recordings. Label values are escaped per the exposition format so a
    hostile endpoint/path string cannot corrupt the scrape."""

    def __init__(self) -> None:
        self.lock = RECORDER.lock  # the one metrics lock (an RLock)
        self.requests = {"deploy-apps": 0, "scale-apps": 0}
        self.simulations = 0
        self.pods_scheduled = 0
        self.pods_unscheduled = 0
        # resilience counters (docs/resilience.md): deadline 504s, snapshot
        # fetch retries/degradations, stale-prep-cache internal retries
        self.request_timeouts = 0
        self.snapshot_retries = 0
        self.snapshot_stale_served = 0
        self.stale_prep_retries = 0
        # C++ engine path attribution (ISSUE 4): scheduled steps served by
        # the incremental cache vs the generic re-evaluation — a silent
        # cache disengage shows up here, not just in wall-clock
        self.native_steps = {"incremental": 0, "generic": 0}
        # bail-reason attribution (abi v5): WHY the incremental envelope
        # disengaged, keyed by nativepath._BAIL_REASONS (sparse — only
        # reasons actually seen), and which carry classes the incremental
        # steps actually exercised (nativepath._CARRY_CLASSES)
        self.native_bails: dict = {}
        self.native_classes: dict = {}

    def record(self, endpoint: str, result: SimulateResult) -> None:
        # simulate wall time is no longer hand-summed here: the request
        # latency histogram (RECORDER.observe_request, one recording path)
        # carries both the distribution and the total
        with self.lock:
            self.requests[endpoint] = self.requests.get(endpoint, 0) + 1
            self.simulations += 1
            self.pods_scheduled += sum(len(ns.pods) for ns in result.node_status)
            self.pods_unscheduled += len(result.unscheduled_pods)
            if result.engine is not None and result.engine.native_steps:
                for path in ("incremental", "generic"):
                    self.native_steps[path] += int(
                        result.engine.native_steps.get(path, 0)
                    )
                bails = result.engine.native_steps.get("bails") or {}
                for reason, n in bails.items():
                    self.native_bails[reason] = (
                        self.native_bails.get(reason, 0) + int(n)
                    )
                classes = result.engine.native_steps.get("classes") or {}
                for klass, n in classes.items():
                    self.native_classes[klass] = (
                        self.native_classes.get(klass, 0) + int(n)
                    )

    def native_snapshot(self) -> dict:
        """Cumulative C++ path attribution for ``/api/debug/profile``
        (rendered by ``simon profile``): step counts by evaluation path,
        bail reasons, and per-carry-class incremental step counts."""
        with self.lock:
            return {
                "steps": dict(self.native_steps),
                "bails": dict(self.native_bails),
                "classes": dict(self.native_classes),
            }

    def bump(self, counter: str, n: int = 1) -> None:
        with self.lock:
            setattr(self, counter, getattr(self, counter) + n)

    def render(self, prep_cache=None, watch=None, admission=None, capacity=None,
               journal=None, memory=None) -> str:
        from ..utils.trace import PREP_STATS

        esc = escape_label_value
        hdr = family_header  # every family comes from the obs/metrics.py registry

        with self.lock:
            lines = [
                *hdr("simon_requests_total"),
                *(
                    f'simon_requests_total{{endpoint="{esc(ep)}"}} {n}'
                    for ep, n in sorted(self.requests.items())
                ),
                *hdr("simon_simulations_total"),
                f"simon_simulations_total {self.simulations}",
                *hdr("simon_pods_scheduled_total"),
                f"simon_pods_scheduled_total {self.pods_scheduled}",
                *hdr("simon_pods_unscheduled_total"),
                f"simon_pods_unscheduled_total {self.pods_unscheduled}",
                *hdr("simon_simulate_seconds_total"),
                f"simon_simulate_seconds_total {RECORDER.simulate_seconds_total():.6f}",
            ]
        # host-side prepare attribution (incremental prepare): total seconds
        # spent producing Prepared inputs, and the encode-cache counters
        lines += [
            *hdr("simon_prepare_seconds_total"),
            f"simon_prepare_seconds_total {PREP_STATS.total_seconds():.6f}",
        ]
        if prep_cache is not None:
            st = prep_cache.stats
            lines += [
                *hdr("simon_prep_cache_hits_total"),
                f"simon_prep_cache_hits_total {st.hits}",
                *hdr("simon_prep_cache_misses_total"),
                f"simon_prep_cache_misses_total {st.misses}",
                *hdr("simon_prep_cache_invalidations_total"),
                f"simon_prep_cache_invalidations_total {st.invalidations}",
            ]
        # resilience layer: deadline 504s, snapshot degradation, engine
        # breaker state, fault injections (docs/resilience.md)
        with self.lock:
            lines += [
                *hdr("simon_request_timeouts_total"),
                f"simon_request_timeouts_total {self.request_timeouts}",
                *hdr("simon_snapshot_fetch_retries_total"),
                f"simon_snapshot_fetch_retries_total {self.snapshot_retries}",
                *hdr("simon_snapshot_stale_served_total"),
                f"simon_snapshot_stale_served_total {self.snapshot_stale_served}",
                *hdr("simon_stale_prep_retries_total"),
                f"simon_stale_prep_retries_total {self.stale_prep_retries}",
                *hdr("simon_native_steps_total"),
                *(
                    f'simon_native_steps_total{{path="{esc(p)}"}} {n}'
                    for p, n in sorted(self.native_steps.items())
                ),
                *hdr("simon_native_bail_total"),
                *(
                    f'simon_native_bail_total{{reason="{esc(r)}"}} {n}'
                    for r, n in sorted(self.native_bails.items())
                ),
            ]
        breakers = sorted(breaker_mod.all_breakers().items())
        lines += hdr("simon_engine_breaker_trips_total")
        lines += [
            f'simon_engine_breaker_trips_total{{engine="{esc(name)}"}} {br.trips_total}'
            for name, br in breakers
        ]
        lines += hdr("simon_engine_breaker_open")
        lines += [
            f'simon_engine_breaker_open{{engine="{esc(name)}"}} '
            f'{int(br.state() != "closed")}'
            for name, br in breakers
        ]
        fired = sorted(faults.fault_stats().items())
        if fired:
            lines += hdr("simon_faults_injected_total")
            lines += [
                f'simon_faults_injected_total{{point="{esc(point)}"}} {n}'
                for point, n in fired
            ]
        # live-twin state machine + event/drift counters (server/watch.py):
        # simon_watch_state one-hot, events by kind, reconnects, drift
        if watch is not None:
            lines += watch.metrics_lines()
        # admission queue / batching / shedding telemetry (ISSUE 8,
        # server/admission.py): queue depth gauge, batch-size histogram,
        # shed counters, real time-in-queue
        if admission is not None:
            lines += admission.metrics_lines()
        # capacity observatory (ISSUE 9, obs/capacity.py): per-node
        # utilization distribution, top-K hottest nodes, spread/
        # fragmentation gauges, headroom per registered profile
        if capacity is not None:
            lines += capacity.metrics_lines()
        # watch-event journal (ISSUE 11, server/journal.py): records/bytes
        # written, writer-queue drops, fsync latency, recovery outcomes
        if journal is not None:
            lines += journal.metrics_lines()
        # memory observatory (ISSUE 12, obs/footprint.py): RSS/device
        # watermarks, prep-cache arena bytes, ring occupancy
        if memory is not None:
            lines += memory.metrics_lines()
        # compile telemetry + cumulative phase profiles (ISSUE 12,
        # obs/profile.py) — process singletons, rendered on every scrape
        from ..obs.profile import COMPILES, PROFILE

        lines += COMPILES.metrics_lines()
        lines += PROFILE.metrics_lines()
        # per-phase / per-endpoint latency histograms, computed from the
        # same spans the flight recorder serves (obs/metrics.py)
        lines += RECORDER.render_lines()
        return "\n".join(lines) + "\n"


METRICS = _Metrics()


def _decode_app(payload: dict) -> ResourceTypes:
    rt = ResourceTypes()
    kind_map = {
        "pods": "Pod",
        "deployments": "Deployment",
        "daemonsets": "DaemonSet",
        "DaemonSets": "DaemonSet",
        "statefulsets": "StatefulSet",
        "StatefulSets": "StatefulSet",
        "Jobs": "Job",
        "jobs": "Job",
        "ConfigMaps": "ConfigMap",
        "configmaps": "ConfigMap",
        "Deployments": "Deployment",
        "Pods": "Pod",
    }
    for key, kind in kind_map.items():
        for obj in payload.get(key) or []:
            obj = dict(obj)
            obj.setdefault("kind", kind)
            decoded = object_from_dict(obj)
            if decoded is not None:
                rt.add(decoded)
    return rt


def _decode_new_nodes(payload: dict) -> List[Node]:
    """Requested nodes become fake nodes exactly like the apply path
    (server.go:187-194 → NewFakeNode): fresh simon-<rand> name, hostname
    label rewritten, simon/new-node marker."""
    from ..models.expand import new_fake_nodes

    nodes = []
    for obj in payload.get("newnodes") or payload.get("NewNodes") or []:
        obj = dict(obj)
        obj.setdefault("kind", "Node")
        nodes.extend(new_fake_nodes(Node.from_dict(obj), 1))
    return nodes


def _response(result: SimulateResult, explain: bool = False) -> dict:
    """getSimulateResponse (server.go:446-470): names only; node entries only
    for nodes holding app pods. ``explain=1`` (ISSUE 7) upgrades each
    unscheduled entry with its typed reason breakdown and adds the
    per-filter reject totals — additive, so existing clients are
    unaffected."""
    expl_by_pod = {}
    engine = result.engine
    if explain and engine is not None and engine.explanations:
        expl_by_pod = {e.pod: e for e in engine.explanations}
    out = {"unscheduledPods": [], "nodeStatus": []}
    for up in result.unscheduled_pods:
        name = f"{up.pod.metadata.namespace}/{up.pod.metadata.name}"
        entry = {"pod": name, "reason": up.reason}
        e = expl_by_pod.get(name)
        if e is not None:
            entry["explanation"] = e.to_dict()
        out["unscheduledPods"].append(entry)
    for ns in result.node_status:
        pods = [
            f"{p.metadata.namespace}/{p.metadata.name}"
            for p in ns.pods
            if LABEL_APP_NAME in p.metadata.labels
        ]
        if pods:
            out["nodeStatus"].append({"node": ns.node.metadata.name, "pods": pods})
    if explain and engine is not None and engine.filter_rejects is not None:
        out["filterRejects"] = engine.filter_rejects
    return out


# flight-recorder storage cap for explain-mode placement audits: the ring
# holds N traces, and a 50k-pod audit would pin ~50k dicts per trace. A
# typo'd knob degrades to the default with a warning (same contract as
# OPENSIM_FLIGHT_RECORDER_N), never a startup crash.
def _explain_store_n() -> int:
    raw = envknobs.raw("OPENSIM_EXPLAIN_STORE_N")
    try:
        return max(1, int(raw)) if raw else 512
    except ValueError:
        log.warning("ignoring unparseable OPENSIM_EXPLAIN_STORE_N=%r (using 512)", raw)
        return 512


_EXPLAIN_STORE_N = _explain_store_n()


def _placements_payload(rid: str, result: SimulateResult) -> dict:
    """The serialized decision audit stored on the request's trace for
    ``GET /api/debug/placements/<request-id>``: unschedulable records first
    (they are what the endpoint exists for), scheduled records filling the
    remaining cap."""
    engine = result.engine
    explanations = engine.explanations or []
    ranked = sorted(explanations, key=lambda e: e.status == "scheduled")
    kept = ranked[:_EXPLAIN_STORE_N]
    return {
        "request_id": rid,
        "engine": engine.describe(),
        "filter_rejects": engine.filter_rejects or {},
        "pods_total": len(explanations),
        "truncated": max(0, len(explanations) - len(kept)),
        "explanations": [e.to_dict() for e in kept],
    }


class _BatchUnroutable(Exception):
    """Internal: the drained batch cannot run as one shared-prep batched
    schedule (empty base prep, delta re-encode declined) — the group
    degrades to solo execution, it does not fail."""


class _BatchState:
    """In-flight batch handed between the pipeline stages (prep →
    dispatch → decode). Everything the engine stage touches lives in
    ``derived``/``items`` — derived prep arrays that are immutable after
    the prep stage releases the base-entry lock (generation swaps build
    NEW cache entries, prepcache.twin_pod_delta)."""

    __slots__ = (
        "tickets", "base", "derived", "items", "stale",
        "prep_s", "dispatch", "dispatch_s",
    )

    def __init__(self, tickets, base, derived, items, stale, prep_s):
        self.tickets = tickets
        self.base = base
        self.derived = derived
        self.items = items
        self.stale = stale
        self.prep_s = prep_s
        self.dispatch = None
        self.dispatch_s = 0.0


class SimonServer:
    def __init__(
        self,
        kubeconfig: str = "",
        master: str = "",
        base_cluster: Optional[ResourceTypes] = None,
        snapshot_ttl_s: float = 30.0,
        prep_cache: Optional["PrepareCache"] = None,  # False disables
        watch=None,
        admission=None,
        capacity=None,
        journal=None,
    ):
        self.kubeconfig = kubeconfig
        self.master = master
        self.base_cluster = base_cluster
        # live twin (server/watch.py, ISSUE 6): when a WatchSupervisor is
        # attached AND synced, requests serve from its event-maintained twin
        # (tagged stale while degraded); until then — and whenever watch
        # mode is off or its bootstrap keeps failing — the polling snapshot
        # below is the graceful fallback, so watch mode has no regression
        # path
        self.watch = watch
        # live-cluster snapshots are cached between requests (the reference
        # serves every request from its always-warm informer cache,
        # pkg/server/server.go:97-137, instead of re-listing the cluster);
        # snapshot_ttl_s bounds staleness, ≤0 disables caching
        self.snapshot_ttl_s = snapshot_ttl_s
        self._snapshot: Optional[ResourceTypes] = None
        self._snapshot_at = 0.0
        self._snapshot_fp: Optional[str] = None
        # polling-snapshot state is mutated from pool-worker AND dispatcher
        # threads under the admission path (the endpoint TryLocks that used
        # to serialize it only guard the OPENSIM_ADMISSION=off path) — an
        # RLock keeps (snapshot, fingerprint) pairs coherent and collapses
        # concurrent refreshes into one apiserver fetch
        self._snapshot_lock = threading.RLock()
        # degradation state: when the apiserver stays down through every
        # retry, requests are served from the last good snapshot and tagged
        # with an X-Simon-Snapshot: stale response header
        self.snapshot_stale = False
        self._snapshot_fetched_at = 0.0
        # encode cache (incremental prepare): the snapshot's expanded+encoded
        # cluster is cached across requests keyed by content fingerprint, so
        # a request pays O(its own app) host work, not O(cluster). Opt out
        # with OPENSIM_PREP_CACHE=0 (restores per-request full prepare).
        if prep_cache is None and envknobs.raw("OPENSIM_PREP_CACHE", "1") != "0":
            from ..engine.prepcache import PrepareCache

            prep_cache = PrepareCache()
        self.prep_cache = prep_cache if prep_cache is not False else None
        # concurrent serving core (ISSUE 8, server/admission.py): admission
        # queue + cross-request batching + bounded worker pool. ``None``
        # defers to OPENSIM_ADMISSION (default on); ``False`` restores the
        # single-flight TryLock path; an AdmissionController instance is
        # used as-is (tests inject tiny windows/bounds).
        from . import admission as admission_mod

        if admission is None:
            admission = admission_mod.admission_enabled()
        if admission is True:
            admission = admission_mod.AdmissionController(
                solo_fn=self._admitted_solo, batch_fn=self._admitted_batch,
                # staged executors (ISSUE 16): when OPENSIM_PIPELINE=on the
                # controller runs these as a prep/dispatch/decode pipeline,
                # overlapping batch k+1's host prep with batch k's engine
                # dispatch; batch_fn above remains the serial fallback
                prep_fn=self._batch_prep, dispatch_fn=self._batch_dispatch,
                decode_fn=self._batch_decode,
            )
        self.admission = admission or None
        # serializes headroom probes (they are expensive scans) and guards
        # the published-generation watermark below
        self._headroom_lock = threading.Lock()  # lockwatch: hold-exempt — probes span engine scans by design
        self._headroom_pub_gen = -1
        # capacity observatory (ISSUE 9, obs/capacity.py): always on —
        # ``None`` builds the default engine, ``False`` disables. With a
        # live twin the watch supervisor bootstraps and event-feeds it; on
        # the polling/custom-cluster paths /api/cluster/report bootstraps
        # it per snapshot key instead.
        if capacity is None:
            from ..obs.capacity import CapacityEngine

            capacity = CapacityEngine()
        self.capacity = capacity or None
        if self.watch is not None and self.capacity is not None:
            self.watch.capacity = self.capacity
        # watch-event journal (ISSUE 11, server/journal.py): attached to the
        # watch supervisor, which restores the twin from its newest
        # checkpoint + suffix replay at start() and records every accepted
        # event after — crash-safe instead of merely self-healing. Kept on
        # the server too for /metrics and the shutdown flush.
        self.journal = journal
        if journal is not None and self.watch is not None:
            self.watch.attach_journal(journal)
        self._headroom_key: Optional[str] = None
        # campaign engine (ISSUE 13): one campaign at a time PER SERVER —
        # each builds its own prep lineage; an instance lock keeps
        # unrelated servers (tests, smokes) from serializing each other
        self._campaign_lock = threading.Lock()  # lockwatch: hold-exempt — a campaign spans many engine scans by design
        # memory observatory (ISSUE 12, obs/footprint.py): arena/cache
        # footprint accounting + RSS/device watermarks over the structures
        # THIS server owns. Always on — every view is computed on demand;
        # only serve() starts the low-rate watermark ticker.
        from ..obs.footprint import MemoryObservatory

        self.memory = MemoryObservatory(
            prep_cache=self.prep_cache,
            timeline=self.capacity.timeline if self.capacity is not None else None,
            journal=journal,
        )
        # time-series ring + SLO engine (ISSUE 20, obs/timeseries.py /
        # obs/slo.py): like the memory ticker, only serve() starts them —
        # tests construct SimonServer freely without a sampler thread
        self.timeseries = None
        self.slo = None
        self._ts_sampler = None

    def metrics_text(self) -> str:
        """THE /metrics body (handler + time-series sampler share it):
        the request-layer families plus, when the ring is running, its
        own telemetry and the SLO burn-rate gauges."""
        text = METRICS.render(
            prep_cache=self.prep_cache, watch=self.watch,
            admission=self.admission, capacity=self.capacity,
            journal=self.journal, memory=self.memory,
        )
        extra: List[str] = []
        if self.timeseries is not None:
            extra += self.timeseries.metrics_lines()
        if self.slo is not None:
            extra += self.slo.metrics_lines()
        return text + ("\n".join(extra) + "\n" if extra else "")

    def start_timeseries(self) -> None:
        """Boot the on-disk time-series ring, the self-scrape sampler and
        the SLO engine (idempotent; serve() calls this)."""
        from ..obs.slo import SLOEngine
        from ..obs.timeseries import TimeSeriesRing, TimeSeriesSampler

        if self.timeseries is not None:
            return
        ts_dir = str(envknobs.value("OPENSIM_TS_DIR") or "") or None
        self.timeseries = TimeSeriesRing(directory=ts_dir)
        self.slo = SLOEngine(self.timeseries)
        self._ts_sampler = TimeSeriesSampler(self.timeseries, self.metrics_text)
        self._ts_sampler.start()

    def _stamp_fleet_trace(self, tr) -> None:
        """Cross-process stitching (ISSUE 20): when serving from a fleet
        twin client, stamp the serving generation and the owner's
        publication span ids onto the request trace. Free with tracing
        off (``tr is None``) and on non-fleet servers (no ``stitch_info``
        on the watch object) — the fast path is two attribute reads."""
        if tr is None:
            return
        stitch = getattr(self.watch, "stitch_info", None)
        if stitch is None:
            return
        try:
            gen, pub = stitch()
        except Exception as e:  # a torn reader mid-swap must not fail the request
            log.debug("fleet stitch skipped: %s: %s", type(e).__name__, e)
            return
        if gen is None:
            return
        tr.serving_generation = gen  # the flight recorder keys the graft on this
        attrs = {"serving_generation": gen}
        if isinstance(pub, dict):
            if pub.get("span"):
                attrs["fleet_publication"] = pub["span"]
            events = [e[0] for e in pub.get("events") or []]
            if events:
                # comma-joined, not a list: span attrs are primitives so
                # they survive the tree's JSON export verbatim
                attrs["fleet_events"] = ",".join(events)
        tr.root.set(**attrs)

    def close(self) -> None:
        """Graceful teardown (docs/serving.md "Shutting down"): stop the
        admission dispatcher + worker pool (the in-flight batch completes,
        queued tickets shed typed 503 ``shutting_down``), then flush, fsync
        and close the journal so the on-disk history is complete up to the
        last accepted event. Idempotent."""
        if self.admission is not None:
            self.admission.stop()
        if self._ts_sampler is not None:
            self._ts_sampler.stop()
        if self.timeseries is not None:
            self.timeseries.close()
        if self.journal is not None:
            self.journal.close()
        self.memory.stop()

    def _twin_snapshot(self) -> Optional[tuple]:
        """(cluster, cache key) from the synced live twin, or None when the
        polling path must serve (no watch, or not yet synced). Tags the
        request stale when the twin is degraded/resyncing."""
        if self.watch is None:
            return None
        check_deadline("snapshot")
        with tracing.span("snapshot", source="twin") as sp:
            got = self.watch.serving_snapshot()
            if got is None:
                sp.set(synced=False)
                return None
            cluster, key, stale = got
            sp.set(key=key, stale=stale, state=self.watch.state())
            _mark_request_snapshot(stale)
            if stale:
                METRICS.bump("snapshot_stale_served")
        return cluster, key

    def current_cluster(self) -> ResourceTypes:
        if self.base_cluster is not None:
            return self.base_cluster
        got = self._twin_snapshot()
        if got is not None:
            import copy as _copy

            # the legacy (cache-off) path mutates the cluster in place —
            # the twin's objects must stay pristine
            return _copy.deepcopy(got[0])
        if self.kubeconfig:
            import copy as _copy

            self._refresh_snapshot()
            # hand each request its own copy: simulate() mutates pods/nodes
            # in place (bind writes nodeName/phase/annotations), and the
            # cached snapshot must stay pristine across requests
            return _copy.deepcopy(self._snapshot)
        return ResourceTypes()

    def _refresh_snapshot(self) -> None:
        with self._snapshot_lock:
            self._refresh_snapshot_locked()

    def _refresh_snapshot_locked(self) -> None:
        import time as _time

        now = _time.monotonic()
        if self._snapshot is not None and not (
            self.snapshot_ttl_s <= 0 or now - self._snapshot_at > self.snapshot_ttl_s
        ):
            # within the TTL window after a degrade the cached snapshot is
            # still the stale one: this request must be tagged too
            _mark_request_snapshot(self.snapshot_stale)
            return
        check_deadline("snapshot")
        attempts, base_delay = snapshot_retry_policy()

        def _fetch() -> ResourceTypes:
            faults.fault_point("snapshot.http")
            return cluster_from_kubeconfig(self.kubeconfig, self.master)

        def _note_retry(attempt: int, exc: BaseException, delay: float) -> None:
            # the trace event comes from retry_call itself (trace_name below)
            METRICS.bump("snapshot_retries")
            log.warning(
                "snapshot fetch attempt %d failed (%s: %s); retrying in %.3fs",
                attempt + 1, type(exc).__name__, exc, delay,
            )

        with tracing.span("snapshot") as snap_span:
            try:
                # the ONE retry layer for the snapshot fetch (the per-endpoint
                # code raises typed single-attempt failures). Only the transient
                # class retries — a missing kubeconfig or auth misconfiguration
                # (plain OSError/RuntimeError) will not heal and surfaces now.
                self._snapshot = retry_call(
                    _fetch,
                    attempts=attempts,
                    base_delay=base_delay,
                    retry_on=(SnapshotFetchError, TimeoutError),
                    on_retry=_note_retry,
                    trace_name="snapshot.retry",
                )
            except (SnapshotFetchError, TimeoutError) as e:
                if self._snapshot is not None:
                    # degrade: serve the last good snapshot, tagged stale, and
                    # re-arm the TTL so a down apiserver is probed once per TTL
                    # window instead of hammered on every request
                    self.snapshot_stale = True
                    _mark_request_snapshot(True)
                    self._snapshot_at = now
                    METRICS.bump("snapshot_stale_served")
                    snap_span.mark(
                        "demoted",
                        reason="stale snapshot served",
                        age_s=round(now - self._snapshot_fetched_at, 3),
                        error=f"{type(e).__name__}: {e}",
                    )
                    log.warning(
                        "snapshot refresh failed after %d attempt(s) (%s: %s); "
                        "serving stale snapshot (age %.1fs)",
                        attempts, type(e).__name__, e, now - self._snapshot_fetched_at,
                    )
                    return
                raise SnapshotUnavailable(
                    f"cluster snapshot unavailable after {attempts} attempt(s): {e}"
                ) from e
        self._snapshot_at = now
        self._snapshot_fetched_at = now
        self.snapshot_stale = False
        _mark_request_snapshot(False)
        self._snapshot_fp = None  # re-fingerprint lazily

    def _snapshot_for_cache(self) -> tuple:
        """(cluster, content fingerprint) for the encode-cache path — no
        defensive deepcopy: the cached Prepared owns sanitized pod copies
        and its bind state is restored after every use, so the snapshot
        objects are never mutated. A fingerprint change (snapshot refresh
        picked up cluster changes) invalidates the stale entries."""
        from ..engine.prepcache import fingerprint_cluster

        if self.base_cluster is not None:
            with self._snapshot_lock:
                if self._snapshot_fp is None:
                    self._snapshot_fp = fingerprint_cluster(self.base_cluster)
                return self.base_cluster, self._snapshot_fp
        got = self._twin_snapshot()
        if got is not None:
            # generation-keyed, not content-fingerprinted: every applied
            # event bumps the twin's generation, and the watch supervisor —
            # not this request path — owns base-entry invalidation (it
            # replaces the base by O(changes) delta instead)
            return got
        if self.kubeconfig:
            # fetch + fingerprint + invalidation under ONE lock: a
            # concurrent refresh swapping self._snapshot between the two
            # reads would cache a prepare under the wrong fingerprint
            with self._snapshot_lock:
                old_fp = self._snapshot_fp
                self._refresh_snapshot_locked()
                if self._snapshot_fp is None:
                    self._snapshot_fp = fingerprint_cluster(self._snapshot)
                    if old_fp is not None and old_fp != self._snapshot_fp:
                        self.prep_cache.invalidate(old_fp)
                return self._snapshot, self._snapshot_fp
        return ResourceTypes(), "empty"

    # -- capacity observatory (ISSUE 9) -------------------------------------

    def _observed_cluster(self) -> tuple:
        """(cluster, stable key) for the capacity view — the cache path's
        (snapshot, fingerprint-or-generation) pair, or a content
        fingerprint on the legacy cache-off path."""
        if self.prep_cache is not None:
            return self._snapshot_for_cache()
        from ..engine.prepcache import fingerprint_cluster

        cluster = self.current_cluster()
        return cluster, fingerprint_cluster(cluster)

    def _probe_headroom(self, cluster: ResourceTypes, key: str) -> dict:
        """Headroom per registered profile, probed through the warm base
        prep (one delta re-encode + batched mask-prefix scans — zero full
        prepares once the base exists; creating a missing base IS the
        serving path's bootstrap prepare). Keyed by the snapshot key: one
        probe set per observed cluster state."""
        from ..engine import prepcache
        from ..obs import capacity as capacity_mod

        if self.capacity is None:
            return {}
        # serialized: concurrent reports must not probe the same state
        # twice, and a slow probe for an OLDER snapshot must not overwrite
        # a newer probe's published gauges (the generation watermark below)
        with self._headroom_lock:
            if self._headroom_key == key:
                return self.capacity.headroom()
            gen0 = self.capacity.generation
            profiles = capacity_mod.headroom_profiles()
            base = None
            if self.prep_cache is not None:
                from ..engine.simulator import prepare

                base_key = f"{key}|base"
                base = self.prep_cache.get(base_key)
                if base is None:
                    watch = prepcache.watch_snapshot(cluster, [])  # before the build
                    base = self.prep_cache.put(
                        base_key,
                        prepcache.CacheEntry(base_key, prepare(cluster, []), watch=watch),
                    )
                self.prep_cache.check_fresh(base)
                if base.prep is None:
                    base = None  # no schedulable pods cached; probe prepares fresh
            out = {}
            for profile in profiles:
                out[profile.name] = capacity_mod.headroom_probe(
                    cluster, profile, base=base,
                    kmax=self.capacity.fit_upper_bound(profile),
                )
            if gen0 >= self._headroom_pub_gen:
                self.capacity.set_headroom(out)
                self._headroom_key = key
                self._headroom_pub_gen = gen0
            return out

    def cluster_report(
        self, extended: Optional[List[str]] = None, probe_headroom: bool = True,
        include_memory: bool = False,
    ) -> dict:
        """The ``GET /api/cluster/report`` body: the capacity sample plus
        the same table rows the text renderer prints
        (``obs/capacity.build_report`` — one computation path, gated by the
        report-parity test). ``include_memory`` (``?mem=1``) adds the
        memory observatory block — summary plus the SAME rows ``simon top
        --mem`` renders (``obs/footprint.memory_rows``, byte-equal parity
        like every other report table)."""
        from ..obs import capacity as capacity_mod

        if self.capacity is None:
            raise RuntimeError("capacity observatory disabled (capacity=False)")
        cluster, key = self._observed_cluster()
        self.capacity.ensure_bootstrap(cluster, key)
        if probe_headroom:
            self._probe_headroom(cluster, key)
        state = self.watch.state() if self.watch is not None else "polling"
        report = capacity_mod.build_report(
            self.capacity, cluster, extended_resources=extended, state=state
        )
        if include_memory:
            from ..obs.footprint import memory_rows

            summary = self.memory.summary()
            report["memory"] = {"summary": summary, "rows": memory_rows(summary)}
        return report

    # -- campaign engine (ISSUE 13) -----------------------------------------

    def run_campaign(self, payload: dict, deadline: Optional[Deadline] = None) -> tuple:
        """``POST /api/campaign`` (docs/campaigns.md): evaluate a
        lifecycle campaign — the request body's ``steps`` list, the same
        shape as a campaign file's ``spec.steps`` — against the observed
        cluster (the live twin when synced, the polling snapshot
        otherwise). Campaigns are serialized: each builds its own prep
        lineage (exactly one full prepare) and never mutates the snapshot
        objects. Returns ``(status, body)``."""
        from ..planner import campaign as campaign_mod

        try:
            with campaign_mod.remote_spec_context():
                steps = campaign_mod.parse_steps(payload.get("steps"))
        except campaign_mod.CampaignError as e:
            return 400, {"error": str(e), "step": e.step, "field": e.field}
        name = str(payload.get("name") or "campaign")
        mode = payload.get("mode") or None
        try:
            with deadline_scope(deadline):
                with self._campaign_lock:
                    with tracing.span("campaign", steps=len(steps)):
                        cluster, _key = self._observed_cluster()
                        # remote spec: step run() must not dereference
                        # server-side paths either (deploy _load at run
                        # time, from-journal reads) — the same gate holds
                        # for the whole evaluation
                        with campaign_mod.remote_spec_context():
                            result = campaign_mod.run_campaign(
                                cluster, steps, mode=mode, name=name
                            )
            return 200, result.to_dict()
        except DeadlineExceeded as e:
            return 504, {"error": str(e), "phase": e.phase, "retryable": True}
        except SnapshotUnavailable as e:
            return 503, {"error": str(e), "retryable": True}
        except campaign_mod.CampaignError as e:
            return 400, {"error": str(e), "step": e.step, "field": e.field}

    # -- handlers -----------------------------------------------------------

    def _simulate_request(self, kind: str, payload: dict,
                          explain: bool = False) -> SimulateResult:
        """`_simulate_request_once` plus stale-entry recovery: a
        ``StaleFingerprintError`` hit means a fingerprinted object was
        ``touch()``ed behind the cache's back — ``PrepareCache.check_fresh``
        already evicted everything the object taints, so ONE internal retry
        re-prepares from the live objects. A REST client has no way to call
        ``invalidate(obj)``; without this the client would eat a 500 for a
        purely server-side cache condition. A second stale failure in the
        same request propagates (typed 500) rather than looping."""
        from ..engine.prepcache import StaleFingerprintError

        try:
            return self._simulate_request_once(kind, payload, explain=explain)
        except StaleFingerprintError as e:
            METRICS.bump("stale_prep_retries")
            log.warning("stale prepare-cache entry (%s); retrying once after eviction", e)
            return self._simulate_request_once(kind, payload, explain=explain)

    def _simulate_request_once(self, kind: str, payload: dict,
                               explain: bool = False) -> SimulateResult:
        """Shared deploy/scale simulation through the encode cache:

        1. identical repeated request → full-key hit: restore + simulate,
           zero re-encoding;
        2. known snapshot → base-entry hit: delta re-encode (append the
           request's app pods; extend nodes from the request's templates;
           flip valid-mask bits for scaled-away pods);
        3. cold → one full prepare of the snapshot, cached for 1+2.
        """
        from ..engine import prepcache
        from ..utils.trace import PREP_STATS
        import time as _time

        new_nodes = _decode_new_nodes(payload)
        app = _decode_app(payload)
        apps = [AppResource(kind, app)]
        scaled: set = set()
        if kind == "scale":
            scaled = {
                (w.kind, w.metadata.namespace, w.metadata.name)
                for w in app.deployments + app.daemon_sets + app.stateful_sets
            }

        if self.prep_cache is None:
            # legacy path: per-request snapshot copy + full prepare
            cluster = _with_new_nodes(self.current_cluster(), new_nodes)
            if scaled:
                cluster.pods = [p for p in cluster.pods if not _owned_by(p, scaled)]
            return simulate(cluster, apps, explain=explain)

        cluster0, fp = self._snapshot_for_cache()
        cluster = _with_new_nodes(cluster0, new_nodes)

        def _filtered() -> ResourceTypes:
            # only the cold full-prepare fallbacks need the scaled pods
            # actually removed from the input; the cached paths express the
            # removal as a drop mask over the prepared stream instead, so
            # the O(all pods) owner scan is skipped on the hot path
            if not scaled:
                return cluster
            out = _with_new_nodes(cluster0, new_nodes)
            out.pods = [p for p in cluster0.pods if not _owned_by(p, scaled)]
            return out

        payload_fp = hashlib.blake2b(
            json.dumps(payload, sort_keys=True, default=str).encode(), digest_size=16
        ).hexdigest()
        full_key = f"{fp}|{kind}|{payload_fp}"
        # full-key reuse only without newnodes: fake-node names are freshly
        # randomized per request, and a cached derived prep would replay the
        # first request's names into later responses
        entry = self.prep_cache.get(full_key) if not new_nodes else None
        if entry is not None and entry.prep is not None:
            self.prep_cache.check_fresh(entry)
            t0 = _time.monotonic()
            with entry.lock:
                entry.restore()
                PREP_STATS.record("hit", _time.monotonic() - t0)
                try:
                    return simulate(
                        cluster, apps, prep=entry.prep,
                        drop_pods=getattr(entry, "drop_mask", None),
                        explain=explain,
                    )
                finally:
                    entry.restore()

        base_key = f"{fp}|base"
        base = self.prep_cache.get(base_key)
        if base is None:
            from ..engine.simulator import prepare

            watch = prepcache.watch_snapshot(cluster0, [])  # before the build
            base = self.prep_cache.put(
                base_key,
                prepcache.CacheEntry(base_key, prepare(cluster0, []), watch=watch),
            )
        if base.prep is None:
            # snapshot with no schedulable pods: nothing worth caching
            return simulate(_filtered(), apps, explain=explain)
        self.prep_cache.check_fresh(base)
        with base.lock:
            base.restore()
            base_prep = base.prep
            if new_nodes:
                base_prep = prepcache.extend_with_nodes(
                    base_prep, new_nodes, cluster0, [], base_entry=base
                )
            derived = (
                prepcache.derive_with_apps(
                    base_prep, cluster, apps,
                    base_entry=base if not new_nodes else None,
                )
                if base_prep is not None
                else None
            )
            if derived is None:
                return simulate(_filtered(), apps, explain=explain)
            # the simulate drop mask composes the scale request's removals
            # with the live twin's event-deleted pods (CacheEntry.base_drop:
            # watch DELETEDs stay in the cached stream, mask-flipped)
            drop = prepcache.union_drop_masks(
                base.base_drop,
                prepcache.drop_mask_for_scaled(derived, _owned_by, scaled)
                if scaled
                else None,
                len(derived.ordered),
            )
            entry = prepcache.CacheEntry(full_key, derived, base=base)
            entry.drop_mask = drop
            if not new_nodes:
                self.prep_cache.put(full_key, entry)
            try:
                return simulate(cluster, apps, prep=derived, drop_pods=drop,
                                explain=explain)
            finally:
                entry.restore()

    # -- admission-path executors (ISSUE 8) --------------------------------
    #
    # Both run on dispatcher/worker-pool threads, never on the HTTP handler
    # thread: they communicate exclusively through the ticket (result or
    # error + the stale flag observed on the executing thread, since
    # _REQUEST_STATE is thread-local and would not survive the handoff).

    def _admitted_solo(self, ticket) -> None:
        """Full-fidelity solo execution: the exact `_simulate_request` path
        (engine ladder, prep cache, one stale retry), with the request's
        deadline and trace installed on this worker thread so phase spans
        and 504s land exactly as on the legacy path."""
        _mark_request_snapshot(False)
        _REQUEST_STATE.request_id = ticket.request_id
        try:
            with deadline_scope(ticket.deadline), tracing.trace_scope(ticket.trace):
                result = self._simulate_request(
                    ticket.kind, ticket.payload, explain=ticket.explain
                )
            ticket.resolve(result=result, stale=request_served_stale())
        except BaseException as e:
            # transported: the REST thread re-raises this into its typed
            # failure ladder (504/503/500) and logs it there
            log.debug("solo execution failed: %s: %s", type(e).__name__, e)
            ticket.resolve(error=e, stale=request_served_stale())

    def _admitted_batch(self, tickets) -> None:
        """Batched execution with the solo path's stale-entry contract (one
        internal retry after eviction) and a solo fallback when the stream
        cannot batch (empty base prep, delta declined)."""
        from ..engine.prepcache import StaleFingerprintError

        try:
            try:
                self._run_batch_once(tickets)
            except StaleFingerprintError as e:
                METRICS.bump("stale_prep_retries")
                log.warning(
                    "stale prepare-cache entry in batch (%s); retrying once "
                    "after eviction", e,
                )
                self._run_batch_once(tickets)
        except _BatchUnroutable as e:
            # the stream cannot batch (no schedulable base pods, delta
            # declined): degrade to full-fidelity solo runs, never an error
            log.info("batch of %d unroutable (%s); running solo", len(tickets), e)
            for t in tickets:
                self._admitted_solo(t)
        except BaseException as e:
            # one failure fails the whole group with the same typed error a
            # solo run would surface — never a partial result
            log.warning(
                "batch of %d failed (%s: %s); failing the group",
                len(tickets), type(e).__name__, e,
            )
            for t in tickets:
                if not t.done.is_set():
                    t.resolve(error=e)

    def _run_batch_once(self, tickets) -> None:
        """Fold N compatible requests onto one shared warm prep and run ONE
        request-axis batched schedule (engine/reqbatch.py), demultiplexing
        a per-request SimulateResult that is bit-identical to a solo run
        (gated by tests/test_admission.py).

        Composed from the same three stage executors the pipelined path
        runs (prep → dispatch → decode), so serial and pipelined modes
        share ONE implementation and cannot drift."""
        state = self._batch_prep_once(tickets)
        if state is None:
            return  # every rider already resolved (payload decode failures)
        self._batch_decode(self._batch_dispatch(state))

    def _batch_prep_once(self, tickets) -> Optional[_BatchState]:
        """Pipeline stage 1 — host prep, under the base entry's lock:
        snapshot/fingerprint, per-rider payload decode, shared
        derive-with-slices, per-rider drop masks. Releases the lock before
        returning: the derived prep it hands the dispatch stage is
        immutable from here on (twin generation swaps build NEW entries —
        prepcache.twin_pod_delta — so a swap mid-flight never mutates
        these arrays)."""
        import time as _time

        import numpy as np

        from ..engine import prepcache, reqbatch
        from ..engine.simulator import prepare

        _mark_request_snapshot(False)
        t0 = _time.monotonic()
        cluster0, fp = self._snapshot_for_cache()
        stale = request_served_stale()
        apps: List[AppResource] = []
        scaled_sets: List[set] = []
        kept: List = []
        for t in tickets:
            # per-ticket decode: ONE malformed payload must fail only its
            # own request (typed 500), never poison the whole batch
            try:
                app = _decode_app(t.payload)
            except Exception as e:
                log.warning(
                    "batch rider payload failed to decode (%s: %s)",
                    type(e).__name__, e,
                )
                t.resolve(error=e, stale=stale)
                continue
            kept.append(t)
            apps.append(AppResource(t.kind, app))
            scaled_sets.append(
                {
                    (w.kind, w.metadata.namespace, w.metadata.name)
                    for w in app.deployments + app.daemon_sets + app.stateful_sets
                }
                if t.kind == "scale"
                else set()
            )
        tickets = kept
        if not tickets:
            return None
        base_key = f"{fp}|base"
        base = self.prep_cache.get(base_key)
        if base is None:
            watch = prepcache.watch_snapshot(cluster0, [])  # before the build
            base = self.prep_cache.put(
                base_key,
                prepcache.CacheEntry(base_key, prepare(cluster0, []), watch=watch),
            )
        self.prep_cache.check_fresh(base)
        with base.lock:
            base.restore()
            if base.prep is not None:
                got = prepcache.derive_with_app_slices(
                    base.prep, cluster0, apps, base_entry=base
                )
                if got is None:
                    raise _BatchUnroutable("delta re-encode declined the stream")
                derived, slices = got
            else:
                # snapshot with no schedulable pods: nothing cached to
                # derive from — one fresh prepare of ALL the batch's apps
                # still beats N solo full prepares (prepare() records the
                # per-app stream slices for exactly this demultiplexing)
                derived = prepare(cluster0, apps)
                if derived is None or derived.app_slices is None:
                    raise _BatchUnroutable("batch expanded to an empty stream")
                slices = derived.app_slices
            items = []
            for s in range(len(tickets)):
                drop = prepcache.union_drop_masks(
                    base.base_drop,
                    prepcache.drop_mask_for_scaled(derived, _owned_by, scaled_sets[s])
                    if scaled_sets[s]
                    else None,
                    len(derived.ordered),
                )
                drops = set(int(i) for i in np.nonzero(drop)[0]) if drop is not None else set()
                items.append(
                    reqbatch.BatchItem(
                        cluster=cluster0, apps=[apps[s]],
                        lo=slices[s][0], hi=slices[s][1], drops=drops,
                        # batched explain (ISSUE 15 satellite): the rider's
                        # audit is built from its own count_all fail rows
                        # over the shared derive
                        explain=tickets[s].explain,
                        # in-flight shedding (ISSUE 9 satellite): the C++
                        # sequential path re-checks this between rider scans
                        deadline=tickets[s].deadline,
                    )
                )
        prep_s = _time.monotonic() - t0
        return _BatchState(tickets, base, derived, items, stale, prep_s)

    def _batch_prep(self, tickets) -> Optional[_BatchState]:
        """The pipelined controller's ``prep_fn``: `_batch_prep_once` with
        the serial path's stale-entry contract (one internal retry after
        eviction — a twin generation swap mid-prep lands here) and the
        `_BatchUnroutable` → None degradation (the controller pools the
        still-unresolved riders to full-fidelity solo runs)."""
        from ..engine.prepcache import StaleFingerprintError

        try:
            try:
                return self._batch_prep_once(tickets)
            except StaleFingerprintError as e:
                METRICS.bump("stale_prep_retries")
                log.warning(
                    "stale prepare-cache entry in batch (%s); retrying once "
                    "after eviction", e,
                )
                return self._batch_prep_once(tickets)
        except _BatchUnroutable as e:
            log.info(
                "batch of %d unroutable (%s); degrading to solo", len(tickets), e
            )
            return None

    def _batch_dispatch(self, state: _BatchState) -> _BatchState:
        """Pipeline stage 2 — the engine dispatch. Runs WITHOUT the base
        entry's lock: it touches only the stage-1 derived prep (immutable)
        and device buffers, and the engines release the GIL, so the NEXT
        batch's host prep overlaps this wall-clock (the tentpole win)."""
        import time as _time

        from ..engine import reqbatch

        t0 = _time.monotonic()
        state.dispatch = reqbatch.dispatch_request_batch(state.derived, state.items)
        state.dispatch_s = _time.monotonic() - t0
        return state

    def _batch_decode(self, state: _BatchState) -> None:
        """Pipeline stage 3 — demultiplex per-rider results under the base
        entry's lock (decode mutates the shared pod objects' bind state;
        the restore discipline hands the next holder pristine state)."""
        import time as _time

        from ..engine import reqbatch

        tickets, base, stale = state.tickets, state.base, state.stale
        t1 = _time.monotonic()
        with base.lock:
            base.restore()
            try:
                results = reqbatch.decode_request_batch(
                    state.derived, state.items, state.dispatch
                )
            finally:
                base.restore()
        run_s = state.dispatch_s + (_time.monotonic() - t1)
        for t, res in zip(tickets, results):
            if isinstance(res, BaseException):
                # a rider shed mid-batch (deadline expired between C++
                # scans): transported like any executor error — the REST
                # thread re-raises into its typed ladder (504 phase=schedule)
                t.resolve(error=res, stale=stale)
                continue
            tr = t.trace
            if tr is not None:
                # synthetic phase spans: the shared batch work, attributed
                # to every rider so per-phase histograms stay live for
                # batched traffic (child_from_seconds exists for this)
                tr.root.child_from_seconds(
                    "prepare", state.prep_s, batched=True, batch=len(tickets)
                )
                tr.root.child_from_seconds(
                    "schedule", run_s, batched=True, batch=len(tickets)
                )
            t.resolve(result=res, stale=stale, batch_size=len(tickets))

    def _handle_admitted(self, endpoint: str, kind: str, payload: dict,
                         deadline: Optional[Deadline] = None,
                         request_id: Optional[str] = None,
                         explain: bool = False) -> tuple:
        """The admission-path endpoint shell: same typed failure ladder as
        the legacy `_handle`, plus two shed outcomes —

        - 503 + reason=queue_full + ``Retry-After``: the admission queue is
          at its bound (load-shedding, server/admission.py);
        - 504 + phase=queue: the request's deadline expired while queued.

        Every outcome records the REAL elapsed time in the request
        histogram (the ISSUE 8 satellite: rejected traffic must carry its
        actual latency, not a fake 0.0)."""
        import math
        import time

        from . import admission as admission_mod

        rid = tracing.sanitize_request_id(request_id) or tracing.new_request_id()
        _REQUEST_STATE.request_id = rid
        _REQUEST_STATE.extra_headers = {}
        _mark_request_snapshot(False)
        tr = tracing.start_trace(endpoint, request_id=rid)
        t0 = time.monotonic()
        status = "error"
        code, body = 500, {"error": "unhandled"}
        result: Optional[SimulateResult] = None
        ticket = None
        try:
            has_new_nodes = bool(payload.get("newnodes") or payload.get("NewNodes"))
            ticket = admission_mod.Ticket(
                kind=kind, payload=payload, explain=explain, deadline=deadline,
                trace=tr, request_id=rid,
                # with the cache off every request takes the legacy
                # full-prepare path: solo through the pool, never batched
                has_new_nodes=has_new_nodes or self.prep_cache is None,
            )
            self.admission.submit(ticket)
            self.admission.wait(ticket)
            result = ticket.result
            _mark_request_snapshot(ticket.stale)
            status = "ok"
            if result.engine is not None:
                result.engine.request_id = rid
                if tr is not None:
                    tr.root.set(engine=result.engine.describe())
                    if ticket.batch_size:
                        tr.root.set(batch_size=ticket.batch_size)
            code, body = 200, _response(result, explain=explain)
            if explain and tr is not None and result.engine is not None:
                tr.placements = _placements_payload(rid, result)
        except admission_mod.QueueFull as e:
            status = "shed"
            log.warning("%s shed: %s", endpoint, e)
            _REQUEST_STATE.extra_headers = {
                "Retry-After": str(max(1, int(math.ceil(e.retry_after_s))))
            }
            # reason distinguishes overload (queue_full) from graceful
            # shutdown (shutting_down) — a client should retry the former
            # against this replica and the latter against another
            code, body = 503, {
                "error": str(e),
                "reason": getattr(e, "reason", "queue_full"),
                "retryable": True,
            }
        except DeadlineExceeded as e:
            status = "deadline-exceeded"
            METRICS.bump("request_timeouts")
            log.warning("%s timed out: %s", endpoint, e)
            code, body = 504, {"error": str(e), "phase": e.phase}
        except SnapshotUnavailable as e:
            log.warning("%s snapshot unavailable: %s", endpoint, e)
            code, body = 503, {"error": str(e), "retryable": True}
        except Exception as e:
            log.warning("%s failed: %s: %s", endpoint, type(e).__name__, e)
            code, body = 500, {"error": str(e), "type": type(e).__name__}
        finally:
            seconds = time.monotonic() - t0
            with RECORDER.lock:
                if status == "ok" and result is not None:
                    METRICS.record(endpoint, result)
                RECORDER.observe_request(endpoint, seconds, status=status)
            if tr is not None:
                if ticket is not None and ticket.queue_s:
                    # real time-in-queue on the span tree (also histogrammed
                    # as simon_queue_wait_seconds by the controller)
                    tr.root.child_from_seconds("queue", ticket.queue_s)
                self._stamp_fleet_trace(tr)
                tr.finish(status=status, http_status=code)
                FLIGHT_RECORDER.record(tr)
                RECORDER.observe_trace(tr)
        return code, body

    def _handle(self, endpoint: str, kind: str, lock: threading.Lock,
                payload: dict, deadline: Optional[Deadline] = None,
                request_id: Optional[str] = None, explain: bool = False) -> tuple:
        """Shared endpoint shell: single-flight busy rejection, deadline
        scope, request-scoped trace, and the failure-mode ladder
        (docs/resilience.md) — every outcome is a typed JSON body, never a
        hang or a raw traceback:

        - 200: simulation result
        - 503 busy: TryLock rejection (server.go:167,:234)
        - 504 + phase: request deadline exhausted at a phase boundary
        - 503 + retryable: apiserver down through every retry, no snapshot
          to degrade to
        - 500 + type: everything else (engine/encoding failure after the
          fallback ladder is exhausted)

        Observability (ISSUE 5): every request gets an id (the client's
        ``X-Simon-Request-Id`` honored when supplied, generated otherwise —
        read it back via :func:`last_request_id`) and, when tracing is
        enabled, a span tree recorded into the flight recorder and folded
        into the /metrics latency histograms on the way out.

        With the admission queue enabled (the default — OPENSIM_ADMISSION,
        ISSUE 8), requests route through ``_handle_admitted`` instead:
        cross-request batching + bounded worker pool + load-shedding; this
        single-flight shell remains the ``OPENSIM_ADMISSION=off`` path.
        """
        import time

        if self.admission is not None:
            return self._handle_admitted(
                endpoint, kind, payload, deadline, request_id, explain=explain
            )
        t0 = time.monotonic()
        rid = tracing.sanitize_request_id(request_id) or tracing.new_request_id()
        _REQUEST_STATE.request_id = rid
        if not lock.acquire(blocking=False):
            # rejected traffic must still be visible in the histograms —
            # overload is exactly what a latency dashboard is watching for.
            # Record the REAL elapsed time (ISSUE 8 satellite): a fake 0.0
            # skewed every dashboard's busy-series percentiles.
            RECORDER.observe_request(
                endpoint, time.monotonic() - t0, status="busy"
            )
            return 503, {"error": "the server is busy now, please try again later"}
        _mark_request_snapshot(False)  # until a refresh says otherwise
        tr = tracing.start_trace(endpoint, request_id=rid)
        t0 = time.monotonic()
        status = "error"
        code, body = 500, {"error": "unhandled"}
        result: Optional[SimulateResult] = None
        try:
            with deadline_scope(deadline), tracing.trace_scope(tr):
                result = self._simulate_request(kind, payload, explain=explain)
            status = "ok"
            if result.engine is not None:
                result.engine.request_id = rid
                if tr is not None:
                    tr.root.set(engine=result.engine.describe())
            code, body = 200, _response(result, explain=explain)
            if explain and tr is not None and result.engine is not None:
                # the decision audit joins the flight recorder: served later
                # at GET /api/debug/placements/<request-id> (serialized and
                # capped — the ctx holding the full Prepared is dropped).
                # engine is None when the snapshot held no schedulable pods
                tr.placements = _placements_payload(rid, result)
        except DeadlineExceeded as e:
            status = "deadline-exceeded"
            METRICS.bump("request_timeouts")
            log.warning("%s timed out: %s", endpoint, e)
            code, body = 504, {"error": str(e), "phase": e.phase}
        except SnapshotUnavailable as e:
            log.warning("%s snapshot unavailable: %s", endpoint, e)
            code, body = 503, {"error": str(e), "retryable": True}
        except Exception as e:  # surface as 500 like gin's error handler
            log.warning("%s failed: %s: %s", endpoint, type(e).__name__, e)
            code, body = 500, {"error": str(e), "type": type(e).__name__}
        finally:
            try:
                seconds = time.monotonic() - t0
                # one recording path for request latency, ONE critical
                # section: the success counters and the histogram land
                # atomically, so a scrape never sees simulations_total
                # bumped with simulate_seconds_total still short a request
                with RECORDER.lock:
                    if status == "ok" and result is not None:
                        METRICS.record(endpoint, result)
                    RECORDER.observe_request(endpoint, seconds, status=status)
                if tr is not None:
                    self._stamp_fleet_trace(tr)
                    tr.finish(status=status, http_status=code)
                    FLIGHT_RECORDER.record(tr)
                    RECORDER.observe_trace(tr)
            finally:
                # the single-flight lock must be released even if telemetry
                # recording throws — a leaked lock would 503 the endpoint
                # until restart
                lock.release()
        return code, body

    def deploy_apps(self, payload: dict, deadline: Optional[Deadline] = None,
                    request_id: Optional[str] = None, explain: bool = False) -> tuple:
        return self._handle("deploy-apps", "deploy", _deploy_lock, payload,
                            deadline, request_id, explain=explain)

    def scale_apps(self, payload: dict, deadline: Optional[Deadline] = None,
                   request_id: Optional[str] = None, explain: bool = False) -> tuple:
        """scale-apps (server.go:233-312): remove the workload's existing
        pods from the cluster snapshot, then re-simulate at the new scale —
        on the cached path the removal is a valid-mask flip over the
        snapshot's cached encoding, not a re-encode."""
        return self._handle("scale-apps", "scale", _scale_lock, payload,
                            deadline, request_id, explain=explain)


def _owned_by(pod, scaled: set) -> bool:
    for ref in pod.metadata.owner_references:
        key = (ref.kind, pod.metadata.namespace, ref.name)
        if key in scaled:
            return True
        # deployment pods are owned via a generated ReplicaSet name prefix
        if ref.kind == "ReplicaSet" and any(
            k == "Deployment" and ns == pod.metadata.namespace and ref.name.startswith(name + "-")
            for k, ns, name in scaled
        ):
            return True
    return False


def _with_new_nodes(cluster: ResourceTypes, nodes: List[Node]) -> ResourceTypes:
    import copy

    out = copy.copy(cluster)
    out.nodes = list(cluster.nodes) + nodes
    return out


def request_deadline(headers) -> Optional[Deadline]:
    """Per-request deadline: the ``X-Simon-Timeout-S`` header wins, else
    ``OPENSIM_REQUEST_TIMEOUT_S`` (unset/0 = no deadline — existing clients
    keep today's unbounded behavior unless they or the operator opt in)."""
    raw = headers.get("X-Simon-Timeout-S") if headers is not None else None
    if raw is None:
        raw = envknobs.raw("OPENSIM_REQUEST_TIMEOUT_S")
    if not raw:
        return None
    try:
        budget = float(raw)
    except ValueError:
        log.warning("ignoring unparseable request timeout %r", raw)
        return None
    return Deadline.after(budget) if budget > 0 else None


def make_handler(server: SimonServer):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive (ISSUE 8): every response carries Content-Length, so
        # HTTP/1.1 persistent connections are safe — a closed-loop client
        # pays one TCP connect + one handler thread per WORKER instead of
        # per request (the per-request connection churn dominated serving
        # latency under load)
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _begin_request(self) -> None:
            # duration is request-scoped, stamped at dispatch: measuring
            # from connection setup() would bill keep-alive idle and slow
            # client uploads to the server. EVERY request — GETs and 4xx
            # paths included — gets an id here (the client's
            # X-Simon-Request-Id honored, generated otherwise), so an
            # access-log line always joins against the flight recorder and
            # can never inherit the id of an earlier request served on the
            # same thread (ISSUE 7 satellite).
            import time

            self._t0 = time.monotonic()
            _REQUEST_STATE.request_id = (
                tracing.sanitize_request_id(self.headers.get("X-Simon-Request-Id"))
                or tracing.new_request_id()
            )
            _REQUEST_STATE.extra_headers = {}

        def _access_log(self, code: int) -> None:
            """Opt-in structured access logging (``OPENSIM_ACCESS_LOG=1``):
            one JSON object per request on the ``opensim_tpu.access``
            logger — request id, endpoint, status, duration — keeping the
            quiet-by-default behavior when unset (ISSUE 5 satellite)."""
            if envknobs.raw("OPENSIM_ACCESS_LOG") != "1":
                return
            import time

            _ACCESS_LOG.info(
                "%s",
                json.dumps(
                    {
                        "ts": round(time.time(), 3),
                        "request_id": last_request_id(),
                        "method": self.command,
                        "endpoint": self.path,
                        "status": code,
                        "duration_s": round(
                            time.monotonic() - getattr(self, "_t0", time.monotonic()), 6
                        ),
                        "remote": self.client_address[0],
                    },
                    sort_keys=True,
                ),
            )

        def _send(self, code: int, body: dict, extra_headers: Optional[dict] = None) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            # every response names its request id — GETs and error paths
            # included — so any response joins the access log + recorder
            if last_request_id() and "X-Simon-Request-Id" not in (extra_headers or {}):
                self.send_header("X-Simon-Request-Id", last_request_id())
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)
            self._access_log(code)

        def do_GET(self):
            self._begin_request()
            if self.path == "/healthz":
                self._send(200, {"status": "ok"})
            elif self.path == "/metrics":
                data = server.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                if last_request_id():
                    self.send_header("X-Simon-Request-Id", last_request_id())
                self.end_headers()
                self.wfile.write(data)
                self._access_log(200)
            elif self.path.split("?", 1)[0] == "/api/cluster/report":
                # capacity observatory (ISSUE 9, docs/observability.md):
                # the live capacity report — SAME rows as the text renderer
                from urllib.parse import parse_qs

                q = parse_qs(self.path.partition("?")[2])
                extended = [
                    e for e in q.get("extended", [""])[-1].split(",") if e
                ]
                probe = q.get("headroom", ["1"])[-1] not in ("0", "false")
                mem = q.get("mem", ["0"])[-1] not in ("", "0", "false")
                try:
                    self._send(
                        200,
                        server.cluster_report(
                            extended=extended, probe_headroom=probe,
                            include_memory=mem,
                        ),
                    )
                except SnapshotUnavailable as e:
                    self._send(503, {"error": str(e), "retryable": True})
                except Exception as e:
                    log.warning(
                        "cluster report failed: %s: %s", type(e).__name__, e
                    )
                    self._send(500, {"error": str(e), "type": type(e).__name__})
            elif self.path.split("?", 1)[0] == "/api/debug/capacity":
                # the capacity timeline ring (obs/timeline.py): trend
                # samples per twin generation for charting
                if server.capacity is None:
                    self._send(404, {"error": "capacity observatory disabled"})
                else:
                    server.capacity.sample()  # fold in the latest generation
                    self._send(
                        200,
                        {
                            "capacity": server.capacity.timeline.capacity,
                            "samples": [
                                s.to_dict()
                                for s in server.capacity.timeline.snapshot()
                            ],
                        },
                    )
            elif self.path.split("?", 1)[0] == "/api/debug/memory":
                # memory observatory (ISSUE 12, docs/observability.md
                # "Memory & profiles"): per-entry arena byte attribution,
                # ring occupancy, RSS/device watermarks. ?fields=0 drops
                # the per-field breakdown for cheap polling.
                from urllib.parse import parse_qs as _parse_qs

                q = _parse_qs(self.path.partition("?")[2])
                fields = q.get("fields", ["1"])[-1] not in ("0", "false")
                try:
                    self._send(200, server.memory.debug_payload(include_fields=fields))
                except Exception as e:
                    log.warning("memory debug failed: %s: %s", type(e).__name__, e)
                    self._send(500, {"error": str(e), "type": type(e).__name__})
            elif self.path.split("?", 1)[0] == "/api/debug/profile":
                # compile telemetry + cumulative phase profiles (ISSUE 12)
                from ..obs import profile as profile_mod

                try:
                    payload = profile_mod.debug_payload()
                    # C++ path attribution (abi v5): envelope engagement,
                    # bail reasons, and carry-class coverage for the
                    # `simon profile` native table
                    payload["native"] = METRICS.native_snapshot()
                    adm = server.admission
                    if adm is not None:
                        # pipelined-admission stage aggregates (ISSUE 16):
                        # the `simon profile` pipeline table reads this
                        payload["pipeline"] = adm.pipeline_snapshot()
                    self._send(200, payload)
                except Exception as e:
                    log.warning("profile debug failed: %s: %s", type(e).__name__, e)
                    self._send(500, {"error": str(e), "type": type(e).__name__})
            elif self.path == "/api/debug/requests":
                # flight recorder (docs/observability.md): newest-first
                # summaries of the last N request traces
                self._send(200, {"requests": FLIGHT_RECORDER.summaries()})
            elif self.path.startswith("/api/debug/requests/"):
                # drop any query string before extracting the id segment
                rid = tracing.sanitize_request_id(
                    self.path.split("?", 1)[0].rsplit("/", 1)[1]
                )
                tr = FLIGHT_RECORDER.get(rid)
                if tr is None:
                    self._send(404, {"error": f"no recorded trace for request id {rid!r}"})
                else:
                    body = tr.tree()
                    # stitched fleet trace (ISSUE 20): graft the owner-side
                    # publication subtree under the worker-side tree
                    gen = getattr(tr, "serving_generation", None)
                    if gen is not None:
                        from ..obs.fleetobs import publication_tree

                        fleet_node = publication_tree(gen)
                        if fleet_node is not None:
                            body["fleet"] = fleet_node
                    self._send(200, body)
            elif self.path.split("?", 1)[0] == "/api/debug/timeseries":
                # the on-disk time-series ring (ISSUE 20): serve() starts
                # it; bare SimonServer constructions answer 503
                if server.timeseries is None:
                    self._send(503, {"error": "time-series ring not running"})
                else:
                    from urllib.parse import parse_qs

                    from ..obs.timeseries import parse_duration_s

                    q = parse_qs(self.path.partition("?")[2])
                    try:
                        range_s = parse_duration_s(q.get("range", [""])[-1])
                    except ValueError as e:
                        self._send(400, {"error": str(e)})
                    else:
                        self._send(200, {
                            "stats": server.timeseries.stats(),
                            "samples": server.timeseries.query(
                                family=q.get("family", [""])[-1],
                                range_s=range_s,
                            ),
                        })
            elif self.path.split("?", 1)[0] == "/api/fleet/slo":
                # SLO burn rates (ISSUE 20, obs/slo.py) — same surface the
                # fleet admin endpoint serves
                if server.slo is None:
                    self._send(503, {"error": "SLO engine not running"})
                else:
                    self._send(200, server.slo.evaluate())
            elif self.path.startswith("/api/debug/placements/"):
                # decision audit (ISSUE 7): the per-pod placement
                # explanations of an explain=1 request, keyed by request id
                rid = tracing.sanitize_request_id(
                    self.path.split("?", 1)[0].rsplit("/", 1)[1]
                )
                tr = FLIGHT_RECORDER.get(rid)
                placements = getattr(tr, "placements", None) if tr is not None else None
                if placements is None:
                    self._send(
                        404,
                        {
                            "error": f"no recorded placements for request id {rid!r}",
                            "hint": "POST /api/deploy-apps?explain=1 records them",
                        },
                    )
                else:
                    self._send(200, placements)
            elif self.path.startswith("/debug/profiler"):
                # pprof analogue (the reference registers pprof on gin,
                # server.go:152): start the JAX profiler server and report
                # where TensorBoard can connect
                from ..utils.trace import start_profiler

                try:
                    port = start_profiler()
                    self._send(200, {"profiler": "running", "port": port, "ui": "tensorboard --logdir ... (trace viewer)"})
                except Exception as e:
                    log.warning("profiler start failed: %s: %s", type(e).__name__, e)
                    self._send(500, {"error": str(e)})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            self._begin_request()
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except ValueError:
                self._send(400, {"error": "invalid JSON body"})
                return
            deadline = request_deadline(self.headers)
            # request-id propagation (ISSUE 5): _begin_request honored the
            # client's X-Simon-Request-Id (sanitized) or generated one; the
            # id is echoed by _send and keys the flight-recorder trace
            request_id = last_request_id()
            path, _, query = self.path.partition("?")
            # explain=1 (decision audit, ISSUE 7): attach per-pod placement
            # explanations to the response and the flight recorder
            from urllib.parse import parse_qs

            explain = parse_qs(query).get("explain", ["0"])[-1] not in ("", "0", "false")
            if path == "/api/deploy-apps":
                code, body = server.deploy_apps(
                    payload, deadline=deadline, request_id=request_id, explain=explain
                )
            elif path == "/api/scale-apps":
                code, body = server.scale_apps(
                    payload, deadline=deadline, request_id=request_id, explain=explain
                )
            elif path == "/api/campaign":
                # campaign engine (ISSUE 13, docs/campaigns.md): a what-if
                # analysis like the cluster report — runs inline on the
                # handler thread, serialized across requests
                code, body = server.run_campaign(payload, deadline=deadline)
            else:
                code, body = 404, {"error": "not found"}
            # degraded-mode transparency: a result computed from a stale
            # snapshot (apiserver down through every retry) says so. Read
            # per-request (thread-local), not off the shared server flag —
            # a concurrent refresh must not mis-tag this response.
            extra = dict(response_extra_headers())  # e.g. Retry-After on shed
            if request_served_stale():
                extra["X-Simon-Snapshot"] = "stale"
            self._send(code, body, extra_headers=extra or None)

    return Handler


class SimonHTTPServer(ThreadingHTTPServer):
    """The serving listener: stdlib ``ThreadingHTTPServer`` with a backlog
    sized for hundreds of concurrent keep-alive clients — the default
    backlog of 5 resets most of a 500-client connect storm before a
    single request is read (ISSUE 15; the fleet's SO_REUSEPORT listener
    subclasses this sizing in server/fleet.py)."""

    request_queue_size = 512


def build_twin(kubeconfig: str, master: str, watch: str, journal: str):
    """(watch supervisor or None, journal or None) for a serving process —
    shared by the single-process :func:`serve` and the fleet owner
    (``server/fleet.serve_fleet``). Raises ``ValueError`` on operator
    errors (both callers print the message and exit 1). Paths must
    already be validated."""
    if watch == "on" and not kubeconfig:
        # "require a synced twin" with nothing to sync FROM is an operator
        # error that must not silently degrade to an empty polling server
        raise ValueError("--watch on requires --kubeconfig")
    supervisor = None
    if kubeconfig and watch != "off":
        from .watch import source_from_kubeconfig, watch_policy, WatchSupervisor

        policy = watch_policy()
        supervisor = WatchSupervisor(
            source_from_kubeconfig(
                kubeconfig, master or None, read_timeout_s=policy["stale_s"]
            ),
            policy=policy,
        )
    jrnl = None
    if journal:
        if supervisor is None:
            # a journal with no event stream to record is an operator
            # mistake worth failing on, not silently ignoring
            raise ValueError(
                "--journal requires the live twin (--kubeconfig and "
                "--watch auto|on)"
            )
        from .journal import Journal, JournalError

        try:
            jrnl = Journal(journal)
        except JournalError as e:
            raise ValueError(str(e)) from e
    return supervisor, jrnl


def fleet_workers(flag: int = 0) -> int:
    """Resolve the fleet size: the ``--workers`` flag wins, else
    ``OPENSIM_WORKERS_FLEET``; 0/1 means single-process serving."""
    if flag:
        return flag
    raw = envknobs.raw("OPENSIM_WORKERS_FLEET")
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        log.warning("ignoring unparseable OPENSIM_WORKERS_FLEET=%r", raw)
        return 0


def serve(
    kubeconfig: str = "", master: str = "", port: int = 8080,
    watch: str = "auto", journal: str = "", workers: int = 0,
    standby: bool = False, ha_handover: bool = False,
) -> int:
    """Start the REST server. ``watch`` selects the snapshot strategy when a
    kubeconfig is configured (docs/live-twin.md):

    - ``auto`` (default): start the live twin in the background and serve
      from it once synced; until then — and if its bootstrap keeps
      failing — requests fall back to the polling snapshot path;
    - ``on``: require the twin to sync before accepting traffic (fail the
      process if it cannot);
    - ``off``: today's polling behavior only.

    ``journal`` names a directory for the crash-safe watch-event journal
    (docs/live-twin.md "Durability & replay"): the twin restores from its
    newest checkpoint + suffix replay at startup and every accepted event
    is recorded after. Requires the live twin (ignored, loudly, with
    ``--watch off`` or no kubeconfig).

    SIGTERM/SIGINT shut down gracefully: the listener stops, the admission
    queue drains (in-flight batch completes, queued requests shed typed
    503 ``shutting_down``), the reflectors stop, the journal is flushed +
    fsynced, and the process exits 0.

    ``workers`` ≥ 2 (or ``OPENSIM_WORKERS_FLEET``) serves through the
    multi-process fleet instead (docs/serving.md "Scaling past one
    process"): a twin-owner process publishing arena deltas over shared
    memory plus N worker processes sharing the port via SO_REUSEPORT.
    """
    import signal

    from ..utils import validate

    # registered validators (OSL1603): the CLI hands these straight from
    # argv; reject control characters before they reach open()/makedirs
    kubeconfig = validate.user_path(kubeconfig, label="--kubeconfig", allow_empty=True)
    journal = validate.user_path(journal, label="--journal", allow_empty=True)

    if envknobs.raw("OPENSIM_FLEET_ATTACH"):
        # this process IS a fleet worker (the supervisor set the knob):
        # attach the owner's publication instead of building a twin
        from .fleet import run_worker

        return run_worker(port)
    if standby:
        # HA hot standby (docs/serving.md "Surviving owner loss & rolling
        # upgrades"): tail the owner's journal, take over on lease expiry
        # or handover — with --handover, request the handover itself
        from .fleet import serve_standby

        return serve_standby(
            kubeconfig, master, port, watch, journal,
            fleet_workers(workers) or 2, handover=ha_handover,
        )
    n_fleet = fleet_workers(workers)
    if n_fleet >= 2:
        from .fleet import serve_fleet

        return serve_fleet(kubeconfig, master, port, watch, journal, n_fleet)

    try:
        supervisor, jrnl = build_twin(kubeconfig, master, watch, journal)
    except ValueError as e:
        print(f"simon server: {e}", flush=True)
        return 1
    server = SimonServer(
        kubeconfig=kubeconfig, master=master, watch=supervisor, journal=jrnl
    )
    # low-rate RSS/device watermark sampler (OPENSIM_MEM_TICKER_S): only
    # the long-lived server process runs it — library/test constructions
    # of SimonServer sample on demand instead
    server.memory.start_ticker()
    # time-series ring + SLO engine (ISSUE 20): long-lived servers only,
    # same rationale as the ticker
    server.start_timeseries()
    if supervisor is not None:
        supervisor.prep_cache = server.prep_cache
        if watch == "on":
            if not supervisor.start(wait_s=60.0):
                print("simon server: --watch on but the twin could not sync", flush=True)
                supervisor.stop()
                return 1
        else:
            supervisor.start()
    httpd = SimonHTTPServer(("0.0.0.0", port), make_handler(server))
    # graceful shutdown (ISSUE 11 satellite): the handler only nudges the
    # serve loop from a helper thread (httpd.shutdown() deadlocks when
    # called from the thread running serve_forever) — the drain sequence
    # itself runs in the one finally block below, signal or not
    def _graceful(signum, frame):
        log.info(
            "received %s; draining and shutting down",
            signal.Signals(signum).name,
        )
        threading.Thread(
            target=httpd.shutdown, name="simon-shutdown", daemon=True
        ).start()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _graceful)
        except ValueError:
            # not the main thread (embedded/test use): skip the handlers;
            # the finally-block drain still runs on loop exit
            break
    mode = "admission queue" if server.admission is not None else "single-flight"
    print(
        f"simon server listening on :{port} [{mode}]"
        + (" (live twin)" if supervisor else "")
        + (f" [journal {journal}]" if jrnl is not None else ""),
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # drain order matters: stop admitting first (queued work sheds
        # typed 503s, the in-flight batch completes), then the reflectors
        # (no new events), then flush+fsync+close the journal (server
        # .close()) so the recorded history is complete to the last event
        if server.admission is not None:
            server.admission.stop()
        if supervisor is not None:
            supervisor.stop()
        server.close()
        print("simon server: shutdown complete", flush=True)
    return 0
