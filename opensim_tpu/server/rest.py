"""REST server — parity with ``pkg/server/server.go``: ``GET /healthz``,
``POST /api/deploy-apps``, ``POST /api/scale-apps`` with the exact request/
response DTOs (``server.go:48-93``) so existing clients can switch backends.

Implementation notes vs the reference:
- stdlib ``http.server`` replaces gin (no third-party web framework in the
  image); single-flight busy rejection mirrors the TryLock 503 behavior
  (``server.go:167,:234``).
- The live-cluster informer snapshot is taken per request via the
  Kubernetes Python client when a kubeconfig is configured; without one, the
  server can still serve simulations whose requests carry their own nodes
  (useful for testing and air-gapped use — a divergence the reference
  doesn't offer).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..engine.simulator import AppResource, SimulateResult, simulate
from ..models.objects import LABEL_APP_NAME, Node, ResourceTypes, object_from_dict
from .snapshot import cluster_from_kubeconfig

log = logging.getLogger("opensim_tpu.server")

_deploy_lock = threading.Lock()
_scale_lock = threading.Lock()


class _Metrics:
    """Process-local counters exposed at /metrics in Prometheus text format
    (the reference's vendored scheduler metrics exist but are never exposed;
    SURVEY.md §5 — this closes that gap)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.requests = {"deploy-apps": 0, "scale-apps": 0}
        self.simulations = 0
        self.pods_scheduled = 0
        self.pods_unscheduled = 0
        self.simulate_seconds_total = 0.0

    def record(self, endpoint: str, result: SimulateResult, seconds: float) -> None:
        with self.lock:
            self.requests[endpoint] = self.requests.get(endpoint, 0) + 1
            self.simulations += 1
            self.pods_scheduled += sum(len(ns.pods) for ns in result.node_status)
            self.pods_unscheduled += len(result.unscheduled_pods)
            self.simulate_seconds_total += seconds

    def render(self, prep_cache=None) -> str:
        from ..utils.trace import PREP_STATS

        with self.lock:
            lines = [
                "# TYPE simon_requests_total counter",
                *(
                    f'simon_requests_total{{endpoint="{ep}"}} {n}'
                    for ep, n in sorted(self.requests.items())
                ),
                "# TYPE simon_simulations_total counter",
                f"simon_simulations_total {self.simulations}",
                "# TYPE simon_pods_scheduled_total counter",
                f"simon_pods_scheduled_total {self.pods_scheduled}",
                "# TYPE simon_pods_unscheduled_total counter",
                f"simon_pods_unscheduled_total {self.pods_unscheduled}",
                "# TYPE simon_simulate_seconds_total counter",
                f"simon_simulate_seconds_total {self.simulate_seconds_total:.6f}",
            ]
        # host-side prepare attribution (incremental prepare): total seconds
        # spent producing Prepared inputs, and the encode-cache counters
        lines += [
            "# TYPE simon_prepare_seconds_total counter",
            f"simon_prepare_seconds_total {PREP_STATS.total_seconds():.6f}",
        ]
        if prep_cache is not None:
            st = prep_cache.stats
            lines += [
                "# TYPE simon_prep_cache_hits_total counter",
                f"simon_prep_cache_hits_total {st.hits}",
                "# TYPE simon_prep_cache_misses_total counter",
                f"simon_prep_cache_misses_total {st.misses}",
                "# TYPE simon_prep_cache_invalidations_total counter",
                f"simon_prep_cache_invalidations_total {st.invalidations}",
            ]
        return "\n".join(lines) + "\n"


METRICS = _Metrics()


def _decode_app(payload: dict) -> ResourceTypes:
    rt = ResourceTypes()
    kind_map = {
        "pods": "Pod",
        "deployments": "Deployment",
        "daemonsets": "DaemonSet",
        "DaemonSets": "DaemonSet",
        "statefulsets": "StatefulSet",
        "StatefulSets": "StatefulSet",
        "Jobs": "Job",
        "jobs": "Job",
        "ConfigMaps": "ConfigMap",
        "configmaps": "ConfigMap",
        "Deployments": "Deployment",
        "Pods": "Pod",
    }
    for key, kind in kind_map.items():
        for obj in payload.get(key) or []:
            obj = dict(obj)
            obj.setdefault("kind", kind)
            decoded = object_from_dict(obj)
            if decoded is not None:
                rt.add(decoded)
    return rt


def _decode_new_nodes(payload: dict) -> List[Node]:
    """Requested nodes become fake nodes exactly like the apply path
    (server.go:187-194 → NewFakeNode): fresh simon-<rand> name, hostname
    label rewritten, simon/new-node marker."""
    from ..models.expand import new_fake_nodes

    nodes = []
    for obj in payload.get("newnodes") or payload.get("NewNodes") or []:
        obj = dict(obj)
        obj.setdefault("kind", "Node")
        nodes.extend(new_fake_nodes(Node.from_dict(obj), 1))
    return nodes


def _response(result: SimulateResult) -> dict:
    """getSimulateResponse (server.go:446-470): names only; node entries only
    for nodes holding app pods."""
    out = {"unscheduledPods": [], "nodeStatus": []}
    for up in result.unscheduled_pods:
        out["unscheduledPods"].append(
            {"pod": f"{up.pod.metadata.namespace}/{up.pod.metadata.name}", "reason": up.reason}
        )
    for ns in result.node_status:
        pods = [
            f"{p.metadata.namespace}/{p.metadata.name}"
            for p in ns.pods
            if LABEL_APP_NAME in p.metadata.labels
        ]
        if pods:
            out["nodeStatus"].append({"node": ns.node.metadata.name, "pods": pods})
    return out


class SimonServer:
    def __init__(
        self,
        kubeconfig: str = "",
        master: str = "",
        base_cluster: Optional[ResourceTypes] = None,
        snapshot_ttl_s: float = 30.0,
        prep_cache=None,
    ):
        self.kubeconfig = kubeconfig
        self.master = master
        self.base_cluster = base_cluster
        # live-cluster snapshots are cached between requests (the reference
        # serves every request from its always-warm informer cache,
        # pkg/server/server.go:97-137, instead of re-listing the cluster);
        # snapshot_ttl_s bounds staleness, ≤0 disables caching
        self.snapshot_ttl_s = snapshot_ttl_s
        self._snapshot: Optional[ResourceTypes] = None
        self._snapshot_at = 0.0
        self._snapshot_fp: Optional[str] = None
        # encode cache (incremental prepare): the snapshot's expanded+encoded
        # cluster is cached across requests keyed by content fingerprint, so
        # a request pays O(its own app) host work, not O(cluster). Opt out
        # with OPENSIM_PREP_CACHE=0 (restores per-request full prepare).
        if prep_cache is None and os.environ.get("OPENSIM_PREP_CACHE", "1") != "0":
            from ..engine.prepcache import PrepareCache

            prep_cache = PrepareCache()
        self.prep_cache = prep_cache if prep_cache is not False else None

    def current_cluster(self) -> ResourceTypes:
        if self.base_cluster is not None:
            return self.base_cluster
        if self.kubeconfig:
            import copy as _copy

            self._refresh_snapshot()
            # hand each request its own copy: simulate() mutates pods/nodes
            # in place (bind writes nodeName/phase/annotations), and the
            # cached snapshot must stay pristine across requests
            return _copy.deepcopy(self._snapshot)
        return ResourceTypes()

    def _refresh_snapshot(self) -> None:
        import time as _time

        now = _time.monotonic()
        if self._snapshot is None or (
            self.snapshot_ttl_s <= 0 or now - self._snapshot_at > self.snapshot_ttl_s
        ):
            self._snapshot = cluster_from_kubeconfig(self.kubeconfig, self.master)
            self._snapshot_at = now
            self._snapshot_fp = None  # re-fingerprint lazily

    def _snapshot_for_cache(self) -> tuple:
        """(cluster, content fingerprint) for the encode-cache path — no
        defensive deepcopy: the cached Prepared owns sanitized pod copies
        and its bind state is restored after every use, so the snapshot
        objects are never mutated. A fingerprint change (snapshot refresh
        picked up cluster changes) invalidates the stale entries."""
        from ..engine.prepcache import fingerprint_cluster

        if self.base_cluster is not None:
            if self._snapshot_fp is None:
                self._snapshot_fp = fingerprint_cluster(self.base_cluster)
            return self.base_cluster, self._snapshot_fp
        if self.kubeconfig:
            old_fp = self._snapshot_fp
            self._refresh_snapshot()
            if self._snapshot_fp is None:
                self._snapshot_fp = fingerprint_cluster(self._snapshot)
                if old_fp is not None and old_fp != self._snapshot_fp:
                    self.prep_cache.invalidate(old_fp)
            return self._snapshot, self._snapshot_fp
        return ResourceTypes(), "empty"

    # -- handlers -----------------------------------------------------------

    def _simulate_request(self, kind: str, payload: dict) -> SimulateResult:
        """Shared deploy/scale simulation through the encode cache:

        1. identical repeated request → full-key hit: restore + simulate,
           zero re-encoding;
        2. known snapshot → base-entry hit: delta re-encode (append the
           request's app pods; extend nodes from the request's templates;
           flip valid-mask bits for scaled-away pods);
        3. cold → one full prepare of the snapshot, cached for 1+2.
        """
        from ..engine import prepcache
        from ..utils.trace import PREP_STATS
        import time as _time

        new_nodes = _decode_new_nodes(payload)
        app = _decode_app(payload)
        apps = [AppResource(kind, app)]
        scaled: set = set()
        if kind == "scale":
            scaled = {
                (w.kind, w.metadata.namespace, w.metadata.name)
                for w in app.deployments + app.daemon_sets + app.stateful_sets
            }

        if self.prep_cache is None:
            # legacy path: per-request snapshot copy + full prepare
            cluster = _with_new_nodes(self.current_cluster(), new_nodes)
            if scaled:
                cluster.pods = [p for p in cluster.pods if not _owned_by(p, scaled)]
            return simulate(cluster, apps)

        cluster0, fp = self._snapshot_for_cache()
        cluster = _with_new_nodes(cluster0, new_nodes)

        def _filtered() -> ResourceTypes:
            # only the cold full-prepare fallbacks need the scaled pods
            # actually removed from the input; the cached paths express the
            # removal as a drop mask over the prepared stream instead, so
            # the O(all pods) owner scan is skipped on the hot path
            if not scaled:
                return cluster
            out = _with_new_nodes(cluster0, new_nodes)
            out.pods = [p for p in cluster0.pods if not _owned_by(p, scaled)]
            return out

        payload_fp = hashlib.blake2b(
            json.dumps(payload, sort_keys=True, default=str).encode(), digest_size=16
        ).hexdigest()
        full_key = f"{fp}|{kind}|{payload_fp}"
        # full-key reuse only without newnodes: fake-node names are freshly
        # randomized per request, and a cached derived prep would replay the
        # first request's names into later responses
        entry = self.prep_cache.get(full_key) if not new_nodes else None
        if entry is not None and entry.prep is not None:
            self.prep_cache.check_fresh(entry)
            t0 = _time.monotonic()
            with entry.lock:
                entry.restore()
                PREP_STATS.record("hit", _time.monotonic() - t0)
                try:
                    return simulate(
                        cluster, apps, prep=entry.prep,
                        drop_pods=getattr(entry, "drop_mask", None),
                    )
                finally:
                    entry.restore()

        base_key = f"{fp}|base"
        base = self.prep_cache.get(base_key)
        if base is None:
            from ..engine.simulator import prepare

            watch = prepcache.watch_snapshot(cluster0, [])  # before the build
            base = self.prep_cache.put(
                base_key,
                prepcache.CacheEntry(base_key, prepare(cluster0, []), watch=watch),
            )
        if base.prep is None:
            # snapshot with no schedulable pods: nothing worth caching
            return simulate(_filtered(), apps)
        self.prep_cache.check_fresh(base)
        with base.lock:
            base.restore()
            base_prep = base.prep
            if new_nodes:
                base_prep = prepcache.extend_with_nodes(
                    base_prep, new_nodes, cluster0, [], base_entry=base
                )
            derived = (
                prepcache.derive_with_apps(
                    base_prep, cluster, apps,
                    base_entry=base if not new_nodes else None,
                )
                if base_prep is not None
                else None
            )
            if derived is None:
                return simulate(_filtered(), apps)
            drop = (
                prepcache.drop_mask_for_scaled(derived, _owned_by, scaled)
                if scaled
                else None
            )
            entry = prepcache.CacheEntry(full_key, derived, base=base)
            entry.drop_mask = drop
            if not new_nodes:
                self.prep_cache.put(full_key, entry)
            try:
                return simulate(cluster, apps, prep=derived, drop_pods=drop)
            finally:
                entry.restore()

    def deploy_apps(self, payload: dict) -> tuple:
        if not _deploy_lock.acquire(blocking=False):
            return 503, {"error": "the server is busy now, please try again later"}
        try:
            import time

            t0 = time.monotonic()
            result = self._simulate_request("deploy", payload)
            METRICS.record("deploy-apps", result, time.monotonic() - t0)
            return 200, _response(result)
        except Exception as e:  # surface as 500 like gin's error handler
            log.warning("deploy-apps failed: %s: %s", type(e).__name__, e)
            return 500, {"error": str(e)}
        finally:
            _deploy_lock.release()

    def scale_apps(self, payload: dict) -> tuple:
        """scale-apps (server.go:233-312): remove the workload's existing
        pods from the cluster snapshot, then re-simulate at the new scale —
        on the cached path the removal is a valid-mask flip over the
        snapshot's cached encoding, not a re-encode."""
        if not _scale_lock.acquire(blocking=False):
            return 503, {"error": "the server is busy now, please try again later"}
        try:
            import time

            t0 = time.monotonic()
            result = self._simulate_request("scale", payload)
            METRICS.record("scale-apps", result, time.monotonic() - t0)
            return 200, _response(result)
        except Exception as e:
            log.warning("scale-apps failed: %s: %s", type(e).__name__, e)
            return 500, {"error": str(e)}
        finally:
            _scale_lock.release()


def _owned_by(pod, scaled: set) -> bool:
    for ref in pod.metadata.owner_references:
        key = (ref.kind, pod.metadata.namespace, ref.name)
        if key in scaled:
            return True
        # deployment pods are owned via a generated ReplicaSet name prefix
        if ref.kind == "ReplicaSet" and any(
            k == "Deployment" and ns == pod.metadata.namespace and ref.name.startswith(name + "-")
            for k, ns, name in scaled
        ):
            return True
    return False


def _with_new_nodes(cluster: ResourceTypes, nodes: List[Node]) -> ResourceTypes:
    import copy

    out = copy.copy(cluster)
    out.nodes = list(cluster.nodes) + nodes
    return out


def make_handler(server: SimonServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send(self, code: int, body: dict) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok"})
            elif self.path == "/metrics":
                data = METRICS.render(prep_cache=server.prep_cache).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path.startswith("/debug/profiler"):
                # pprof analogue (the reference registers pprof on gin,
                # server.go:152): start the JAX profiler server and report
                # where TensorBoard can connect
                from ..utils.trace import start_profiler

                try:
                    port = start_profiler()
                    self._send(200, {"profiler": "running", "port": port, "ui": "tensorboard --logdir ... (trace viewer)"})
                except Exception as e:
                    log.warning("profiler start failed: %s: %s", type(e).__name__, e)
                    self._send(500, {"error": str(e)})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except ValueError:
                self._send(400, {"error": "invalid JSON body"})
                return
            if self.path == "/api/deploy-apps":
                code, body = server.deploy_apps(payload)
            elif self.path == "/api/scale-apps":
                code, body = server.scale_apps(payload)
            else:
                code, body = 404, {"error": "not found"}
            self._send(code, body)

    return Handler


def serve(kubeconfig: str = "", master: str = "", port: int = 8080) -> int:
    server = SimonServer(kubeconfig=kubeconfig, master=master)
    httpd = ThreadingHTTPServer(("0.0.0.0", port), make_handler(server))
    print(f"simon server listening on :{port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0
