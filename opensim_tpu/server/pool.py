"""Bounded worker pool for the concurrent serving core (ISSUE 8).

Unbatchable requests (newnodes, explain mode, mixed twin generations) used
to serialize behind the single-flight TryLock; now they run concurrently
through this pool, bounded by ``OPENSIM_WORKERS`` so a traffic spike
degrades into queueing + shedding (``server/admission.py``) instead of
unbounded thread creation.

Two modes (``OPENSIM_WORKERS_MODE``):

- ``thread`` (the ``auto`` default): a ``ThreadPoolExecutor``. The engine
  phase already parallelizes past the GIL here — the C++ scan engine runs
  through ctypes (which releases the GIL for the call) and XLA dispatches
  block off-thread — so threads buy real concurrency for the dominant
  cost. Host prep (expand + encode, pure Python/numpy) still contends.
- ``process``: a forked worker pool for the GIL-bound host half. Workers
  are forked at pool start, inheriting the server's warm NodeArenas and
  prep cache copy-on-write, and execute *closed* top-level functions
  (payload → serialized JSON-safe response) so nothing unpicklable crosses
  the pipe. Platforms without ``fork`` (or where the probe task fails —
  e.g. an XLA runtime that does not survive forking) fall back to threads
  with a warning, never a broken server.

The pool never owns correctness: per-entry prep-cache locks still
serialize touches of shared pod objects, exactly as on the solo path.
"""

from __future__ import annotations

import concurrent.futures
import logging
import multiprocessing
import os
from typing import Callable, Optional

from ..utils import envknobs

log = logging.getLogger("opensim_tpu.server")

__all__ = ["WorkerPool", "worker_count", "worker_mode"]


def worker_count() -> int:
    """``OPENSIM_WORKERS``: bounded concurrency for unbatchable requests.
    Default: half the visible cores, clamped to [2, 8] — enough to overlap
    engine runs without oversubscribing the box the engines compute on. A
    typo degrades to the default with a warning (the env-knob contract
    every server knob follows), never a startup crash."""
    raw = envknobs.raw("OPENSIM_WORKERS")
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            log.warning("ignoring unparseable OPENSIM_WORKERS=%r", raw)
    return max(2, min(8, (os.cpu_count() or 2) // 2))


def worker_mode() -> str:
    raw = envknobs.raw("OPENSIM_WORKERS_MODE", "auto").strip().lower() or "auto"
    if raw not in ("auto", "thread", "process"):
        log.warning("ignoring unknown OPENSIM_WORKERS_MODE=%r (using auto)", raw)
        return "auto"
    return raw


def _probe() -> int:
    """Trivial top-level task proving a forked worker can execute and
    answer — must be module-level (picklable by reference)."""
    return 42


class WorkerPool:
    """submit(fn, *args) -> Future, over threads or forked processes.

    In process mode only *picklable* tasks cross into the forked workers;
    anything that cannot pickle (bound methods, admission Tickets carrying
    ``threading.Event``s — whose resolution could not propagate back from
    a child process anyway) transparently runs on the thread executor
    instead, with a one-time warning. A submit() can therefore never hang
    a client on an unobservable pickling error."""

    def __init__(self, workers: Optional[int] = None, mode: Optional[str] = None):
        self.workers = workers if workers is not None else worker_count()
        want = mode if mode is not None else worker_mode()
        self.mode = "thread"
        self._proc_pool: Optional[concurrent.futures.Executor] = None
        # deliberately unguarded (no `# guarded-by:`): a boolean one-shot
        # flag whose worst-case race is a duplicate log line — the GIL
        # makes the flip atomic, and the executors themselves are the
        # stdlib's thread-safe objects (everything else here is
        # init-published before the first submit())
        self._warned_unpicklable = False
        if want == "process":
            pool = self._try_process_pool()
            if pool is not None:
                self._proc_pool, self.mode = pool, "process"
            else:
                log.warning(
                    "OPENSIM_WORKERS_MODE=process unavailable on this "
                    "platform; falling back to threads"
                )
        # the thread executor always exists: it is the sole executor in
        # thread mode and the unpicklable-task fallback in process mode
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="simon-worker"
        )

    def _try_process_pool(self) -> Optional[concurrent.futures.Executor]:
        """Fork-based pool, proven live by a probe task: fork is the point
        (COW inheritance of the warm arenas), and a runtime whose forked
        children wedge (XLA holds locks across fork on some platforms)
        must surface NOW, at startup, not on the first real request."""
        if "fork" not in multiprocessing.get_all_start_methods():
            return None
        try:
            ctx = multiprocessing.get_context("fork")
            pool = concurrent.futures.ProcessPoolExecutor(
                max_workers=self.workers, mp_context=ctx
            )
            if pool.submit(_probe).result(timeout=10.0) != 42:
                pool.shutdown(wait=False)
                return None
            return pool
        except Exception as e:  # platform-specific fork/pipe failures
            log.warning(
                "process worker pool probe failed (%s: %s)", type(e).__name__, e
            )
            return None

    def submit(self, fn: Callable, *args, **kwargs) -> concurrent.futures.Future:
        if self._proc_pool is not None:
            import pickle

            try:
                pickle.dumps((fn, args, kwargs))
            except Exception:
                if not self._warned_unpicklable:
                    self._warned_unpicklable = True
                    log.warning(
                        "process worker pool: task %r is not picklable; "
                        "running such tasks on threads instead",
                        getattr(fn, "__qualname__", fn),
                    )
                return self._pool.submit(fn, *args, **kwargs)
            return self._proc_pool.submit(fn, *args, **kwargs)
        return self._pool.submit(fn, *args, **kwargs)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._proc_pool is not None:
            self._proc_pool.shutdown(wait=False, cancel_futures=True)
